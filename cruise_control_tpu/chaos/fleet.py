"""Multi-cluster chaos: M member stacks over ONE simulated clock.

The fleet plane's failure domains are whole member *endpoints* — the
admin/sampler surface the coordinating control plane reaches a member
cluster through — so the faults here scope per member, not per broker:
``kill_endpoint`` makes every call to one member time out,
``delay_endpoint`` adds per-call latency the caller's deadline
arbitrates, ``flap_endpoint`` alternates up/down on the shared step
counter. :class:`ChaosEndpoint` is the interposition point;
:class:`ChaosFleetHarness` wires M (sim, monitor, sampler) member stacks
into one :class:`~cruise_control_tpu.fleet.FleetRegistry` (journal,
notifier, and optionally a move-budget coordinator attached) and drives
everything step-by-step off one :class:`~.engine.ChaosEngine`.

Determinism contract: the registry runs ``fetch_workers=0`` (serial
fetches in registration order) and the member monitors carry NO retry
policy, so the only thing that advances the shared simulated clock is
the engine itself plus the explicit latency an endpoint-delay fault
burns — the same ``(schedule, seed)`` pair replays byte-identically
(:meth:`ChaosFleetHarness.digest`).
"""

from __future__ import annotations

import hashlib
import json

from ..core.events import EventJournal
from ..detector import SelfHealingNotifier
from ..executor.kafka_admin import AdminTimeoutError
from ..fleet import FleetRegistry, MemberHealth, MoveBudgetCoordinator
from ..monitor import (LoadMonitor, LoadMonitorTaskRunner,
                       MetricFetcherManager, MonitorConfig,
                       NotEnoughValidWindowsException)
from ..monitor.sampler import SyntheticWorkloadSampler
from .engine import ChaosEngine, ChaosSampler
from .harness import DEFAULT_GOALS, build_sim, default_optimizer


class ChaosEndpoint:
    """A member cluster's admin/sampler endpoint under chaos: every
    public call consults the shared engine's per-member fault state
    before delegating to the member sim.

    - endpoint down (killed, or in a flap's down phase): the call raises
      :class:`AdminTimeoutError` immediately — the whole endpoint is
      unreachable, not one RPC.
    - endpoint delayed: the call burns the delay in *simulated* time
      (bounded by ``call_deadline_ms``); a delay past the deadline is a
      timeout. The burn rides ``engine.sleep_ms`` so scheduled faults
      still land at their exact timestamps mid-call.
    """

    def __init__(self, inner, engine: ChaosEngine, member_id: str, *,
                 call_deadline_ms: int = 0) -> None:
        self.inner = inner
        self.engine = engine
        self.member_id = member_id
        self.call_deadline_ms = call_deadline_ms
        self.calls = 0
        self.failed_calls = 0

    def _gate(self, name: str) -> None:
        self.calls += 1
        eng = self.engine
        delay = eng.endpoint_delay_ms.get(self.member_id, 0)
        if delay:
            burn = (min(delay, self.call_deadline_ms)
                    if self.call_deadline_ms else delay)
            eng.sleep_ms(burn)
            if self.call_deadline_ms and delay > self.call_deadline_ms:
                self.failed_calls += 1
                raise AdminTimeoutError(
                    f"endpoint {self.member_id!r}: {name} exceeded "
                    f"{self.call_deadline_ms} ms deadline "
                    f"({delay} ms injected delay)")
        if eng.endpoint_down(self.member_id):
            self.failed_calls += 1
            raise AdminTimeoutError(
                f"endpoint {self.member_id!r} unreachable: {name}")

    def __getattr__(self, name):
        attr = getattr(self.inner, name)
        if name.startswith("_") or not callable(attr):
            return attr

        def call(*args, **kwargs):
            self._gate(name)
            return attr(*args, **kwargs)

        call.__name__ = name
        return call


class _FleetMember:
    """One member's stack: its own sim + endpoint + monitor + sampler
    feed, registered into the shared registry."""

    def __init__(self, member_id: str, sim, engine: ChaosEngine, *,
                 step_ms: int, call_deadline_ms: int,
                 sampler=None) -> None:
        self.id = member_id
        self.sim = sim
        self.endpoint = ChaosEndpoint(sim, engine, member_id,
                                      call_deadline_ms=call_deadline_ms)
        # No admin retry policy and no stale serving: a degraded member
        # must FAIL its fetch (the health machine's signal), and a
        # readmission probe must succeed only once the model genuinely
        # rebuilds from fresh post-recovery samples.
        self.monitor = LoadMonitor(self.endpoint, MonitorConfig(
            num_windows=4, window_ms=2 * step_ms,
            min_samples_per_window=1,
            num_broker_windows=4, broker_window_ms=2 * step_ms,
            serve_stale_on_incomplete=False))
        # ``sampler`` swaps the inner metric source per member (e.g. a
        # trace-replaying workload.TraceSampler for burst-clocked
        # soaks); a callable without get_samples is a factory receiving
        # the member's chaos endpoint (members' sims are built
        # internally, so the caller cannot pre-bind one). The
        # ChaosSampler wrap stays, so injected endpoint / metrics
        # faults still apply to replayed traffic.
        if sampler is not None and callable(sampler) \
                and not hasattr(sampler, "get_samples"):
            sampler = sampler(self.endpoint)
        self.sampler = ChaosSampler(
            sampler if sampler is not None
            else SyntheticWorkloadSampler(self.endpoint), engine)
        self.fetcher = MetricFetcherManager(self.sampler, max_retries=1)
        self.runner = LoadMonitorTaskRunner(
            self.monitor, self.fetcher, sampling_interval_ms=step_ms)
        self.sampling_failures = 0
        self.handle = None   # set by ChaosFleetHarness after register


class ChaosFleetHarness:
    """M member stacks + one FleetRegistry on one chaos clock.

    Defaults are chaos-test scale and shape-shared with the rest of the
    chaos suite (``build_sim`` members, ``default_optimizer`` chain):
    quarantine after 2 degraded ticks, breakers tripping on 2 failures
    inside a 8-step rolling window, reopening after 2 steps.
    """

    def __init__(self, member_ids=("east", "west", "south"), *,
                 seed: int = 0, step_ms: int = 1000,
                 goals: list[str] | None = None,
                 optimizer=None,
                 quarantine_after: int = 2,
                 breaker_failures: int = 2,
                 breaker_open_steps: int = 2,
                 breaker_window_steps: int = 8,
                 call_deadline_ms: int = 0,
                 budget_per_tick: int = 0,
                 budget_carry_max_ticks: int = 2,
                 samplers: dict | None = None) -> None:
        """``samplers`` maps member id -> inner MetricSampler override,
        either an instance or a factory ``(endpoint) -> sampler``
        (members absent from the map keep the synthetic live-state
        sampler) — the trace-replay hook burst-clocked fleet soaks use."""
        member_ids = list(member_ids)
        if not member_ids:
            raise ValueError("a fleet needs at least one member")
        sims = {mid: build_sim() for mid in member_ids}
        # The FIRST member's sim carries the engine clock; siblings are
        # advanced to the same now on every step.
        self.engine = ChaosEngine(sims[member_ids[0]], seed=seed,
                                  step_ms=step_ms)
        self.step_ms = step_ms
        self.journal = EventJournal(512, node="fleet",
                                    now_ms=self.engine.now_ms,
                                    categories=("fleet",))
        self.notifier = SelfHealingNotifier(
            alert_threshold_ms=step_ms,
            self_healing_threshold_ms=3 * step_ms)
        self.budget = (MoveBudgetCoordinator(
            budget_per_tick=budget_per_tick,
            carry_max_ticks=budget_carry_max_ticks,
            journal=self.journal) if budget_per_tick > 0 else None)
        goals = goals or list(DEFAULT_GOALS)
        self.registry = FleetRegistry(
            optimizer or default_optimizer(goals),
            now_ms=self.engine.now_ms,
            fetch_workers=0,                 # serial: replay-deterministic
            quarantine_after=quarantine_after,
            seed=seed,
            breaker_window_ms=breaker_window_steps * step_ms,
            breaker_failures=breaker_failures,
            breaker_open_ms=breaker_open_steps * step_ms,
            journal=self.journal, notifier=self.notifier,
            budget=self.budget)
        self.members: dict[str, _FleetMember] = {}
        for mid in member_ids:
            m = _FleetMember(mid, sims[mid], self.engine,
                             step_ms=step_ms,
                             call_deadline_ms=call_deadline_ms,
                             sampler=(samplers or {}).get(mid))
            m.handle = self.registry.register(
                mid, m.monitor, endpoint=f"chaos://{mid}")
            m.runner.start(self.engine.now_ms(), skip_loading=True)
            self.members[mid] = m
        #: health-transition log: one line per observed per-member change
        self.transitions: list[str] = []
        self._last_health = {mid: MemberHealth.HEALTHY
                             for mid in member_ids}
        #: simulated ms each registry tick consumed (latency invariant:
        #: a dead endpoint fails instantly, so sibling ticks burn 0)
        self.tick_sim_cost_ms: list[int] = []

    # -------------------------------------------------------------- loop
    def step(self, *, tick: bool = True) -> dict | None:
        """One fleet-plane iteration: advance the shared clock one step
        (applying due faults), advance every member sim to now, run the
        members' sampling rounds, then (``tick``) one registry tick."""
        self.engine.tick()
        now = self.engine.now_ms()
        for m in self.members.values():
            m.sim.advance_to(now)
            try:
                m.runner.maybe_run_sampling(now)
            except Exception:   # noqa: BLE001 — chaos-injected
                m.sampling_failures += 1
        if not tick:
            return None
        before = self.engine.now_ms()
        summary = self.registry.tick(before)
        self.tick_sim_cost_ms.append(self.engine.now_ms() - before)
        self._record_transitions()
        return summary

    def _record_transitions(self) -> None:
        now = self.engine.now_ms()
        for mid, m in self.members.items():
            health = m.handle.health
            if health != self._last_health[mid]:
                self.transitions.append(
                    f"[{now}ms] {mid}: "
                    f"{self._last_health[mid]} -> {health}")
                self._last_health[mid] = health

    def run(self, steps: int, *, tick: bool = True) -> None:
        for _ in range(steps):
            self.step(tick=tick)

    def warmup(self, max_steps: int = 12) -> None:
        """Sampling-only steps until EVERY member can build a model,
        then one forced registry tick (compiles the fleet dispatch and
        fills every member's cache) — the pre-fault baseline."""
        for _ in range(max_steps):
            self.step(tick=False)
            now = self.engine.now_ms()
            try:
                for m in self.members.values():
                    m.monitor.cluster_model(now)
            except NotEnoughValidWindowsException:
                continue
            self.registry.tick(now, force=True)
            self._record_transitions()
            return
        raise AssertionError(
            f"fleet never warmed in {max_steps} steps "
            f"(seed={self.engine.seed})")

    def steps_until(self, predicate, max_steps: int, *,
                    what: str = "condition") -> int:
        for i in range(max_steps):
            if predicate():
                return i
            self.step()
        raise AssertionError(
            f"{what} not reached within {max_steps} steps "
            f"(seed={self.engine.seed}); transitions:\n  "
            + "\n  ".join(self.transitions)
            + "\nchaos log:\n  " + "\n  ".join(self.engine.applied[-20:]))

    # --------------------------------------------------------- predicates
    def health(self, member_id: str) -> str:
        return self.members[member_id].handle.health

    def quarantined(self, member_id: str) -> bool:
        return self.health(member_id) == MemberHealth.QUARANTINED

    def healthy(self, member_id: str) -> bool:
        return self.health(member_id) == MemberHealth.HEALTHY

    # ------------------------------------------------------------- replay
    def digest(self) -> str:
        """Replay fingerprint: health transitions + applied-fault log +
        the journal's deterministic fields (perf stamps excluded — they
        ride the host perf counter, everything else rides the sim
        clock). Two runs of the same ``(schedule, seed)`` must match
        byte-identically."""
        events = [(e.seq, e.ts_ms, e.category, e.action, e.severity,
                   e.cause, e.epoch, e.detail)
                  for e in self.journal.events()]
        payload = {"transitions": self.transitions,
                   "applied": self.engine.applied,
                   "journal": events}
        return hashlib.sha256(
            json.dumps(payload, sort_keys=True,
                       default=repr).encode()).hexdigest()
