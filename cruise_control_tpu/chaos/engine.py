"""The chaos engine: seeded, step-keyed fault injection over the simulated
cluster.

Three cooperating pieces:

- :class:`ChaosEngine` owns the fault schedule (scripted
  :class:`FaultEvent` list) and the simulated clock. Time advances only
  through :meth:`ChaosEngine.tick` / :meth:`ChaosEngine.sleep_ms` (the
  executor's sleep is wired to the latter), and due events apply **in
  schedule order at their exact simulated timestamps** — so a broker
  crash scheduled for step 7 lands mid-execution if the executor happens
  to be sleeping across step 7, exactly the same way on every replay.
- :class:`ChaosAdminClient` wraps a
  :class:`~cruise_control_tpu.executor.admin.ClusterAdminClient` and
  consults the engine before every RPC: sustained error *rates* (a
  deterministic per-call draw keyed off ``(seed, method, call#)``) and
  finite *bursts* raise classified admin errors
  (:class:`~cruise_control_tpu.executor.kafka_admin.AdminTimeoutError`
  for retryable codes) — the generalization of the mock wire's
  ``fail_with`` hook to rates.
- :class:`ChaosSampler` wraps a
  :class:`~cruise_control_tpu.monitor.sampler.MetricSampler` and drops
  whole sampling rounds at the scheduled rate — the metric-dropout fault
  the monitor's stale-model degradation defends against.

Nothing here touches ``time.time``/``random`` module state: the same
``(schedule, seed)`` pair always produces the same run.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..core.retry import deterministic_uniform as _draw
from ..executor.kafka_admin import (AdminOperationError, AdminTimeoutError,
                                    consume_injection)
from ..monitor.sampler import Samples


class ProcessCrashed(RuntimeError):
    """A scheduled ``crash_process`` fault fired: the control plane
    "dies" at this exact simulated instant. Propagates out of whatever
    the stack was doing (the executor's sleeps included); the
    ``simulates_process_crash`` marker tells the executor to skip ALL
    teardown — no abort RPCs, no throttle cleanup, state abandoned —
    exactly what a real SIGKILL leaves behind. The harness driver
    catches it, marks the stack crashed, and restarts from the
    snapshot."""

    #: checked by Executor's finally block (duck-typed: the executor
    #: must not import the chaos package).
    simulates_process_crash = True


@dataclass
class FaultEvent:
    """One scheduled fault: ``action`` (an :data:`ChaosEngine.ACTIONS`
    name) applied when the engine's clock reaches ``step``."""

    step: int
    action: str
    kwargs: dict = field(default_factory=dict)

    def describe(self) -> str:
        args = ", ".join(f"{k}={v!r}" for k, v in self.kwargs.items())
        return f"step {self.step}: {self.action}({args})"


class ChaosAdminClient:
    """Admin-SPI wrapper injecting rate/burst errors before delegation.

    Only the mutating + polling RPCs the executor and facade issue are
    interception points; everything else (test hooks, ``offline_logdirs``,
    ``broker_metrics``) passes through untouched via ``__getattr__``.
    """

    #: kept in lockstep with the explicit delegation methods below by
    #: test_chaos_admin_client_intercepts_every_declared_rpc
    INTERCEPTED = (
        "describe_cluster", "describe_partitions",
        "alter_partition_reassignments", "list_partition_reassignments",
        "elect_preferred_leaders", "alter_replica_log_dirs",
        "describe_replica_log_dirs", "alter_broker_config",
        "describe_broker_config", "alter_topic_config",
        "describe_topic_config",
    )

    def __init__(self, inner, engine: "ChaosEngine") -> None:
        self.inner = inner
        self.engine = engine

    def _call(self, name, *args, **kwargs):
        self.engine.maybe_fail_admin(name)
        return getattr(self.inner, name)(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.inner, name)

    # Explicit delegations so the wrapper satisfies the ClusterAdminClient
    # protocol statically (and every RPC is one grep away).
    def describe_cluster(self):
        return self._call("describe_cluster")

    def describe_partitions(self):
        return self._call("describe_partitions")

    def alter_partition_reassignments(self, targets):
        return self._call("alter_partition_reassignments", targets)

    def list_partition_reassignments(self):
        return self._call("list_partition_reassignments")

    def elect_preferred_leaders(self, tps):
        return self._call("elect_preferred_leaders", tps)

    def alter_replica_log_dirs(self, moves):
        return self._call("alter_replica_log_dirs", moves)

    def describe_replica_log_dirs(self):
        return self._call("describe_replica_log_dirs")

    def alter_broker_config(self, broker_id, config):
        return self._call("alter_broker_config", broker_id, config)

    def describe_broker_config(self, broker_id):
        return self._call("describe_broker_config", broker_id)

    def alter_topic_config(self, topic, config):
        return self._call("alter_topic_config", topic, config)

    def describe_topic_config(self, topic):
        return self._call("describe_topic_config", topic)


class ChaosSampler:
    """MetricSampler wrapper dropping whole rounds at the engine's
    scheduled ``sample_drop_rate`` (deterministic per-round draw)."""

    parallel_safe = False

    def __init__(self, inner, engine: "ChaosEngine") -> None:
        self.inner = inner
        self.engine = engine
        self._rounds = 0

    def get_samples(self, assignment):
        self._rounds += 1
        rate = self.engine.sample_drop_rate
        if rate and _draw(self.engine.seed, "sampler", self._rounds) < rate:
            self.engine.note("sampler", "dropped round "
                             f"[{assignment.start_ms}, {assignment.end_ms})")
            return Samples([], [])
        return self.inner.get_samples(assignment)


class ChaosEngine:
    """Seeded fault scheduler + deterministic clock for one simulated
    cluster (`sim` is a
    :class:`~cruise_control_tpu.executor.simulated.SimulatedKafkaCluster`).

    The step counter is the schedule key: step ``k`` corresponds to
    simulated time ``k * step_ms``. :meth:`tick` advances one step;
    :meth:`sleep_ms` (handed to the executor as its sleep) advances
    arbitrary spans — both apply due events at their exact timestamps on
    the way, so faults land mid-execution deterministically.
    """

    #: action name -> handler(self, **kwargs); the schedule vocabulary
    ACTIONS = ("kill_broker", "restart_broker", "fail_logdir",
               "stall_broker", "unstall_broker", "admin_error_rate",
               "admin_burst", "drop_samples", "clock_jump",
               "crash_process", "cut_stream", "delay_stream",
               "kill_endpoint", "restart_endpoint", "delay_endpoint",
               "flap_endpoint")

    def __init__(self, sim, *, seed: int = 0, step_ms: int = 1000,
                 events: list[FaultEvent] | None = None) -> None:
        self.sim = sim
        self.seed = seed
        self.step_ms = step_ms
        self.admin = ChaosAdminClient(sim, self)
        #: pending schedule, kept sorted by (step, insertion order)
        self._pending: list[tuple[int, int, FaultEvent]] = []
        self._order = 0
        for e in events or ():
            self.schedule(e.step, e.action, **e.kwargs)
        #: replay/diagnosis log of everything the engine did
        self.applied: list[str] = []
        #: method -> (rate in [0,1], error code) sustained injections
        self.admin_error_rates: dict[str, tuple[float, str]] = {}
        #: method -> (error code, remaining count) burst injections
        self.admin_bursts: dict[str, tuple[str, int]] = {}
        #: probability a sampling round is dropped wholesale
        self.sample_drop_rate = 0.0
        #: replication-stream faults (read by ReplicationChannel when the
        #: engine is its fault_source): a cut makes every poll answer
        #: None (follower reads it as a severed connection); a delay
        #: withholds frames younger than the given age, modelling a slow
        #: link without reordering (frames still deliver in sequence once
        #: old enough).
        self.stream_cut = False
        self.stream_delay_ms = 0
        #: fleet-member endpoint faults (PR-19, keyed by member id; read
        #: by ChaosEndpoint): a killed endpoint times out every admin
        #: call; a delay burns sim time per call; a flap alternates the
        #: endpoint up/down every ``period`` steps.
        self.endpoints_down: set[str] = set()
        self.endpoint_delay_ms: dict[str, int] = {}
        self.endpoint_flap: dict[str, int] = {}
        self._admin_counters: dict[str, int] = {}
        self._saved_rates: dict[int, float] = {}
        #: clock offset applied on top of sim time (clock_jump faults)
        self._jumped_ms = 0

    # ------------------------------------------------------------- clock
    @property
    def step(self) -> int:
        return self.sim.now_ms // self.step_ms

    def now_ms(self) -> int:
        return self.sim.now_ms

    def schedule(self, step: int, action: str, **kwargs) -> None:
        if action not in self.ACTIONS:
            raise ValueError(f"unknown chaos action {action!r}; "
                             f"expected one of {self.ACTIONS}")
        self._pending.append((step, self._order,
                              FaultEvent(step, action, kwargs)))
        self._order += 1
        self._pending.sort(key=lambda t: (t[0], t[1]))

    def note(self, source: str, what: str) -> None:
        self.applied.append(f"[{self.sim.now_ms}ms] {source}: {what}")

    def sleep_ms(self, ms: int) -> None:
        """Advance simulated time, applying due events at their exact
        timestamps — the executor's sleep, so scheduled faults interleave
        with execution progress deterministically."""
        target = self.sim.now_ms + ms
        while self._pending and self._pending[0][0] * self.step_ms <= target:
            step, _, event = self._pending.pop(0)
            at = max(step * self.step_ms, self.sim.now_ms)
            self.sim.advance_to(at)
            self._apply(event)
        # A clock_jump applied above may have leapt past the original
        # target — never rewind the simulated clock to pre-jump time.
        self.sim.advance_to(max(target, self.sim.now_ms))

    def tick(self, steps: int = 1) -> None:
        for _ in range(steps):
            self.sleep_ms(self.step_ms)

    # ------------------------------------------------------------ faults
    def _apply(self, event: FaultEvent) -> None:
        self.note("schedule", event.describe())
        getattr(self, f"_do_{event.action}")(**event.kwargs)

    def _do_kill_broker(self, broker: int) -> None:
        self.sim.kill_broker(broker)

    def _do_restart_broker(self, broker: int) -> None:
        self.sim.restart_broker(broker)

    def _do_fail_logdir(self, broker: int, logdir: str | None = None) -> None:
        self.sim.fail_logdir(broker,
                             logdir or self.sim._healthy_logdir(broker))

    def _do_stall_broker(self, broker: int) -> None:
        """Stalled reassignment: incoming-copy bandwidth collapses to ~0
        (the broker stays alive, so dead-task detection does NOT fire —
        only the movement timeout or the watchdog can unwedge it)."""
        b = self.sim._brokers[broker]
        self._saved_rates.setdefault(broker, b.reassignment_rate_mb_s)
        b.reassignment_rate_mb_s = 1e-9

    def _do_unstall_broker(self, broker: int) -> None:
        saved = self._saved_rates.pop(broker, None)
        if saved is not None:
            self.sim._brokers[broker].reassignment_rate_mb_s = saved

    def _do_admin_error_rate(self, method: str, rate: float,
                             code: str = "REQUEST_TIMED_OUT") -> None:
        if rate <= 0:
            self.admin_error_rates.pop(method, None)
        else:
            self.admin_error_rates[method] = (min(rate, 1.0), code)

    def _do_admin_burst(self, method: str, count: int,
                        code: str = "REQUEST_TIMED_OUT") -> None:
        self.admin_bursts[method] = (code, count)

    def _do_drop_samples(self, rate: float) -> None:
        self.sample_drop_rate = min(max(rate, 0.0), 1.0)

    def _do_crash_process(self) -> None:
        """Process-level fault: kill the control plane at this exact
        simulated instant — mid-execution when the executor happens to be
        sleeping across the scheduled step (same determinism contract as
        every other fault). Raises; see :class:`ProcessCrashed`."""
        raise ProcessCrashed(
            f"chaos: control-plane process crashed at t={self.sim.now_ms}ms "
            f"(seed={self.seed})")

    def _do_cut_stream(self, on: bool = True) -> None:
        """Sever (or restore, ``on=False``) the replication push channel:
        follower polls return None, lag grows, the replica transitions
        STREAMING -> LAGGING and starts refusing gated reads."""
        self.stream_cut = bool(on)

    def _do_delay_stream(self, ms: int = 0) -> None:
        """Add ``ms`` of one-way delivery delay to the replication
        stream (0 restores the instant link). Delayed frames are hidden,
        not dropped — they deliver in order once old enough."""
        self.stream_delay_ms = max(0, int(ms))

    def _do_kill_endpoint(self, member: str) -> None:
        """Kill a fleet member's WHOLE admin/sampler endpoint: every
        call from the coordinating plane times out (the member cluster
        itself may be fine — this is the network/control-plane failure
        domain the quarantine machine isolates)."""
        self.endpoints_down.add(member)
        self.endpoint_flap.pop(member, None)

    def _do_restart_endpoint(self, member: str) -> None:
        self.endpoints_down.discard(member)
        self.endpoint_flap.pop(member, None)

    def _do_delay_endpoint(self, member: str, ms: int = 0) -> None:
        """Add ``ms`` of per-call latency to a member endpoint (0
        restores). The caller's deadline decides whether the slowed call
        still lands or counts as missed."""
        if ms <= 0:
            self.endpoint_delay_ms.pop(member, None)
        else:
            self.endpoint_delay_ms[member] = int(ms)

    def _do_flap_endpoint(self, member: str, period: int = 1) -> None:
        """Flap a member endpoint: alternates down/up every ``period``
        steps, keyed off the shared step counter (down on even
        ``step // period`` parity) so replay reproduces the exact same
        up/down lattice."""
        self.endpoints_down.discard(member)
        self.endpoint_flap[member] = max(int(period), 1)

    def endpoint_down(self, member: str) -> bool:
        """Is this member's endpoint unreachable right now?"""
        if member in self.endpoints_down:
            return True
        period = self.endpoint_flap.get(member)
        if period:
            return (self.step // period) % 2 == 0
        return False

    def _do_clock_jump(self, ms: int) -> None:
        """Forward clock jump: simulated time leaps (windows roll, time
        thresholds trip early). In-flight copies see the elapsed time too
        — a wall-clock jump on a live cluster does the same."""
        self._jumped_ms += ms
        self.sim.advance_to(self.sim.now_ms + ms)

    # ------------------------------------------------------- admin faults
    def maybe_fail_admin(self, method: str) -> None:
        """Raise the scheduled classified admin error for this call, if
        any. Burst injections take precedence over sustained rates."""
        n = self._admin_counters[method] = (
            self._admin_counters.get(method, 0) + 1)
        burst = self.admin_bursts.get(method)
        if burst is not None:
            fire, nxt = consume_injection(*burst)
            if nxt is None:
                self.admin_bursts.pop(method)
            else:
                self.admin_bursts[method] = nxt
            if fire:
                self._raise(method, fire)
        entry = self.admin_error_rates.get(method)
        if entry is not None:
            rate, code = entry
            if _draw(self.seed, method, n) < rate:
                self._raise(method, code)

    def _raise(self, method: str, code: str) -> None:
        self.note("admin", f"injected {code} on {method} "
                  f"(call #{self._admin_counters[method]})")
        if code == "REQUEST_TIMED_OUT":
            raise AdminTimeoutError(
                f"chaos: {method} timed out (injected, seed={self.seed})")
        raise AdminOperationError(
            f"chaos: {method} failed with {code} (injected, "
            f"seed={self.seed})")

    # -------------------------------------------------- random schedules
    def schedule_random_soak(self, steps: int, *,
                             recover_margin: int = None) -> None:
        """Generate a recoverable randomized fault schedule from the seed.

        Deterministic in ``(seed, steps, cluster broker set)``. Every
        destructive fault schedules its own recovery inside the first
        ``steps - recover_margin`` steps, so the post-schedule heal phase
        can always restore a healthy cluster — the soak asserts recovery,
        not mere survival.
        """
        import random
        rng = random.Random(self.seed)
        brokers = sorted(self.sim.describe_cluster())
        margin = (steps // 3 if recover_margin is None else recover_margin)
        horizon = max(steps - margin, 1)

        # One broker crash + recovery (never more than one dead at once:
        # rf-2 test topologies cannot survive correlated double failures).
        victim = rng.choice(brokers)
        down = rng.randint(1, max(horizon // 3, 1))
        at = rng.randint(0, max(horizon - down, 0))
        self.schedule(at, "kill_broker", broker=victim)
        self.schedule(at + down, "restart_broker", broker=victim)

        # A sustained admin-timeout window on a random executor RPC.
        method = rng.choice(["alter_partition_reassignments",
                             "list_partition_reassignments",
                             "describe_cluster",
                             "elect_preferred_leaders"])
        w0 = rng.randint(0, horizon)
        self.schedule(w0, "admin_error_rate", method=method,
                      rate=rng.uniform(0.1, 0.5))
        self.schedule(min(w0 + rng.randint(1, max(horizon // 2, 1)), steps),
                      "admin_error_rate", method=method, rate=0.0)

        # A metric-dropout window.
        d0 = rng.randint(0, horizon)
        self.schedule(d0, "drop_samples", rate=rng.uniform(0.3, 0.9))
        self.schedule(min(d0 + rng.randint(1, max(horizon // 2, 1)), steps),
                      "drop_samples", rate=0.0)

        # Optionally: a stall window on a (possibly different) broker.
        if rng.random() < 0.5:
            stall = rng.choice(brokers)
            s0 = rng.randint(0, horizon)
            self.schedule(s0, "stall_broker", broker=stall)
            self.schedule(
                min(s0 + rng.randint(1, max(horizon // 3, 1)), steps),
                "unstall_broker", broker=stall)

        # Optionally: a forward clock jump of a few windows.
        if rng.random() < 0.5:
            self.schedule(rng.randint(0, steps), "clock_jump",
                          ms=self.step_ms * rng.randint(2, 8))
