"""``ConfluentKafkaAdminWire`` — the production :class:`KafkaAdminWire`
binding over ``confluent_kafka.admin.AdminClient``.

This is the module the adapter's docstring promises: the ~50 lines that
express the reference executor's admin calls
(``ExecutionUtils.java:446`` ``submitReplicaReassignmentTasks`` →
``alterPartitionReassignments``, ``:407`` ``submitPreferredLeaderElection``
→ ``electLeaders``, ``ExecutorAdminUtils`` logdir/config ops) against the
real client API. ``confluent_kafka`` is not bundled in this deployment
image, so everything is import-guarded: importing this module is always
safe, constructing :class:`ConfluentKafkaAdminWire` without the package
raises with an actionable message, and the contract tests in
``tests/test_kafka_admin.py`` run against the mock wire everywhere and
against this binding when the package is present (skipped otherwise).

Error mapping: confluent futures raise ``KafkaException`` wrapping a
``KafkaError`` whose ``name()`` is the broker protocol error name — the
exact strings :class:`KafkaAdminClusterClient` classifies
(``UNKNOWN_TOPIC_OR_PARTITION``, ``REQUEST_TIMED_OUT``, ...), so the
translation is one ``except`` clause.

librdkafka note: AlterPartitionReassignments / ListPartitionReassignments
(KIP-455) and AlterReplicaLogDirs are version-dependent in librdkafka;
the binding forwards when the installed ``AdminClient`` exposes them and
raises :class:`AdminOperationError` naming the missing method otherwise,
so an under-featured client fails loudly at the call site rather than
silently skipping a rebalance step.
"""

from __future__ import annotations

from .kafka_admin import AdminOperationError, KafkaWireError

try:  # pragma: no cover - exercised only where confluent_kafka is installed
    import confluent_kafka
    import confluent_kafka.admin as _ck_admin
    HAVE_CONFLUENT_KAFKA = True
except ImportError:  # the deployment image here has no Kafka client
    confluent_kafka = None
    _ck_admin = None
    HAVE_CONFLUENT_KAFKA = False


class _WireFuture:
    """Adapts a confluent future: ``KafkaException`` → :class:`KafkaWireError`
    carrying the broker error name the adapter classifies."""

    def __init__(self, inner):
        self._inner = inner

    def result(self, timeout: float | None = None):
        try:
            return self._inner.result(timeout)
        except confluent_kafka.KafkaException as e:
            err = e.args[0]
            raise KafkaWireError(err.name(), err.str()) from e


class _ValueFuture:
    """A pre-resolved per-key future (for APIs that return one future for
    the whole batch with per-key errors in the payload)."""

    def __init__(self, error_name: str | None, message: str = ""):
        self._error_name = error_name
        self._message = message

    def result(self, timeout: float | None = None):
        if self._error_name is not None:
            raise KafkaWireError(self._error_name, self._message)
        return None


class ConfluentKafkaAdminWire:
    """:class:`KafkaAdminWire` over a live cluster. ``conf`` is the librdkafka
    config dict (``{"bootstrap.servers": ...}`` + security settings)."""

    def __init__(self, conf: dict, request_timeout_s: float = 30.0):
        if not HAVE_CONFLUENT_KAFKA:
            raise ImportError(
                "confluent_kafka is not installed; install it (pip install "
                "confluent-kafka) to drive a real cluster, or construct the "
                "executor with MockKafkaAdminWire / SimulatedKafkaCluster")
        self._admin = _ck_admin.AdminClient(conf)
        self._timeout = request_timeout_s

    def _require(self, method: str):
        fn = getattr(self._admin, method, None)
        if fn is None:
            raise AdminOperationError(
                f"the installed confluent_kafka AdminClient has no "
                f"{method}() (librdkafka too old for this KIP); upgrade "
                f"confluent-kafka to execute this step")
        return fn

    # ----------------------------------------------------------- metadata
    def describe_cluster(self) -> dict[int, dict]:
        md = self._admin.list_topics(timeout=self._timeout)
        return {b_id: {"host": b.host, "rack": None}
                for b_id, b in md.brokers.items()}

    def list_topics(self) -> dict[tuple[str, int], dict]:
        md = self._admin.list_topics(timeout=self._timeout)
        out: dict[tuple[str, int], dict] = {}
        for tname, topic in md.topics.items():
            for pid, pm in topic.partitions.items():
                out[(tname, pid)] = {"replicas": list(pm.replicas),
                                     "leader": pm.leader,
                                     "isr": list(pm.isrs)}
        return out

    # ------------------------------------------------------ reassignments
    def alter_partition_reassignments(self, targets):
        fn = self._require("alter_partition_reassignments")
        request = {
            confluent_kafka.TopicPartition(t, p):
                (None if reps is None else list(reps))
            for (t, p), reps in targets.items()}
        futures = fn(request, request_timeout=self._timeout)
        return {(tp.topic, tp.partition): _WireFuture(f)
                for tp, f in futures.items()}

    def list_partition_reassignments(self) -> dict[tuple[str, int], dict]:
        fn = self._require("list_partition_reassignments")
        futures = fn(request_timeout=self._timeout)
        out: dict[tuple[str, int], dict] = {}
        for tp, fut in futures.items():
            r = _WireFuture(fut).result(self._timeout)
            out[(tp.topic, tp.partition)] = {
                "target": list(getattr(r, "replicas", ())),
                "adding": list(getattr(r, "adding_replicas", ())),
                "removing": list(getattr(r, "removing_replicas", ()))}
        return out

    # ---------------------------------------------------------- elections
    def elect_leaders(self, tps):
        fn = self._require("elect_leaders")
        request = [confluent_kafka.TopicPartition(t, p) for t, p in tps]
        batch = fn(_ck_admin.ElectionType.PREFERRED, request,
                   request_timeout=self._timeout)
        # One future for the batch, per-partition KafkaError in the payload
        # (processElectLeadersResult walks the same map,
        # ExecutionUtils.java:611) — fan back out to per-key futures.
        try:
            per_tp = batch.result(self._timeout)
        except confluent_kafka.KafkaException as e:
            err = e.args[0]
            return {(t, p): _ValueFuture(err.name(), err.str())
                    for t, p in tps}
        out = {}
        for tp, err in per_tp.items():
            out[(tp.topic, tp.partition)] = _ValueFuture(
                None if err is None else err.name(),
                "" if err is None else err.str())
        return out

    # ------------------------------------------------------------ logdirs
    def describe_log_dirs(self) -> dict[int, dict[str, dict]]:
        md = self._admin.list_topics(timeout=self._timeout)
        fn = self._require("describe_log_dirs")
        futures = fn(list(md.brokers), request_timeout=self._timeout)
        out: dict[int, dict[str, dict]] = {}
        for broker_id, fut in futures.items():
            dirs = _WireFuture(fut).result(self._timeout)
            out[broker_id] = {
                d.path: {"replicas": {
                    (r.topic, r.partition): r.size
                    for r in getattr(d, "replicas", ())}}
                for d in dirs}
        return out

    def alter_replica_log_dirs(self, moves):
        fn = self._require("alter_replica_log_dirs")
        # The executor's batch spans brokers and may hold the same
        # (topic, partition) on two brokers (planner.intra_broker_batch);
        # a TopicPartition-keyed request would silently drop one. Issue
        # one wire call per broker so keys never collide.
        by_broker: dict[int, dict[tuple[str, int, int], str]] = {}
        for (t, p, b), logdir in moves.items():
            by_broker.setdefault(b, {})[(t, p, b)] = logdir
        out = {}
        for b, broker_moves in by_broker.items():
            request = {
                confluent_kafka.TopicPartition(t, p): logdir
                for (t, p, _b), logdir in broker_moves.items()}
            futures = fn(request, request_timeout=self._timeout)
            for tp, f in futures.items():
                out[(tp.topic, tp.partition, b)] = _WireFuture(f)
        return out

    # ------------------------------------------------------------ configs
    def describe_configs(self, resource_type: str, name: str
                         ) -> dict[str, str]:
        res = _ck_admin.ConfigResource(
            getattr(_ck_admin.ConfigResource.Type, resource_type.upper()),
            name)
        futures = self._admin.describe_configs([res],
                                               request_timeout=self._timeout)
        entries = _WireFuture(futures[res]).result(self._timeout)
        return {k: v.value for k, v in entries.items() if v.value is not None}

    def incremental_alter_configs(self, resource_type: str, name: str,
                                  ops: dict[str, str | None]):
        res = _ck_admin.ConfigResource(
            getattr(_ck_admin.ConfigResource.Type, resource_type.upper()),
            name)
        for key, value in ops.items():
            if value is None:
                res.add_incremental_config(
                    _ck_admin.ConfigEntry(
                        key, None,
                        incremental_operation=_ck_admin
                        .AlterConfigOpType.DELETE))
            else:
                res.add_incremental_config(
                    _ck_admin.ConfigEntry(
                        key, value,
                        incremental_operation=_ck_admin
                        .AlterConfigOpType.SET))
        futures = self._admin.incremental_alter_configs(
            [res], request_timeout=self._timeout)
        return _WireFuture(futures[res])
