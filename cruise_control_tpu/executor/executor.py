"""The executor: applies optimization proposals to the (simulated or real)
cluster (ref ``executor/Executor.java``).

Mirrors ``ProposalExecutionRunnable.execute()`` (``Executor.java:1442-1502``)
phase ordering::

    1. inter-broker replica movements   (interBrokerMoveReplicas :1607)
    2. intra-broker (logdir) movements  (intraBrokerMoveReplicas :1679)
    3. leadership movements             (moveLeaderships :1742)

with per-round planner batches under concurrency caps, progress polling
every ``progress_check_interval_ms``, adaptive concurrency
(``ConcurrencyAdjuster`` ``:493-644``), replication throttling, dead-task
detection when brokers die mid-flight (``ExecutionUtils.maybeMarkTaskAsDead``),
user-triggered stop (``userTriggeredStopExecution`` ``:1145``), and
single-execution reservation (``:1100`` handshake).

Host-side by design: execution is I/O-bound control-plane work — exactly the
part of the reference that stays off the TPU.
"""

from __future__ import annotations

import enum
import logging
import sys
import threading
import time as _time
from dataclasses import dataclass, field

from ..core.retry import RetryPolicy
from ..model.proposals import ExecutionProposal
from .admin import ClusterAdminClient
from .concurrency import (ConcurrencyAdjuster, ConcurrencyConfig,
                          ExecutionConcurrencyManager)
from .kafka_admin import RETRYABLE_ADMIN_ERRORS
from .planner import ExecutionTaskPlanner
from .strategy import StrategyContext, strategy_chain
from .tasks import (ExecutionTask, ExecutionTaskManager, IntraBrokerReplicaMove,
                    TaskState, TaskType)
from .throttle import ReplicationThrottleHelper


class ExecutorState(enum.Enum):
    """ref ``ExecutorState.State``."""

    NO_TASK_IN_PROGRESS = "NO_TASK_IN_PROGRESS"
    STARTING_EXECUTION = "STARTING_EXECUTION"
    INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS = (
        "INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS")
    INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS = (
        "INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS")
    LEADER_MOVEMENT_TASK_IN_PROGRESS = "LEADER_MOVEMENT_TASK_IN_PROGRESS"
    STOPPING_EXECUTION = "STOPPING_EXECUTION"


class ExecutorNotifier:
    """SPI for execution lifecycle alerts (ref ExecutorNotifier.java)."""

    def on_execution_started(self, uuid: str) -> None:  # pragma: no cover
        pass

    def on_execution_finished(self, result: "ExecutionResult") -> None:  # pragma: no cover
        pass


@dataclass
class ExecutorConfig:
    """Subset of ExecutorConfig constants (ref config/constants/ExecutorConfig)."""

    progress_check_interval_ms: int = 10_000
    #: floor for per-request progress-check overrides (ref
    #: min.execution.progress.check.interval.ms)
    min_progress_check_interval_ms: int = 5_000
    #: per-task stall bound before it is declared DEAD
    replica_movement_timeout_ms: int = 3_600_000
    leadership_movement_timeout_ms: int = 180_000
    default_replication_throttle_bytes: int | None = None
    concurrency: ConcurrencyConfig = field(default_factory=ConcurrencyConfig)
    concurrency_adjuster_enabled: bool = True
    #: how often the adjuster re-evaluates caps (ref
    #: concurrency.adjuster.interval.ms); progress polls in between skip
    #: the refresh
    concurrency_adjuster_interval_ms: int = 1_800_000
    #: adjuster per-type enables (ref concurrency.adjuster.
    #: inter.broker.replica.enabled / leadership.enabled)
    adjuster_inter_broker_enabled: bool = True
    adjuster_leadership_enabled: bool = True
    #: recently removed/demoted broker exclusion windows (ref
    #: removal/demotion.history.retention.time.ms)
    removal_history_retention_ms: int = 86_400_000
    demotion_history_retention_ms: int = 86_400_000
    #: in-flight tasks older than this are logged as slow (ref
    #: task.execution.alerting.threshold.ms), at most once per backoff
    slow_task_alerting_threshold_ms: int = 90_000
    slow_task_alerting_backoff_ms: int = 60_000
    #: strategy chain applied when a request names none (ref
    #: default.replica.movement.strategies)
    default_strategy_names: tuple = ()
    #: ref max.num.cluster.movements: ceiling on the concurrency any
    #: request (or the adjuster) may ask for across movement types —
    #: bounds the executor's in-flight bookkeeping. Requests exceeding
    #: it are rejected at submission (the reference throws on the
    #: equivalent setters).
    max_num_cluster_movements: int = 1250
    #: shared backoff+jitter policy for retryable admin failures
    #: (AdminTimeoutError) on the setup/poll/abort paths (ref
    #: admin.retry.* config keys)
    admin_retry: RetryPolicy = field(default_factory=RetryPolicy)
    #: stuck-execution watchdog: an execution still in flight past this
    #: deadline is force-aborted and the single-execution reservation
    #: released (0 = disabled; ref execution.stuck.watchdog.timeout.ms)
    stuck_execution_timeout_ms: int = 0
    #: executor.device.scheduling: compute inter-broker batches on the
    #: device (schedule.DeviceMoveScheduler) + run the pipelined phase.
    #: False = host greedy planner (the documented degrade path). The
    #: facade reads this to decide whether to build a MoveSchedule.
    device_scheduling: bool = False
    #: executor.schedule.bandwidth.mb.per.batch (None = unconstrained —
    #: keeps the device schedule bit-identical to the greedy planner)
    schedule_bandwidth_mb_per_batch: float | None = None
    #: executor.schedule.max.repair.rounds: bisection-repair budget for
    #: hard-goal-violating batch boundaries
    schedule_max_repair_rounds: int = 4
    #: executor.forecast.deferral.*: consult forecast trajectories to
    #: defer heals on projected-shrinking topics and pre-position
    #: leaders for projected-hot topics (PR 13 follow-up)
    forecast_deferral_enabled: bool = False
    forecast_deferral_horizon_ms: int = 3_600_000
    forecast_deferral_shrink_factor: float = 0.7
    forecast_hot_factor: float = 1.5


@dataclass
class ExecutionResult:
    uuid: str
    state_counts: dict
    started_ms: int
    finished_ms: int
    stopped: bool
    num_dead_tasks: int

    @property
    def succeeded(self) -> bool:
        return not self.stopped and self.num_dead_tasks == 0


class OngoingExecutionError(RuntimeError):
    """ref OngoingExecutionException."""


class RecentBrokers:
    """Set of broker ids with per-entry timestamps and a retention window
    (ref Executor.java:426-434 recently removed/demoted broker history +
    removal/demotion.history.retention.time.ms expiry). Set-like enough
    for the existing call sites: ``|=``, ``in``, iteration, ``clear``."""

    def __init__(self, retention_ms: int, now_ms) -> None:
        self._stamps: dict[int, int] = {}
        self.retention_ms = retention_ms
        self._now_ms = now_ms

    def _prune(self) -> None:
        cutoff = self._now_ms() - self.retention_ms
        for b in [b for b, t in self._stamps.items() if t < cutoff]:
            del self._stamps[b]

    def __ior__(self, brokers) -> "RecentBrokers":
        now = self._now_ms()
        for b in brokers:
            self._stamps[b] = now
        return self

    def __contains__(self, broker: int) -> bool:
        self._prune()
        return broker in self._stamps

    def __iter__(self):
        self._prune()
        return iter(sorted(self._stamps))

    def __len__(self) -> int:
        self._prune()
        return len(self._stamps)

    def __bool__(self) -> bool:
        return len(self) > 0

    def clear(self) -> None:
        self._stamps.clear()


#: Audit trail of execution lifecycle events (ref the reference's
#: dedicated OPERATION_LOG logger, ``Executor.java`` notifyExecutionFinished
#: / operation log appender in config/log4j.properties).
OPERATION_LOG = logging.getLogger("cruise_control_tpu.operation")


class Executor:
    def __init__(self, admin: ClusterAdminClient,
                 config: ExecutorConfig | None = None,
                 notifier: ExecutorNotifier | None = None,
                 topic_config_provider=None,
                 now_ms=None, sleep_ms=None, registry=None,
                 tracer=None) -> None:
        from ..core.sensors import (EXECUTOR_SENSOR, MetricRegistry)
        from ..core.tracing import default_tracer
        #: span tracer: executions emit executor.execute → per-phase →
        #: per-task lifecycle spans (tasks via the tracker)
        self.tracer = tracer or default_tracer()
        self.admin = admin
        self.config = config or ExecutorConfig()
        # ref max.num.cluster.movements: validate the STATIC config
        # relationship at construction (server startup) so a
        # misconfiguration fails the deploy, not every later execution
        # (incl. silent self-healing failures); and clamp the adjuster's
        # upper bounds so additive increase can never climb past the
        # ceiling either.
        self._check_movement_cap(self.config.concurrency)
        cap = self.config.max_num_cluster_movements
        cc0 = self.config.concurrency
        if (cc0.max_leader_movements > cap
                or cc0.min_leader_movements > cap):
            # BOTH adjuster bounds clamp to the ceiling: the manager
            # computes max(min_bound, min(value, max_bound)), so an
            # unclamped min FLOOR would re-raise leadership concurrency
            # above the ceiling after any adjuster write.
            from dataclasses import replace as _dc_replace
            self.config = _dc_replace(
                self.config, concurrency=_dc_replace(
                    cc0,
                    max_leader_movements=min(cc0.max_leader_movements,
                                             cap),
                    min_leader_movements=min(cc0.min_leader_movements,
                                             cap)))
        self.notifier = notifier or ExecutorNotifier()
        # Per-topic min.insync.replicas source for the min-ISR-aware
        # strategies/adjuster (ref TopicConfigProvider SPI); defaults to
        # reading dynamic topic configs through the admin client.
        if topic_config_provider is None:
            from ..config.topics import AdminTopicConfigProvider
            topic_config_provider = AdminTopicConfigProvider(admin)
        self.topic_config_provider = topic_config_provider
        self._now_ms = now_ms or (lambda: int(_time.time() * 1000))
        self._sleep_ms = sleep_ms or (lambda ms: _time.sleep(ms / 1000))
        self._lock = threading.RLock()
        self._state = ExecutorState.NO_TASK_IN_PROGRESS
        self._stop_requested = threading.Event()
        self._task_manager: ExecutionTaskManager | None = None
        self._progress_interval_ms = self.config.progress_check_interval_ms
        self._last_adjust_ms = 0
        self._last_slow_alert_ms = 0
        self._current_uuid: str | None = None
        #: brokers removed/demoted by recent executions (ref
        #: Executor.java:426-434), expiring per the history retention
        self.recently_removed_brokers = RecentBrokers(
            self.config.removal_history_retention_ms, self._now_ms)
        self.recently_demoted_brokers = RecentBrokers(
            self.config.demotion_history_retention_ms, self._now_ms)
        #: adjuster types disabled at runtime via /admin (seeded into each
        #: execution's ConcurrencyAdjuster; ref
        #: DISABLE_CONCURRENCY_ADJUSTER_FOR_PARAM)
        self.adjuster_disabled_types: set[str] = set()
        # Execution sensors (ref Executor.java:256-266
        # proposal-execution-timer, ExecutionTaskTracker.java:121-122
        # movement-rate meters, Executor.java:348-360 ongoing gauges).
        self.registry = registry or MetricRegistry()
        _n = MetricRegistry.name
        self._execution_timer = self.registry.timer(
            _n(EXECUTOR_SENSOR, "proposal-execution-timer"))
        self._partition_move_meter = self.registry.meter(
            _n(EXECUTOR_SENSOR, "partition-movement-rate"))
        self._leadership_move_meter = self.registry.meter(
            _n(EXECUTOR_SENSOR, "leadership-movement-rate"))
        self._executions_started = self.registry.counter(
            _n(EXECUTOR_SENSOR, "executions-started"))
        self._executions_stopped = self.registry.counter(
            _n(EXECUTOR_SENSOR, "executions-stopped"))
        # Robustness sensors: retried admin calls, swallowed-but-logged
        # teardown failures, and watchdog-forced aborts must all be
        # visible on /metrics — a silently-degrading executor is the
        # failure mode the chaos suite exists to prevent.
        self._admin_retries = self.registry.meter(
            _n(EXECUTOR_SENSOR, "admin-retry-rate"))
        # Scheduled-pipeline sensors: a completed-but-misplaced
        # reassignment (verify step) and the ETA-skipped poll rounds the
        # pipelined phase avoided must both be observable on /metrics.
        self._verify_failures = self.registry.meter(
            _n(EXECUTOR_SENSOR, "scheduled-verify-failure-rate"))
        self._polls_skipped = self.registry.counter(
            _n(EXECUTOR_SENSOR, "scheduled-polls-skipped"))
        #: last scheduled execution's pipeline statistics (devicestats'
        #: ``executor`` section; None until a scheduled execution ran)
        self.last_schedule_stats: dict | None = None
        self._teardown_failures = self.registry.meter(
            _n(EXECUTOR_SENSOR, "teardown-failure-rate"))
        self._watchdog_aborts = self.registry.counter(
            _n(EXECUTOR_SENSOR, "watchdog-forced-aborts"))
        self._fencing_aborts = self.registry.counter(
            _n(EXECUTOR_SENSOR, "fencing-forced-aborts"))
        #: leadership fence (core/leader.py LeaderElector, or any object
        #: with ``epoch`` + ``is_current(token)``). When set, every
        #: execution captures the fencing epoch at start and re-checks it
        #: at each phase boundary / progress poll: a deposed leader's
        #: in-flight execution aborts instead of dueling with the new
        #: leader. None = unfenced (single-process default).
        self.fence = None
        self._fence_token: int | None = None
        self._fenced = False
        self._exec_started_ms = 0
        #: decision journal (core/events.py), attached by the facade —
        #: execution admits/completions/aborts are the decisions that
        #: mutate the real cluster, the ones forensics cares most about.
        self.journal = None
        self._exec_journal_seq: int | None = None
        self.registry.gauge(
            _n(EXECUTOR_SENSOR, "has-ongoing-execution"),
            lambda: int(self.has_ongoing_execution()))
        # Per-(action, state) gauges over the current execution's task
        # tracker (ref the documented Executor sensor catalog,
        # docs/wiki "Sensors.md": Executor.replica-action-in-progress,
        # leadership-action-pending, ...-aborting/aborted/dead).
        def _tracked(task_types, state):
            def read():
                tm = self._task_manager
                if tm is None:
                    return 0
                return sum(tm.tracker.num_in(t, state) for t in task_types)
            return read
        _replica = (TaskType.INTER_BROKER_REPLICA_ACTION,
                    TaskType.INTRA_BROKER_REPLICA_ACTION)
        _leader = (TaskType.LEADER_ACTION,)
        for action, types in (("replica", _replica),
                              ("leadership", _leader)):
            for state in (TaskState.PENDING, TaskState.IN_PROGRESS,
                          TaskState.ABORTING, TaskState.ABORTED,
                          TaskState.DEAD):
                name = state.value.lower().replace("_", "-")
                self.registry.gauge(
                    _n(EXECUTOR_SENSOR, f"{action}-action-{name}"),
                    _tracked(types, state))

    # ------------------------------------------------------------- state
    @property
    def state(self) -> ExecutorState:
        return self._state

    def _check_movement_cap(self, cc) -> None:
        """ref max.num.cluster.movements: no movement-type concurrency may
        exceed the cluster-wide ceiling (Executor.java throws on the
        equivalent setters — a runaway per-request override must not
        balloon in-flight bookkeeping)."""
        cap = self.config.max_num_cluster_movements
        for fname in ("max_num_cluster_partition_movements",
                      "num_concurrent_leader_movements",
                      "num_concurrent_intra_broker_partition_movements"):
            val = getattr(cc, fname)
            if val > cap:
                raise ValueError(
                    f"{fname}={val} exceeds max.num.cluster.movements"
                    f"={cap}")

    def has_ongoing_execution(self) -> bool:
        return self._state is not ExecutorState.NO_TASK_IN_PROGRESS

    # -------------------------------------------------- recovery plumbing
    def _admin_call(self, what: str, fn, *args, **kwargs):
        """Run a retryable admin RPC under the shared backoff policy: a
        transient AdminTimeoutError is retried with exponential backoff +
        jitter (on the execution clock, so chaos replays are exact);
        fatal errors propagate on the first attempt."""
        def on_retry(attempt, delay_ms, exc):
            self._admin_retries.mark()
            OPERATION_LOG.warning(
                "Admin call %s failed transiently (%s: %s); retry %d in "
                "%d ms", what, type(exc).__name__, exc, attempt + 1,
                delay_ms)
        return self.config.admin_retry.call(
            fn, *args, retry_on=RETRYABLE_ADMIN_ERRORS,
            sleep_ms=self._sleep_ms, now_ms=self._now_ms,
            on_retry=on_retry, **kwargs)

    def _teardown_call(self, what: str, fn, *args, **kwargs):
        """Teardown-path variant of :meth:`_admin_call`: retries like the
        main path, but an exhausted retry budget is LOGGED AND METERED
        instead of raised — a cleanup failure must never strand the
        executor mid-teardown holding the single-execution reservation.
        Returns None when the call ultimately failed."""
        try:
            return self._admin_call(what, fn, *args, **kwargs)
        except Exception as exc:   # noqa: BLE001 — teardown must proceed
            self._teardown_failures.mark()
            OPERATION_LOG.error(
                "Teardown call %s failed after retries (%s: %s); "
                "continuing teardown", what, type(exc).__name__, exc)
            return None

    def _watchdog_check(self) -> None:
        """Stuck-execution watchdog (execution.stuck.watchdog.timeout.ms):
        an execution past its deadline is force-aborted through the normal
        stop path, which releases the reservation and aborts in-flight
        tasks — a wedged execution must not hold the executor forever."""
        deadline = self.config.stuck_execution_timeout_ms
        if not deadline or self._stop_requested.is_set():
            return
        elapsed = self._now_ms() - self._exec_started_ms
        if elapsed > deadline:
            self._watchdog_aborts.inc()
            OPERATION_LOG.error(
                "Execution %s stuck: %d ms in flight exceeds the "
                "stuck-execution watchdog deadline (%d ms); force-aborting",
                self._current_uuid or "(no-uuid)", elapsed, deadline)
            self._stop_requested.set()

    def _fence_check(self) -> None:
        """Leadership fence: an execution whose fencing epoch is no
        longer current (this process lost, resigned, or outlived its
        lease) stops mutating at the next check point — the stop flag
        aborts every phase loop, and the abort path skips cluster-side
        cancellations (see _abort_in_flight) so the only process issuing
        admin mutations is the new leader."""
        if (self.fence is None or self._fenced
                or self._stop_requested.is_set()):
            return
        # Keep the lease alive while we are demonstrably running: a
        # leader blocked in a long execution renews from its own poll
        # loop (renew-only — a lease that already lapsed stays lapsed,
        # so a paused process still fences below).
        keepalive = getattr(self.fence, "keepalive", None)
        if keepalive is not None:
            keepalive(self._now_ms())
        if not self.fence.is_current(self._fence_token):
            self._fenced = True
            self._fencing_aborts.inc()
            if self.journal is not None:
                self.journal.record(
                    "execute", "fence-abort", severity="error",
                    epoch=self._fence_token, cause=self._exec_journal_seq,
                    detail={"uuid": self._current_uuid})
            OPERATION_LOG.error(
                "Execution %s FENCED: fencing epoch %s is no longer "
                "current (leadership lost); aborting at the next phase "
                "boundary without cluster-side cancellation",
                self._current_uuid or "(no-uuid)", self._fence_token)
            self._stop_requested.set()

    def state_json(self) -> dict:
        """Serialized for the /state endpoint (ref ExecutorState.java)."""
        out = {"state": self._state.value}
        if self.fence is not None:
            out["fencingEpoch"] = self._fence_token
        tm = self._task_manager
        if tm is not None:
            out["taskSummary"] = tm.tracker.summary()
            out["triggeredUserTaskId"] = self._current_uuid
        return out

    def stop_execution(self, force: bool = False,
                       stop_external_agent: bool = False) -> None:
        """User-triggered stop (ref userTriggeredStopExecution :1145).

        ``force`` cancels the cluster's in-flight reassignments NOW
        instead of waiting for the run loop's next poll to observe the
        stop flag (ref FORCE_STOP_PARAM / maybeStopPartitionReassignment);
        with ``stop_external_agent`` the cancellation covers every ongoing
        reassignment — including ones started outside this executor (ref
        STOP_EXTERNAL_AGENT_PARAM)."""
        if self.has_ongoing_execution():
            self._stop_requested.set()
        elif not (force and stop_external_agent):
            return
        if force:
            ongoing = self._admin_call(
                "listPartitionReassignments",
                self.admin.list_partition_reassignments)
            if not stop_external_agent:
                tm = self._task_manager
                ours = ({t.topic_partition for tt in TaskType
                         for t in tm.tracker.tasks_in(
                             tt, TaskState.IN_PROGRESS)}
                        if tm is not None else set())
                ongoing = {tp: v for tp, v in ongoing.items() if tp in ours}
            if ongoing:
                self._admin_call("forceCancelReassignments",
                                 self.admin.alter_partition_reassignments,
                                 {tp: None for tp in ongoing})

    # ----------------------------------------------------------- execute
    def execute_proposals(self, proposals: list[ExecutionProposal],
                          uuid: str = "",
                          intra_broker_moves: list[IntraBrokerReplicaMove] | None = None,
                          strategy_names: list[str] | None = None,
                          strategy_context: StrategyContext | None = None,
                          throttle_bytes: int | None = None,
                          removed_brokers: set[int] | None = None,
                          demoted_brokers: set[int] | None = None,
                          concurrency_overrides: dict | None = None,
                          progress_check_interval_ms: int | None = None,
                          throttle_excluded_brokers: set[int] | None = None,
                          schedule=None,
                          leadership_priority_topics: set[str] | None = None,
                          ) -> ExecutionResult:
        """Apply proposals to the cluster; blocks until done/stopped (ref
        ``executeProposals`` ``Executor.java:810`` + ProposalExecutionRunnable).
        Call from a worker thread for async semantics (the API layer does).

        ``concurrency_overrides`` maps :class:`ConcurrencyConfig` field
        names to per-request values and ``progress_check_interval_ms``
        overrides the poll cadence for THIS execution only (ref the
        per-request concurrency/interval parameters the runnables read,
        e.g. ``RebalanceParameters`` CONCURRENT_*_PARAM).

        ``schedule`` (a :class:`.schedule.MoveSchedule` over THESE
        proposals) switches the inter-broker phase to the pipelined
        executor: precomputed batches, one overlapped admin-RPC round per
        poll, ETA-based poll skipping, and a placement-verify step on
        completion. None = the host greedy planner (the documented
        degrade path). ``leadership_priority_topics`` front-loads those
        topics' leadership moves (forecast-projected hot topics get their
        leaders pre-positioned first)."""
        # Pure parameter validation BEFORE the single-execution
        # reservation: a rejected request must not consume the slot, emit
        # an orphan on_execution_finished, or count as an execution.
        cc = self.config.concurrency
        if concurrency_overrides:
            from dataclasses import replace as _dc_replace
            cc = _dc_replace(cc, **concurrency_overrides)
        self._check_movement_cap(cc)
        # Leadership gate BEFORE the reservation: a standby (or a leader
        # whose lease already lapsed) must refuse outright, not consume
        # the single-execution slot and abort one poll later.
        if self.fence is not None \
                and not self.fence.is_current(self.fence.epoch):
            from ..core.leader import NotLeaderError
            if self.journal is not None:
                self.journal.record(
                    "execute", "refused-not-leader", severity="warn",
                    detail={"uuid": uuid})
            raise NotLeaderError(
                "refusing execution: this process does not hold the "
                "leadership lease",
                leader_id=getattr(self.fence, "leader_id", lambda: None)())
        with self._lock:
            if self.has_ongoing_execution():
                raise OngoingExecutionError(
                    "an execution is already in progress")
            self._state = ExecutorState.STARTING_EXECUTION
            self._stop_requested.clear()
            self._task_manager = ExecutionTaskManager(tracer=self.tracer)
            self._current_uuid = uuid
        started = self._now_ms()
        self._exec_started_ms = started
        self._executions_started.inc()
        self._exec_journal_seq = (self.journal.record(
            "execute", "started",
            epoch=self.fence.epoch if self.fence is not None else None,
            detail={"uuid": uuid, "numProposals": len(proposals)})
            if self.journal is not None else None)
        # Fencing epoch captured ONCE at start: every later check compares
        # against this token, so a takeover mid-execution (epoch moved)
        # fences even if this process later wins leadership back.
        self._fenced = False
        self._fence_token = (self.fence.epoch if self.fence is not None
                             else None)
        uid = uuid or "(no-uuid)"
        tm = self._task_manager
        throttler = ReplicationThrottleHelper(
            self.admin, throttle_bytes
            if throttle_bytes is not None
            else self.config.default_replication_throttle_bytes)
        # Root execution span, closed in the finally below (an ExitStack
        # keeps the existing try/finally shape — the span must cover the
        # whole run including the abort/cleanup path).
        import contextlib
        _span_stack = contextlib.ExitStack()
        exec_span = _span_stack.enter_context(self.tracer.span(
            "executor.execute", uuid=uid, proposals=len(proposals)))
        # Everything after the reservation sits inside try/finally: a
        # transient admin failure during setup must release the
        # single-execution reservation, or the executor is wedged in
        # STARTING_EXECUTION forever.
        try:
            tasks = tm.add_execution_proposals(proposals)
            if intra_broker_moves:
                tm.add_intra_broker_tasks(intra_broker_moves)
            planner = ExecutionTaskPlanner(strategy_chain(
                strategy_names
                if strategy_names is not None
                else list(self.config.default_strategy_names) or None))
            # Per-request interval floor-clamped (ref
            # min.execution.progress.check.interval.ms).
            self._progress_interval_ms = max(
                progress_check_interval_ms
                if progress_check_interval_ms is not None
                else self.config.progress_check_interval_ms,
                self.config.min_progress_check_interval_ms)
            concurrency = ExecutionConcurrencyManager(
                cc, list(self._admin_call("describeCluster",
                                          self.admin.describe_cluster)))
            adjuster = (ConcurrencyAdjuster(concurrency)
                        if self.config.concurrency_adjuster_enabled else None)
            if adjuster is not None:
                adjuster.disabled_types |= self.adjuster_disabled_types
                if not self.config.adjuster_inter_broker_enabled:
                    adjuster.disabled_types.add("inter_broker_replica")
                if not self.config.adjuster_leadership_enabled:
                    adjuster.disabled_types.add("leadership")
            self._last_adjust_ms = self._now_ms()
            self._last_slow_alert_ms = 0
            inter = [t for t in tasks
                     if t.task_type is TaskType.INTER_BROKER_REPLICA_ACTION]
            self._admin_call("setThrottles", throttler.set_throttles,
                             inter, excluded_brokers=throttle_excluded_brokers)
            self.notifier.on_execution_started(uuid)
            OPERATION_LOG.info(
                "Execution %s started: %d inter-broker, %d intra-broker, "
                "%d leadership tasks%s", uid, len(inter),
                len(intra_broker_moves or []),
                sum(1 for t in tasks
                    if t.task_type is TaskType.LEADER_ACTION),
                (f" (fencing epoch {self._fence_token})"
                 if self._fence_token is not None else ""))
            self._state = ExecutorState.INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS
            if schedule is not None and schedule.batches:
                with self.tracer.span("executor.inter-broker-phase",
                                      scheduled=True):
                    self._run_scheduled_inter_broker_phase(
                        schedule, proposals, concurrency, adjuster)
            else:
                with self.tracer.span("executor.inter-broker-phase"):
                    self._run_inter_broker_phase(planner, concurrency,
                                                 adjuster, strategy_context)
            if not self._stop_requested.is_set():
                OPERATION_LOG.info(
                    "Execution %s: inter-broker phase complete", uid)
            self._state = ExecutorState.INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS
            with self.tracer.span("executor.intra-broker-phase"):
                self._run_intra_broker_phase(planner, concurrency)
            self._state = ExecutorState.LEADER_MOVEMENT_TASK_IN_PROGRESS
            with self.tracer.span("executor.leadership-phase"):
                self._run_leadership_phase(planner, concurrency,
                                           leadership_priority_topics)
            if not self._stop_requested.is_set():
                OPERATION_LOG.info(
                    "Execution %s: leadership phase complete", uid)
        finally:
            # A simulated hard process crash (chaos crash_process fault)
            # must behave like a real one: no teardown, no cleanup RPCs,
            # state abandoned exactly as the dying process would leave it
            # — the restart-from-snapshot path owns recovery.
            if getattr(sys.exc_info()[1], "simulates_process_crash",
                       False):
                raise
            try:
                stopped = self._stop_requested.is_set()
                if stopped:
                    self._state = ExecutorState.STOPPING_EXECUTION
                    self._abort_in_flight()
                if self._fenced:
                    # Throttle configs now belong to the new leader —
                    # a deposed epoch must not clear them (see the
                    # fenced-abort note in _abort_in_flight).
                    OPERATION_LOG.warning(
                        "Fenced abort: leaving replication throttles to "
                        "the new leader")
                else:
                    self._teardown_call("clearThrottles",
                                        throttler.clear_throttles)
                if removed_brokers:
                    self.recently_removed_brokers |= removed_brokers
                if demoted_brokers:
                    self.recently_demoted_brokers |= demoted_brokers
                dead = sum(tm.tracker.num_in(t, TaskState.DEAD)
                           for t in TaskType)
                result = ExecutionResult(
                    uuid=uuid, state_counts=tm.tracker.summary(),
                    started_ms=started, finished_ms=self._now_ms(),
                    stopped=stopped, num_dead_tasks=dead)
                self._execution_timer.update(
                    (result.finished_ms - result.started_ms) / 1000.0)
                if stopped:
                    self._executions_stopped.inc()
                # An in-flight exception must not be recorded as a success.
                exc = sys.exc_info()[1]
                outcome = ("STOPPED" if stopped
                           else f"FAILED ({type(exc).__name__})" if exc
                           else "finished")
                OPERATION_LOG.info(
                    "Execution %s %s: %s (%d dead tasks, %.1fs)", uid,
                    outcome, result.state_counts, dead,
                    (result.finished_ms - result.started_ms) / 1000.0)
                exec_span.set(stopped=stopped, deadTasks=dead,
                              outcome=outcome)
                if self.journal is not None:
                    self.journal.record(
                        "execute",
                        ("fenced-abort" if self._fenced
                         else "stopped" if stopped
                         else "failed" if exc else "completed"),
                        severity=("error" if self._fenced or exc
                                  else "warn" if stopped or dead
                                  else "info"),
                        cause=self._exec_journal_seq,
                        epoch=self._fence_token,
                        detail={"uuid": uuid, "deadTasks": dead,
                                "stateCounts": dict(result.state_counts),
                                **({"error": type(exc).__name__}
                                   if exc else {})})
            finally:
                # Cleanup itself raising must STILL release the
                # single-execution reservation — a wedged
                # STOPPING_EXECUTION state would refuse every later
                # execution (including self-healing fixes) forever.
                self._state = ExecutorState.NO_TASK_IN_PROGRESS
                # The span must close even when cleanup itself raises: a
                # leaked active span would mis-parent every later span
                # recorded on this pooled worker thread.
                _span_stack.close()
            self.notifier.on_execution_finished(result)
        return result

    # ------------------------------------------------------------ phases
    def _run_inter_broker_phase(self, planner, concurrency, adjuster,
                                strategy_context) -> None:
        """ref interBrokerMoveReplicas Executor.java:1607: loop planner batch
        -> alterPartitionReassignments -> poll until finished."""
        tm = self._task_manager
        tt = TaskType.INTER_BROKER_REPLICA_ACTION
        ctx = strategy_context or self._build_strategy_context()
        # Strategy-chain sort happens ONCE per phase (ref TreeSet ordering
        # at plan time); per-round batches walk the cached order.
        planner.begin_phase(tm.tracker.tasks_in(tt, TaskState.PENDING), ctx)
        while (tm.tracker.num_remaining(tt) > 0
               and not self._stop_requested.is_set()):
            # Fence BEFORE building/submitting a batch: a deposed leader
            # must not issue one more mutation on its way out.
            self._fence_check()
            if self._stop_requested.is_set():
                break
            pending = tm.tracker.tasks_in(tt, TaskState.PENDING)
            in_progress = tm.tracker.tasks_in(tt, TaskState.IN_PROGRESS)
            batch = planner.inter_broker_batch(pending, in_progress,
                                               concurrency, ctx)
            if batch:
                targets = {t.topic_partition: list(t.proposal.new_replicas)
                           for t in batch}
                errors = self._admin_call(
                    "alterPartitionReassignments",
                    self.admin.alter_partition_reassignments, targets)
                if self.journal is not None:
                    self.journal.record(
                        "execute", "batch-admitted",
                        cause=self._exec_journal_seq,
                        epoch=self._fence_token,
                        detail={"numTasks": len(batch),
                                "numErrors": sum(
                                    1 for e in errors.values()
                                    if e is not None)})
                now = self._now_ms()
                for t in batch:
                    if errors.get(t.topic_partition) is None:
                        tm.tracker.transition(t, TaskState.IN_PROGRESS, now)
                    else:
                        tm.tracker.transition(t, TaskState.IN_PROGRESS, now)
                        tm.tracker.transition(t, TaskState.DEAD, now)
            elif not in_progress:
                # Nothing in flight and nothing schedulable (all pending
                # blocked by dead-broker caps): mark the rest dead.
                now = self._now_ms()
                for t in pending:
                    tm.tracker.transition(t, TaskState.IN_PROGRESS, now)
                    tm.tracker.transition(t, TaskState.DEAD, now)
                break
            self._sleep_ms(self._progress_interval_ms)
            self._watchdog_check()
            self._fence_check()
            if self._fenced:
                break   # no more RPCs — the poll itself issues cancels
            self._poll_inter_broker_progress()
            self._maybe_alert_slow_tasks()
            self._maybe_adjust_concurrency(adjuster)
        # A completed reassignment leaves the old leader in charge when it
        # is still a member of the new replica set; proposals that also
        # demand a leader change finish with a preferred election (the
        # reassignment made new_replicas[0] the preferred replica).
        self._fence_check()
        needs_election = [
            t.topic_partition
            for t in tm.tracker.tasks_in(tt, TaskState.COMPLETED)
            if t.proposal.has_leader_action]
        if needs_election and not self._stop_requested.is_set():
            self._admin_call("electPreferredLeaders",
                             self.admin.elect_preferred_leaders,
                             needs_election)

    def _maybe_adjust_concurrency(self, adjuster) -> None:
        """Adjuster refresh every concurrency_adjuster_interval_ms (ref
        Executor.java:560-584 min-ISR based adjustment): broker metrics
        feed AIMD, partitions at/below min-ISR are the cluster-wide
        brake. Shared by the greedy and scheduled inter-broker loops."""
        now = self._now_ms()
        if (adjuster is None
                or now - self._last_adjust_ms
                < self.config.concurrency_adjuster_interval_ms):
            return
        self._last_adjust_ms = now
        alive = self._admin_call("describeCluster",
                                 self.admin.describe_cluster)
        metrics = {b: self.admin.broker_metrics(b)
                   for b, up in alive.items() if up}
        num_min_isr = sum(
            1 for info in self._admin_call(
                "describePartitions",
                self.admin.describe_partitions).values()
            if len(info.isr) <= 1 and len(info.replicas) > 1)
        adjuster.refresh(metrics, num_min_isr_partitions=num_min_isr)

    def _overlapped_admin(self, calls: list[tuple]) -> list:
        """Run ``[(what, fn, *args), ...]`` admin RPCs as one round,
        returning results in input order. Calls overlap on a thread pool
        ONLY when the admin client declares ``concurrent_safe`` — the
        simulated cluster replays chaos deterministically precisely
        because RPCs arrive in program order, so overlap is opt-in per
        backend (the bench's latency-modeling wrapper opts in; a real
        AdminClient is thread-safe and would too). Every call still rides
        the shared retry policy via :meth:`_admin_call`."""
        if len(calls) > 1 and getattr(self.admin, "concurrent_safe",
                                      False):
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(max_workers=len(calls)) as pool:
                futures = [pool.submit(self._admin_call, c[0], c[1],
                                       *c[2:]) for c in calls]
                return [f.result() for f in futures]
        return [self._admin_call(c[0], c[1], *c[2:]) for c in calls]

    def _run_scheduled_inter_broker_phase(self, schedule, proposals,
                                          concurrency, adjuster) -> None:
        """Pipelined inter-broker phase over a precomputed
        :class:`.schedule.MoveSchedule`.

        Differences from the greedy loop, in decreasing order of wall
        time saved against a latency-bearing admin backend:

        - **ETA-based poll skipping**: the schedule knows each batch's
          inbound bytes per destination and the throttle rate, so polls
          are skipped while the copy provably cannot have finished —
          fence/watchdog checks still run EVERY interval; only the RPCs
          are skipped. An underestimate degrades to extra poll rounds.
        - **Overlapped RPC rounds**: each poll round issues its three
          reads (list reassignments, cluster liveness, partition
          placements) as one :meth:`_overlapped_admin` round.
        - **Same-round placement verify**: a task absent from the ongoing
          set is checked against its target placement IN THE SAME round
          (COMPLETED is terminal, so the verdict must precede the
          transition); a mismatch is DEAD + metered, not silent success.

        Batch admission is a barrier: batch N+1 submits only when every
        previously submitted task is terminal, so the cluster only ever
        rests at the exact boundary placements the scheduler audited
        against the hard goals. The fence gate runs before every
        admission and after every sleep, same as the greedy loop."""
        tm = self._task_manager
        tt = TaskType.INTER_BROKER_REPLICA_ACTION
        by_prop = {id(t.proposal): t
                   for t in tm.tracker.tasks_in(tt, TaskState.PENDING)}
        batches: list[list[ExecutionTask]] = []
        for idxs in schedule.batches:
            tasks = [by_prop[id(proposals[i])] for i in idxs
                     if 0 <= i < len(proposals)
                     and id(proposals[i]) in by_prop]
            if tasks:
                batches.append(tasks)
        stats = {"batches": len(batches),
                 "moves": sum(len(b) for b in batches),
                 "polls_performed": 0, "polls_skipped": 0,
                 "overlapped_rounds": 0, "verify_failures": 0,
                 "eta_waits": 0}
        etas = list(schedule.eta_ms) + [None] * (len(batches)
                                                 - len(schedule.eta_ms))
        next_batch = 0
        poll_due_ms = 0
        while (tm.tracker.num_remaining(tt) > 0
               and not self._stop_requested.is_set()):
            self._fence_check()
            if self._stop_requested.is_set():
                break
            in_flight = tm.tracker.tasks_in(tt, TaskState.IN_PROGRESS)
            calls: list[tuple] = []
            admit: list[ExecutionTask] | None = None
            if not in_flight and next_batch < len(batches):
                admit = batches[next_batch]
                targets = {t.topic_partition: list(t.proposal.new_replicas)
                           for t in admit}
                calls.append(("alterPartitionReassignments",
                              self.admin.alter_partition_reassignments,
                              targets))
            now = self._now_ms()
            do_poll = bool(in_flight) and now >= poll_due_ms
            if in_flight and not do_poll:
                stats["polls_skipped"] += 1
                self._polls_skipped.inc()
            if do_poll:
                stats["polls_performed"] += 1
                calls += [("listPartitionReassignments",
                           self.admin.list_partition_reassignments),
                          ("describeCluster",
                           self.admin.describe_cluster),
                          ("describePartitions",
                           self.admin.describe_partitions)]
            if len(calls) > 1:
                stats["overlapped_rounds"] += 1
            results = self._overlapped_admin(calls)
            if admit is not None:
                errors = results.pop(0)
                if self.journal is not None:
                    self.journal.record(
                        "execute", "batch-admitted",
                        cause=self._exec_journal_seq,
                        epoch=self._fence_token,
                        detail={"batchIndex": next_batch,
                                "numTasks": len(admit),
                                "numErrors": sum(
                                    1 for e in errors.values()
                                    if e is not None)})
                now = self._now_ms()
                for t in admit:
                    tm.tracker.transition(t, TaskState.IN_PROGRESS, now)
                    if errors.get(t.topic_partition) is not None:
                        tm.tracker.transition(t, TaskState.DEAD, now)
                eta = etas[next_batch] if next_batch < len(etas) else None
                if eta:
                    poll_due_ms = now + eta
                    stats["eta_waits"] += 1
                else:
                    poll_due_ms = 0
                next_batch += 1
            if do_poll:
                ongoing, alive, parts = results
                self._process_scheduled_poll(ongoing, alive, parts, stats)
            elif (admit is None and not in_flight
                  and next_batch >= len(batches)):
                # Remaining tasks are in no batch (stale/filtered
                # proposals): mirror the greedy loop's unschedulable
                # handling so the phase terminates.
                now = self._now_ms()
                for t in tm.tracker.tasks_in(tt, TaskState.PENDING):
                    tm.tracker.transition(t, TaskState.IN_PROGRESS, now)
                    tm.tracker.transition(t, TaskState.DEAD, now)
                break
            if tm.tracker.num_remaining(tt) <= 0:
                break
            self._sleep_ms(self._progress_interval_ms)
            self._watchdog_check()
            self._fence_check()
            if self._fenced:
                break
            self._maybe_alert_slow_tasks()
            self._maybe_adjust_concurrency(adjuster)
        self.last_schedule_stats = {**schedule.stats, **stats}
        self._fence_check()
        needs_election = [
            t.topic_partition
            for t in tm.tracker.tasks_in(tt, TaskState.COMPLETED)
            if t.proposal.has_leader_action]
        if needs_election and not self._stop_requested.is_set():
            self._admin_call("electPreferredLeaders",
                             self.admin.elect_preferred_leaders,
                             needs_election)

    def _process_scheduled_poll(self, ongoing, alive, parts, stats) -> None:
        """One scheduled-phase poll round's bookkeeping: verify-then-
        complete, dead-destination/timeout cancellation — the greedy
        poll's semantics plus the placement-verify step."""
        tm = self._task_manager
        tt = TaskType.INTER_BROKER_REPLICA_ACTION
        now = self._now_ms()
        cancels: dict[tuple[str, int], None] = {}
        completed = 0
        for t in tm.tracker.tasks_in(tt, TaskState.IN_PROGRESS):
            tp = t.topic_partition
            if tp not in ongoing:
                info = parts.get(tp)
                if (info is not None
                        and list(info.replicas)
                        == list(t.proposal.new_replicas)):
                    tm.tracker.transition(t, TaskState.COMPLETED, now)
                    self._partition_move_meter.mark()
                    completed += 1
                else:
                    # The reassignment vanished from the ongoing set but
                    # the placement does not match the proposal (e.g. an
                    # external agent rewrote it): claiming success would
                    # poison every later plan's baseline.
                    stats["verify_failures"] += 1
                    self._verify_failures.mark()
                    tm.tracker.transition(t, TaskState.DEAD, now)
                    if self.journal is not None:
                        self.journal.record(
                            "execute", "verify-failure", severity="error",
                            cause=self._exec_journal_seq,
                            epoch=self._fence_token,
                            detail={"topicPartition": list(tp),
                                    "observed": (None if info is None
                                                 else list(info.replicas)),
                                    "proposed": list(
                                        t.proposal.new_replicas)})
                    OPERATION_LOG.warning(
                        "Scheduled execution: %s completed with placement "
                        "%s != proposed %s; marking DEAD", tp,
                        None if info is None else list(info.replicas),
                        list(t.proposal.new_replicas))
                continue
            dest_dead = any(not alive.get(b, False)
                            for b in t.proposal.replicas_to_add)
            timed_out = (t.start_time_ms is not None and
                         now - t.start_time_ms
                         > self.config.replica_movement_timeout_ms)
            if dest_dead or timed_out:
                cancels[tp] = None
                tm.tracker.transition(t, TaskState.DEAD, now)
        if completed and self.journal is not None \
                and not tm.tracker.tasks_in(tt, TaskState.IN_PROGRESS):
            # The whole admitted batch verified and drained — the
            # admit/complete pair brackets each scheduled batch.
            self.journal.record(
                "execute", "batch-completed",
                cause=self._exec_journal_seq, epoch=self._fence_token,
                detail={"numVerified": completed})
        if cancels:
            self._admin_call("cancelDeadReassignments",
                             self.admin.alter_partition_reassignments,
                             cancels)

    def _maybe_alert_slow_tasks(self) -> None:
        """Log tasks in flight past the alerting threshold, at most once
        per backoff window (ref Executor.java slow-task alerting via
        task.execution.alerting.threshold.ms /
        slow.task.alerting.backoff.ms)."""
        now = self._now_ms()
        if now - self._last_slow_alert_ms \
                < self.config.slow_task_alerting_backoff_ms:
            return
        tm = self._task_manager
        slow = [t for tt in TaskType
                for t in tm.tracker.tasks_in(tt, TaskState.IN_PROGRESS)
                if t.start_time_ms is not None
                and now - t.start_time_ms
                > self.config.slow_task_alerting_threshold_ms]
        if slow:
            self._last_slow_alert_ms = now
            OPERATION_LOG.warning(
                "Slow tasks (> %d ms in flight): %s",
                self.config.slow_task_alerting_threshold_ms,
                [t.topic_partition for t in slow[:20]])

    def _poll_inter_broker_progress(self) -> None:
        tm = self._task_manager
        tt = TaskType.INTER_BROKER_REPLICA_ACTION
        in_flight = tm.tracker.tasks_in(tt, TaskState.IN_PROGRESS)
        if not in_flight:
            return
        ongoing = self._admin_call("listPartitionReassignments",
                                   self.admin.list_partition_reassignments)
        alive = self._admin_call("describeCluster",
                                 self.admin.describe_cluster)
        now = self._now_ms()
        cancels: dict[tuple[str, int], None] = {}
        for t in in_flight:
            tp = t.topic_partition
            if tp not in ongoing:
                tm.tracker.transition(t, TaskState.COMPLETED, now)
                self._partition_move_meter.mark()
                continue
            # Dead destination => the copy can never finish (ref
            # ExecutionUtils.maybeMarkTaskAsDead): cancel + DEAD.
            dest_dead = any(not alive.get(b, False)
                            for b in t.proposal.replicas_to_add)
            timed_out = (t.start_time_ms is not None and
                         now - t.start_time_ms
                         > self.config.replica_movement_timeout_ms)
            if dest_dead or timed_out:
                cancels[tp] = None
                tm.tracker.transition(t, TaskState.DEAD, now)
        if cancels:
            self._admin_call("cancelDeadReassignments",
                             self.admin.alter_partition_reassignments,
                             cancels)

    def _run_intra_broker_phase(self, planner, concurrency) -> None:
        """ref intraBrokerMoveReplicas Executor.java:1679 (logdir moves)."""
        tm = self._task_manager
        tt = TaskType.INTRA_BROKER_REPLICA_ACTION
        while (tm.tracker.num_remaining(tt) > 0
               and not self._stop_requested.is_set()):
            self._fence_check()
            if self._stop_requested.is_set():
                break
            pending = tm.tracker.tasks_in(tt, TaskState.PENDING)
            in_progress = tm.tracker.tasks_in(tt, TaskState.IN_PROGRESS)
            batch = planner.intra_broker_batch(pending, in_progress, concurrency)
            if batch:
                moves = {(t.proposal.topic, t.proposal.partition,
                          t.proposal.broker_id): t.proposal.dest_logdir
                         for t in batch}
                errors = self._admin_call(
                    "alterReplicaLogDirs",
                    self.admin.alter_replica_log_dirs, moves)
                now = self._now_ms()
                for t in batch:
                    key = (t.proposal.topic, t.proposal.partition,
                           t.proposal.broker_id)
                    tm.tracker.transition(t, TaskState.IN_PROGRESS, now)
                    if errors.get(key) is not None:
                        tm.tracker.transition(t, TaskState.DEAD, now)
            elif not in_progress:
                break
            self._sleep_ms(self._progress_interval_ms)
            self._watchdog_check()
            dirs = self._admin_call("describeReplicaLogDirs",
                                    self.admin.describe_replica_log_dirs)
            alive = self._admin_call("describeCluster",
                                     self.admin.describe_cluster)
            now = self._now_ms()
            for t in tm.tracker.tasks_in(tt, TaskState.IN_PROGRESS):
                key = (t.proposal.topic, t.proposal.partition,
                       t.proposal.broker_id)
                if dirs.get(key) == t.proposal.dest_logdir:
                    tm.tracker.transition(t, TaskState.COMPLETED, now)
                elif not alive.get(t.proposal.broker_id, False):
                    tm.tracker.transition(t, TaskState.DEAD, now)

    def _run_leadership_phase(self, planner, concurrency,
                              priority_topics: set[str] | None = None
                              ) -> None:
        """ref moveLeaderships Executor.java:1742 -> electLeaders batches.

        ``priority_topics`` (forecast-projected hot topics) front-load:
        their leadership moves fill the earliest batches so projected-hot
        partitions get their leaders pre-positioned before the traffic
        arrives — a stable partition, so equal-priority tasks keep the
        tracker's execution-id order."""
        tm = self._task_manager
        tt = TaskType.LEADER_ACTION
        while (tm.tracker.num_remaining(tt) > 0
               and not self._stop_requested.is_set()):
            self._fence_check()
            if self._stop_requested.is_set():
                break
            pending = tm.tracker.tasks_in(tt, TaskState.PENDING)
            if priority_topics:
                pending = sorted(
                    pending,
                    key=lambda t: (0 if t.proposal.topic in priority_topics
                                   else 1, t.execution_id))
            batch = planner.leadership_batch(pending, concurrency)
            if not batch:
                break
            # Leadership transfer = make the desired broker the preferred
            # replica (a metadata-only reorder reassignment), then elect it
            # (ref ExecutionUtils.java:435 electLeaders; Kafka applies
            # same-set reassignments instantly).
            current = self._admin_call("describePartitions",
                                       self.admin.describe_partitions)
            reorders = {
                t.topic_partition: list(t.proposal.new_replicas)
                for t in batch
                if (info := current.get(t.topic_partition)) is not None
                and info.replicas != list(t.proposal.new_replicas)}
            if reorders:
                self._admin_call("alterPartitionReassignments",
                                 self.admin.alter_partition_reassignments,
                                 reorders)
            errors = self._admin_call(
                "electPreferredLeaders",
                self.admin.elect_preferred_leaders,
                [t.topic_partition for t in batch])
            now = self._now_ms()
            for t in batch:
                tm.tracker.transition(t, TaskState.IN_PROGRESS, now)
                ok = errors.get(t.topic_partition) is None
                tm.tracker.transition(
                    t, TaskState.COMPLETED if ok else TaskState.DEAD, now)
                if ok:
                    self._leadership_move_meter.mark()
            if tm.tracker.num_remaining(tt) > 0:
                self._sleep_ms(self._progress_interval_ms)
                self._watchdog_check()

    # ------------------------------------------------------------ helpers
    def _abort_in_flight(self) -> None:
        """On stop: cancel reassignments and mark tasks aborted (ref
        stopExecution's ABORTING/ABORTED path).

        The cancel RPC rides the teardown retry wrapper: a transient
        AdminTimeoutError mid-cancellation is retried with backoff, and an
        exhausted budget is logged + metered instead of raised — tasks
        transition ABORTING → ABORTED either way, so a flaky admin can't
        strand the tracker (or the reservation) in ABORTING."""
        tm = self._task_manager
        now = self._now_ms()
        cancels = {}
        aborting = []
        for tt in TaskType:
            for t in tm.tracker.tasks_in(tt, TaskState.IN_PROGRESS):
                if tt is TaskType.INTER_BROKER_REPLICA_ACTION:
                    cancels[t.topic_partition] = None
                tm.tracker.transition(t, TaskState.ABORTING, now)
                aborting.append(t)
        if cancels and self._fenced:
            # A FENCED abort issues no cluster-side cancellations: the
            # new leader already owns those partitions and a late cancel
            # from the deposed epoch could kill ITS reassignments — the
            # exact duel fencing exists to prevent. In-flight copies
            # either complete (Kafka keeps streaming) or the new leader
            # manages them; tasks still transition ABORTED locally.
            OPERATION_LOG.warning(
                "Fenced abort: leaving %d in-flight reassignment(s) to "
                "the new leader (no cancellation RPC issued)",
                len(cancels))
        elif cancels:
            self._teardown_call("cancelInFlightReassignments",
                                self.admin.alter_partition_reassignments,
                                cancels)
        now = self._now_ms()
        for t in aborting:
            tm.tracker.transition(t, TaskState.ABORTED, now)

    def _build_strategy_context(self) -> StrategyContext:
        parts = self._admin_call("describePartitions",
                                 self.admin.describe_partitions)
        alive = self._admin_call("describeCluster",
                                 self.admin.describe_cluster)
        urp = {tp for tp, info in parts.items()
               if len(info.isr) < len(info.replicas)}
        offline = {tp for tp, info in parts.items()
                   if any(not alive.get(b, False) for b in info.replicas)}

        min_isr_cache: dict[str, int] = {}

        def min_isr(topic: str) -> int:
            if topic not in min_isr_cache:
                cfg = self.topic_config_provider.topic_configs(topic)
                min_isr_cache[topic] = int(
                    cfg.get("min.insync.replicas", 1))
            return min_isr_cache[topic]

        return StrategyContext(
            partition_size_mb={tp: info.size_mb for tp, info in parts.items()},
            urp=urp,
            min_isr_with_offline={tp for tp in offline
                                  if len(parts[tp].isr) <= min_isr(tp[0])},
            one_above_min_isr_with_offline={
                tp for tp in offline
                if len(parts[tp].isr) == min_isr(tp[0]) + 1})
