"""Real-cluster admin adapter: ``ClusterAdminClient`` over a Kafka admin
wire client.

The reference executor's bottom half drives Kafka through ``AdminClient``
(``ExecutionUtils.java:446`` ``submitReplicaReassignmentTasks`` →
``alterPartitionReassignments``, ``:407`` ``submitPreferredLeaderElection``
→ ``electLeaders``, ``ExecutorAdminUtils`` logdir ops) and classifies
per-partition failures from the returned futures
(``processAlterPartitionReassignmentsResult`` ``ExecutionUtils.java:561``,
``processElectLeadersResult`` ``:611``). This module is the TPU framework's
equivalent: :class:`KafkaAdminClusterClient` implements the
:class:`~cruise_control_tpu.executor.admin.ClusterAdminClient` protocol the
executor consumes, on top of a narrow :class:`KafkaAdminWire` protocol
shaped like ``confluent_kafka.admin.AdminClient`` (methods returning
per-key futures). In production the wire is a ~50-line binding to
confluent-kafka (not bundled in this environment); in tests it is
:class:`MockKafkaAdminWire`, which reproduces broker-side error codes so
the classification logic is contract-tested without a cluster.

Error-code classification parity (reference lines in brackets):

=============================  =============================================
Kafka error                    adapter behavior
=============================  =============================================
INVALID_REPLICA_ASSIGNMENT     reassignment error "dead destination
                               broker(s)" → executor marks the task DEAD
                               [ExecutionUtils.java:574-576]
UNKNOWN_TOPIC_OR_PARTITION     treated as deleted: reassignment/election
                               reports an error mentioning "deleted"
                               [:577-579, :630-633]
NO_REASSIGNMENT_IN_PROGRESS    cancel of a non-ongoing reassignment —
                               success (nothing to cancel) [:580-583]
REQUEST_TIMED_OUT              raises :class:`AdminTimeoutError` — a
                               cluster/controller-side issue, retryable at
                               a higher level [:584-589, :654-658]
ELECTION_NOT_NEEDED            election success (leader already preferred)
                               [:625-627]
PREFERRED_LEADER_NOT_AVAILABLE error (target offline); the executor's
                               dead-task detection handles it [:634-636]
CLUSTER_AUTHORIZATION_FAILED   raises :class:`AdminAuthorizationError`
                               [:659-661]
other                          raises :class:`AdminOperationError`
                               (unexpected — surface loudly) [:590-592]
=============================  =============================================
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Protocol

from .admin import PartitionInfo, ReassignmentInfo


class KafkaWireError(Exception):
    """A broker-side error for one key of an admin request. ``code`` is the
    Kafka protocol error name (``Errors`` enum name in the Java client)."""

    def __init__(self, code: str, message: str = ""):
        super().__init__(f"{code}: {message}" if message else code)
        self.code = code


class AdminTimeoutError(RuntimeError):
    """REQUEST_TIMED_OUT — check broker/controller health, consider raising
    ``admin.client.request.timeout.ms`` (ref ExecutionUtils.java:584)."""


class AdminAuthorizationError(RuntimeError):
    """CLUSTER_AUTHORIZATION_FAILED (ref ExecutionUtils.java:659)."""


class AdminOperationError(RuntimeError):
    """An unclassified broker error (ref ExecutionUtils.java:590)."""


#: The canonical retryable/fatal split for raised admin errors: timeouts
#: are cluster/controller-side transients the shared
#: :class:`~cruise_control_tpu.core.retry.RetryPolicy` may re-attempt;
#: authorization and unclassified operation errors are fatal — retrying
#: them can only repeat the failure (ref ExecutionUtils.java:584 vs :659).
RETRYABLE_ADMIN_ERRORS: tuple = (AdminTimeoutError,)
FATAL_ADMIN_ERRORS: tuple = (AdminAuthorizationError, AdminOperationError)


def consume_injection(code: str, remaining):
    """Advance a ``(code, remaining)`` fault-injection entry one call.

    The one decrement/pop state machine `MockKafkaAdminWire.fail_with`
    and the chaos engine's `admin_burst` schedules share, so the two
    cannot drift on the edge cases: ``remaining=None`` is sustained
    (fires forever), ``remaining<=0`` fires nothing, ``remaining=n``
    fires the next ``n`` calls. Returns ``(fire, next_entry)`` — the
    code to raise for THIS call (or None) and the replacement entry
    (or None when the schedule is spent)."""
    if remaining is None:
        return code, (code, None)
    if remaining <= 0:
        return None, None
    return code, ((code, remaining - 1) if remaining > 1 else None)


class _Future(Protocol):
    def result(self, timeout: float | None = None): ...


class KafkaAdminWire(Protocol):
    """The thin wire surface a production binding must provide — method
    shapes mirror ``confluent_kafka.admin.AdminClient`` so the binding is
    mechanical. Futures resolve to None (or a value) or raise
    :class:`KafkaWireError` with the broker's error code."""

    def describe_cluster(self) -> dict[int, dict]:
        """broker id -> {"host": ..., "rack": ...} for LIVE brokers."""
        ...

    def list_topics(self) -> dict[tuple[str, int], dict]:
        """(topic, partition) -> {"replicas": [...], "leader": int,
        "isr": [...]}."""
        ...

    def alter_partition_reassignments(
            self, targets: dict[tuple[str, int], list[int] | None]
    ) -> dict[tuple[str, int], _Future]: ...

    def list_partition_reassignments(
            self) -> dict[tuple[str, int], dict]:
        """tp -> {"target": [...], "adding": [...], "removing": [...]}."""
        ...

    def elect_leaders(self, tps: list[tuple[str, int]]
                      ) -> dict[tuple[str, int], _Future]: ...

    def describe_log_dirs(self) -> dict[int, dict[str, dict]]:
        """broker -> logdir -> {"replicas": {(topic, part): size_bytes}}."""
        ...

    def alter_replica_log_dirs(
            self, moves: dict[tuple[str, int, int], str]
    ) -> dict[tuple[str, int, int], _Future]: ...

    def describe_configs(self, resource_type: str, name: str
                         ) -> dict[str, str]: ...

    def incremental_alter_configs(
            self, resource_type: str, name: str,
            ops: dict[str, str | None]) -> _Future: ...


class KafkaAdminClusterClient:
    """``ClusterAdminClient`` adapter over a :class:`KafkaAdminWire`.

    Stateless between calls; safe to share across executor phases. Broker
    liveness is metadata-derived (a broker present in describe_cluster is
    live — the reference does the same via ``Cluster.aliveBrokers``), so
    ``known_brokers`` remembers every broker ever seen to report dead ones
    as ``False`` rather than omitting them.
    """

    def __init__(self, wire: KafkaAdminWire,
                 metrics_source=None) -> None:
        self.wire = wire
        #: optional callable broker_id -> {metric: value} feeding the
        #: concurrency adjuster (the reference queries broker JMX through
        #: its metric sampler; a Prometheus-backed source slots in here).
        self.metrics_source = metrics_source
        self.known_brokers: set[int] = set()

    # ------------------------------------------------------------ topology
    def describe_cluster(self) -> dict[int, bool]:
        live = set(self.wire.describe_cluster())
        self.known_brokers |= live
        return {b: (b in live) for b in sorted(self.known_brokers)}

    def describe_partitions(self) -> dict[tuple[str, int], PartitionInfo]:
        # Index the logdir map per partition once: at real-cluster scale
        # (10^5 replica entries) a per-partition rescan would make every
        # executor progress poll and sampling round O(P x replicas).
        by_tp: dict[tuple[str, int], list[tuple[int, str, float]]] = {}
        for (t, p, b), (d, sz) in self._replica_logdirs_and_sizes().items():
            by_tp.setdefault((t, p), []).append((b, d, sz))
        out: dict[tuple[str, int], PartitionInfo] = {}
        for (topic, part), meta in self.wire.list_topics().items():
            entries = by_tp.get((topic, part), [])
            out[(topic, part)] = PartitionInfo(
                topic=topic, partition=part,
                replicas=list(meta["replicas"]),
                leader=int(meta.get("leader", -1)),
                isr=set(meta.get("isr", ())),
                size_mb=max((sz / 1e6 for _b, _d, sz in entries),
                            default=0.0),
                logdirs={b: d for b, d, _sz in entries})
        return out

    # ------------------------------------------------------- reassignments
    def alter_partition_reassignments(
            self, targets: dict[tuple[str, int], list[int] | None]
    ) -> dict[tuple[str, int], str | None]:
        """ref ExecutionUtils.submitReplicaReassignmentTasks (:446) +
        processAlterPartitionReassignmentsResult (:561)."""
        if not targets:
            return {}
        futures = self.wire.alter_partition_reassignments(targets)
        errors: dict[tuple[str, int], str | None] = {}
        for tp, fut in futures.items():
            try:
                fut.result()
                errors[tp] = None
            except KafkaWireError as e:
                errors[tp] = self._classify_reassignment_error(
                    tp, e, cancel=targets.get(tp) is None)
        return errors

    def _classify_reassignment_error(self, tp, e: KafkaWireError,
                                     cancel: bool) -> str | None:
        if e.code == "INVALID_REPLICA_ASSIGNMENT":
            # Dead destination broker(s) — the executor marks the task DEAD
            # (ref :574-576 deadTopicPartitions).
            return "dead destination broker(s): INVALID_REPLICA_ASSIGNMENT"
        if e.code == "UNKNOWN_TOPIC_OR_PARTITION":
            # Topic deleted mid-execution (ref :577-579). A cancel for a
            # deleted partition is a success (nothing left to move).
            return None if cancel else "topic or partition deleted"
        if e.code == "NO_REASSIGNMENT_IN_PROGRESS":
            # Cancelling something that already finished (ref :580-583).
            return None
        if e.code == "REQUEST_TIMED_OUT":
            raise AdminTimeoutError(
                f"alterPartitionReassignments timed out for {tp}; check "
                "broker/controller health and consider increasing "
                "admin.client.request.timeout.ms") from e
        if e.code == "CLUSTER_AUTHORIZATION_FAILED":
            raise AdminAuthorizationError(
                "not authorized to alter partition reassignments") from e
        raise AdminOperationError(
            f"unexpected error for {tp}: {e.code}") from e

    def list_partition_reassignments(
            self) -> dict[tuple[str, int], ReassignmentInfo]:
        return {tp: ReassignmentInfo(target=list(d.get("target", ())),
                                     adding=list(d.get("adding", ())),
                                     removing=list(d.get("removing", ())))
                for tp, d in self.wire.list_partition_reassignments().items()}

    # ----------------------------------------------------------- elections
    def elect_preferred_leaders(self, tps: list[tuple[str, int]]
                                ) -> dict[tuple[str, int], str | None]:
        """ref ExecutionUtils.submitPreferredLeaderElection (:407) +
        processElectLeadersResult (:611)."""
        if not tps:
            return {}
        futures = self.wire.elect_leaders(list(tps))
        errors: dict[tuple[str, int], str | None] = {}
        for tp, fut in futures.items():
            try:
                fut.result()
                errors[tp] = None
            except KafkaWireError as e:
                errors[tp] = self._classify_election_error(tp, e)
        return errors

    def _classify_election_error(self, tp, e: KafkaWireError) -> str | None:
        if e.code == "ELECTION_NOT_NEEDED":
            # Leader is already the preferred replica (ref :625-627).
            return None
        if e.code in ("UNKNOWN_TOPIC_OR_PARTITION", "INVALID_TOPIC_EXCEPTION"):
            return "topic or partition deleted"
        if e.code == "PREFERRED_LEADER_NOT_AVAILABLE":
            # Preferred replica offline (ref :634-636): reported as an
            # error so the executor's dead-task handling reacts; a later
            # run re-elects once the broker returns.
            return "preferred leader not available"
        if e.code == "REQUEST_TIMED_OUT":
            raise AdminTimeoutError(
                f"electLeaders timed out for {tp}; check broker/controller "
                "health and consider increasing "
                "admin.client.request.timeout.ms") from e
        if e.code == "CLUSTER_AUTHORIZATION_FAILED":
            raise AdminAuthorizationError(
                "not authorized to trigger leader election") from e
        # NOT_CONTROLLER etc: the Java client drops the election on
        # controller change; a follow-up execution re-elects (ref :637-641
        # maybeReexecuteLeadershipTasks). Reported, not raised.
        return f"election failed: {e.code}"

    # -------------------------------------------------------------- logdirs
    def _replica_logdirs_and_sizes(
            self) -> dict[tuple[str, int, int], tuple[str, float]]:
        out: dict[tuple[str, int, int], tuple[str, float]] = {}
        for broker, dirs in self.wire.describe_log_dirs().items():
            for logdir, info in dirs.items():
                for (topic, part), size in info.get("replicas", {}).items():
                    out[(topic, part, broker)] = (logdir, float(size))
        return out

    def describe_replica_log_dirs(self) -> dict[tuple[str, int, int], str]:
        return {k: d for k, (d, _sz)
                in self._replica_logdirs_and_sizes().items()}

    def describe_logdirs(self) -> dict[int, list[str]]:
        """All LIVE configured logdirs per broker, incl. empty ones (ref
        AdminClient.describeLogDirs omitting offline dirs)."""
        return {b: sorted(dirs)
                for b, dirs in self.wire.describe_log_dirs().items()}

    def alter_replica_log_dirs(self, moves: dict[tuple[str, int, int], str]
                               ) -> dict[tuple[str, int, int], str | None]:
        if not moves:
            return {}
        futures = self.wire.alter_replica_log_dirs(moves)
        errors: dict[tuple[str, int, int], str | None] = {}
        for key, fut in futures.items():
            try:
                fut.result()
                errors[key] = None
            except KafkaWireError as e:
                if e.code == "REQUEST_TIMED_OUT":
                    raise AdminTimeoutError(
                        f"alterReplicaLogDirs timed out for {key}") from e
                if e.code == "CLUSTER_AUTHORIZATION_FAILED":
                    raise AdminAuthorizationError(
                        "not authorized to alter replica log dirs") from e
                errors[key] = f"logdir move failed: {e.code}"
        return errors

    # -------------------------------------------------------------- configs
    def _config_result(self, what: str, fut: _Future) -> None:
        """Classify config-op failures like every other admin path — the
        throttle helper calls alter_broker_config inside execute_proposals'
        finally block, so a raw wire error would mask the original
        in-flight exception and dodge AdminTimeoutError-based retries."""
        try:
            fut.result()
        except KafkaWireError as e:
            if e.code == "REQUEST_TIMED_OUT":
                raise AdminTimeoutError(f"{what} timed out") from e
            if e.code == "CLUSTER_AUTHORIZATION_FAILED":
                raise AdminAuthorizationError(
                    f"not authorized for {what}") from e
            raise AdminOperationError(f"{what} failed: {e.code}") from e

    def alter_broker_config(self, broker_id: int,
                            config: dict[str, str | None]) -> None:
        self._config_result(
            f"alterConfigs(broker {broker_id})",
            self.wire.incremental_alter_configs(
                "broker", str(broker_id), config))

    def describe_broker_config(self, broker_id: int) -> dict[str, str]:
        return dict(self.wire.describe_configs("broker", str(broker_id)))

    def alter_topic_config(self, topic: str,
                           config: dict[str, str | None]) -> None:
        self._config_result(
            f"alterConfigs(topic {topic})",
            self.wire.incremental_alter_configs("topic", topic, config))

    def describe_topic_config(self, topic: str) -> dict[str, str]:
        return dict(self.wire.describe_configs("topic", topic))

    # -------------------------------------------------------------- metrics
    def broker_metrics(self, broker_id: int) -> dict[str, float]:
        if self.metrics_source is None:
            return {}
        return dict(self.metrics_source(broker_id))


# --------------------------------------------------------------------------
# Mock wire: broker-side behavior for contract tests (and a template for
# what a confluent-kafka binding must surface).
# --------------------------------------------------------------------------

class _ImmediateFuture:
    __slots__ = ("_exc", "_value")

    def __init__(self, value=None, exc: Exception | None = None):
        self._value = value
        self._exc = exc

    def result(self, timeout: float | None = None):
        if self._exc is not None:
            raise self._exc
        return self._value


@dataclass
class MockKafkaAdminWire:
    """In-memory Kafka admin wire with reference broker error semantics:
    unknown topics answer UNKNOWN_TOPIC_OR_PARTITION, reassignments to
    non-live brokers answer INVALID_REPLICA_ASSIGNMENT, cancelling a
    non-ongoing reassignment answers NO_REASSIGNMENT_IN_PROGRESS, electing
    an already-preferred leader answers ELECTION_NOT_NEEDED, and electing
    an offline preferred replica answers PREFERRED_LEADER_NOT_AVAILABLE.
    ``fail_with`` injects arbitrary codes per key for timeout /
    authorization paths: a bare code string is one-shot (popped on use);
    a ``(code, n)`` tuple fails the next ``n`` calls touching the key; a
    ``(code, None)`` tuple fails every call until cleared — the sustained
    form chaos schedules use."""

    brokers: dict[int, dict] = field(default_factory=dict)
    #: (topic, partition) -> {"replicas": [...], "leader": int, "isr": [...]}
    partitions: dict[tuple[str, int], dict] = field(default_factory=dict)
    logdirs: dict[int, dict[str, dict]] = field(default_factory=dict)
    configs: dict[tuple[str, str], dict] = field(default_factory=dict)
    ongoing: dict[tuple[str, int], dict] = field(default_factory=dict)
    #: injected error codes: key -> code (one-shot) | (code, n) | (code,
    #: None) — see the class docstring
    fail_with: dict = field(default_factory=dict)

    def _injected(self, key):
        entry = self.fail_with.get(key)
        if entry is None:
            return None
        if isinstance(entry, str):
            self.fail_with.pop(key)
            return KafkaWireError(entry)
        fire, nxt = consume_injection(*entry)
        if nxt is None:
            self.fail_with.pop(key)
        else:
            self.fail_with[key] = nxt
        return KafkaWireError(fire) if fire else None

    def describe_cluster(self) -> dict[int, dict]:
        return dict(self.brokers)

    def list_topics(self) -> dict[tuple[str, int], dict]:
        return {tp: dict(meta) for tp, meta in self.partitions.items()}

    def alter_partition_reassignments(self, targets):
        futures = {}
        for tp, target in targets.items():
            exc = self._injected(tp)
            if exc is not None:
                futures[tp] = _ImmediateFuture(exc=exc)
            elif tp not in self.partitions:
                futures[tp] = _ImmediateFuture(
                    exc=KafkaWireError("UNKNOWN_TOPIC_OR_PARTITION"))
            elif target is None:
                if tp in self.ongoing:
                    del self.ongoing[tp]
                    futures[tp] = _ImmediateFuture()
                else:
                    futures[tp] = _ImmediateFuture(
                        exc=KafkaWireError("NO_REASSIGNMENT_IN_PROGRESS"))
            elif any(b not in self.brokers for b in target):
                futures[tp] = _ImmediateFuture(
                    exc=KafkaWireError("INVALID_REPLICA_ASSIGNMENT"))
            else:
                current = self.partitions[tp]["replicas"]
                if set(target) == set(current):
                    # Same-set reorder: metadata-only, Kafka applies it
                    # instantly (no data copy, nothing to list as ongoing).
                    self.partitions[tp]["replicas"] = list(target)
                else:
                    self.ongoing[tp] = {
                        "target": list(target),
                        "adding": [b for b in target if b not in current],
                        "removing": [b for b in current if b not in target]}
                futures[tp] = _ImmediateFuture()
        return futures

    def complete_reassignment(self, tp) -> None:
        """Test hook: finish an in-flight reassignment broker-side."""
        info = self.ongoing.pop(tp)
        meta = self.partitions[tp]
        meta["replicas"] = list(info["target"])
        meta["isr"] = list(info["target"])

    def list_partition_reassignments(self):
        return {tp: dict(d) for tp, d in self.ongoing.items()}

    def elect_leaders(self, tps):
        futures = {}
        for tp in tps:
            exc = self._injected(tp)
            if exc is not None:
                futures[tp] = _ImmediateFuture(exc=exc)
                continue
            meta = self.partitions.get(tp)
            if meta is None:
                futures[tp] = _ImmediateFuture(
                    exc=KafkaWireError("UNKNOWN_TOPIC_OR_PARTITION"))
                continue
            preferred = meta["replicas"][0]
            if meta.get("leader") == preferred:
                futures[tp] = _ImmediateFuture(
                    exc=KafkaWireError("ELECTION_NOT_NEEDED"))
            elif preferred not in self.brokers:
                futures[tp] = _ImmediateFuture(
                    exc=KafkaWireError("PREFERRED_LEADER_NOT_AVAILABLE"))
            else:
                meta["leader"] = preferred
                futures[tp] = _ImmediateFuture()
        return futures

    def describe_log_dirs(self):
        return {b: {d: {"replicas": dict(info.get("replicas", {}))}
                    for d, info in dirs.items()}
                for b, dirs in self.logdirs.items()}

    def alter_replica_log_dirs(self, moves):
        futures = {}
        for (topic, part, broker), dest in moves.items():
            exc = self._injected((topic, part, broker))
            if exc is not None:
                futures[(topic, part, broker)] = _ImmediateFuture(exc=exc)
                continue
            dirs = self.logdirs.get(broker, {})
            if dest not in dirs:
                futures[(topic, part, broker)] = _ImmediateFuture(
                    exc=KafkaWireError("LOG_DIR_NOT_FOUND"))
                continue
            for d, info in dirs.items():
                size = info.get("replicas", {}).pop((topic, part), None)
                if size is not None:
                    dirs[dest].setdefault("replicas", {})[(topic, part)] = size
            futures[(topic, part, broker)] = _ImmediateFuture()
        return futures

    def describe_configs(self, resource_type, name):
        return dict(self.configs.get((resource_type, name), {}))

    def incremental_alter_configs(self, resource_type, name, ops):
        cfg = self.configs.setdefault((resource_type, name), {})
        for k, v in ops.items():
            if v is None:
                cfg.pop(k, None)
            else:
                cfg[k] = v
        return _ImmediateFuture()
