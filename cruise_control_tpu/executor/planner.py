"""Execution task planner (ref ``executor/ExecutionTaskPlanner.java``).

Hands the executor per-round batches of movement tasks honoring per-broker
and cluster concurrency caps, in the order of the configured movement
strategy chain (``getInterBrokerReplicaMovementTasks``
``ExecutionTaskPlanner.java:348``, ``getLeadershipMovementTasks`` ``:302``).
"""

from __future__ import annotations

from .concurrency import ExecutionConcurrencyManager
from .strategy import ReplicaMovementStrategy, StrategyContext, strategy_chain
from .tasks import ExecutionTask, TaskType


class ExecutionTaskPlanner:
    def __init__(self, strategy: ReplicaMovementStrategy | None = None):
        self.strategy = strategy or strategy_chain(None)
        self._ordered: list[ExecutionTask] | None = None

    def sort_key(self, task: ExecutionTask, ctx: StrategyContext):
        """Total-order sort key: the strategy chain's key first, then an
        explicit typed tie-break. Chains built by ``strategy_chain`` end
        in execution-id order, but a caller-supplied bare strategy can
        tie — and Python's stable sort would then fall back to the
        *insertion order* of the list being sorted, which differs across
        processes (tracker iteration after a restore, a replayed plan).
        The device scheduler and the host batcher must order identically
        in every process, so equal strategy keys break on
        ``(task_type, execution_id)`` — typed values, no ``id()`` or
        insertion-order dependence."""
        return (self.strategy.key(task, ctx), task.task_type.value,
                task.execution_id)

    def begin_phase(self, tasks: list[ExecutionTask],
                    ctx: StrategyContext | None = None) -> None:
        """Sort the phase's tasks by the strategy chain ONCE (ref
        ``ExecutionTaskPlanner.addExecutionProposals`` sorting into a
        TreeSet at plan time): at LinkedIn scale a rebalance carries
        ~500K movement tasks, and re-evaluating the Python strategy key
        inside a per-round sort (thousands of rounds per execution) is
        hours of pure ordering overhead. Per-round batch calls then walk
        this order, filtering by live task state — O(N) with no key
        calls."""
        ctx = ctx or StrategyContext()
        self._ordered = sorted(tasks,
                               key=lambda t: self.sort_key(t, ctx))

    def _in_order(self, pending: list[ExecutionTask],
                  ctx: StrategyContext) -> list[ExecutionTask]:
        if self._ordered is None:
            return sorted(pending, key=lambda t: self.sort_key(t, ctx))
        live = {id(t) for t in pending}
        if len(self._ordered) == len(pending):
            # Cheap identity check before trusting the cached order:
            # equal length alone would silently return stale tasks for a
            # caller passing a same-length but different list.
            if all(id(t) in live for t in self._ordered):
                return self._ordered
        covered = [t for t in self._ordered if id(t) in live]
        if len(covered) == len(pending):
            return covered
        # Pending tasks the cached phase order has never seen (caller
        # skipped begin_phase for them): the cache can't order what it
        # doesn't contain — sort the actual list rather than silently
        # dropping the uncovered tasks from every batch.
        return sorted(pending, key=lambda t: self.sort_key(t, ctx))

    def inter_broker_batch(self, pending: list[ExecutionTask],
                           in_progress: list[ExecutionTask],
                           concurrency: ExecutionConcurrencyManager,
                           ctx: StrategyContext | None = None
                           ) -> list[ExecutionTask]:
        """Next batch of inter-broker movements.

        A movement occupies a slot on every broker it adds a replica to AND
        every broker it removes one from (ref
        ``ExecutionTaskPlanner.java:348-420`` tracking both sides' in-progress
        counts); the cluster-wide cap bounds total concurrent movements.
        """
        ctx = ctx or StrategyContext()
        slots: dict[int, int] = {}
        for t in in_progress:
            for b in (*t.proposal.replicas_to_add, *t.proposal.replicas_to_remove):
                slots[b] = slots.get(b, 0) + 1
        budget = concurrency.cluster_movement_cap - len(in_progress)
        batch: list[ExecutionTask] = []
        for task in self._in_order(pending, ctx):
            if budget <= 0:
                break
            brokers = (*task.proposal.replicas_to_add,
                       *task.proposal.replicas_to_remove)
            if any(slots.get(b, 0) >= concurrency.inter_broker_cap(b)
                   for b in brokers):
                continue
            for b in brokers:
                slots[b] = slots.get(b, 0) + 1
            batch.append(task)
            budget -= 1
        return batch

    def leadership_batch(self, pending: list[ExecutionTask],
                         concurrency: ExecutionConcurrencyManager
                         ) -> list[ExecutionTask]:
        """Next batch of leadership movements: cluster cap plus a per-broker
        cap on the broker *gaining* leadership (ref
        ``ExecutionTaskPlanner.java:302-340``)."""
        cap = concurrency.leadership_cluster_cap
        per_broker: dict[int, int] = {}
        batch: list[ExecutionTask] = []
        for task in pending:
            if len(batch) >= cap:
                break
            leader = task.proposal.new_leader
            if per_broker.get(leader, 0) >= concurrency.leadership_broker_cap:
                continue
            per_broker[leader] = per_broker.get(leader, 0) + 1
            batch.append(task)
        return batch

    def intra_broker_batch(self, pending: list[ExecutionTask],
                           in_progress: list[ExecutionTask],
                           concurrency: ExecutionConcurrencyManager
                           ) -> list[ExecutionTask]:
        """Next batch of intra-broker (disk) movements: per-broker cap on
        concurrent logdir copies (ref ExecutionTaskPlanner's intra path)."""
        slots: dict[int, int] = {}
        for t in in_progress:
            b = t.proposal.broker_id
            slots[b] = slots.get(b, 0) + 1
        batch: list[ExecutionTask] = []
        for task in pending:
            b = task.proposal.broker_id
            if slots.get(b, 0) >= concurrency.intra_broker_cap:
                continue
            slots[b] = slots.get(b, 0) + 1
            batch.append(task)
        return batch
