"""Replica movement strategies (ref ``executor/strategy/*.java``).

A strategy orders the pending inter-broker movement tasks the planner hands
out each round. Strategies chain (ref
``AbstractReplicaMovementStrategy.chain``): the first strategy is the
primary sort key, ties fall through to the next, and every chain ends with
:class:`BaseReplicaMovementStrategy` (execution-id order) so the total order
is deterministic.

Instead of the reference's comparator objects, a strategy here is a *sort
key function* ``(task, context) -> value``; chaining is tuple composition —
the natural Python shape for the same semantics.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Sequence

from .tasks import ExecutionTask


@dataclass
class StrategyContext:
    """Cluster facts strategies may consult (ref strategies receive a
    ``StrategyOptions`` with partition sizes / URP / min-ISR info)."""

    #: (topic, partition) -> data size in MB (disk load of the partition)
    partition_size_mb: dict[tuple[str, int], float] = field(default_factory=dict)
    #: partitions currently under-replicated
    urp: set[tuple[str, int]] = field(default_factory=set)
    #: partitions at/below min-ISR with an offline replica
    min_isr_with_offline: set[tuple[str, int]] = field(default_factory=set)
    #: partitions one above min-ISR with an offline replica
    one_above_min_isr_with_offline: set[tuple[str, int]] = field(default_factory=set)


class ReplicaMovementStrategy:
    """SPI (ref ReplicaMovementStrategy.java)."""

    name = "ReplicaMovementStrategy"

    def key(self, task: ExecutionTask, ctx: StrategyContext):
        """Sort key component; lower sorts earlier."""
        raise NotImplementedError

    def chain(self, nxt: "ReplicaMovementStrategy") -> "ChainedStrategy":
        return ChainedStrategy([self, nxt])


class ChainedStrategy(ReplicaMovementStrategy):
    def __init__(self, strategies: Sequence[ReplicaMovementStrategy]):
        flat: list[ReplicaMovementStrategy] = []
        for s in strategies:
            flat.extend(s.strategies if isinstance(s, ChainedStrategy) else [s])
        self.strategies = flat
        self.name = "+".join(s.name for s in flat)

    def key(self, task: ExecutionTask, ctx: StrategyContext):
        return tuple(s.key(task, ctx) for s in self.strategies)

    def chain(self, nxt: ReplicaMovementStrategy) -> "ChainedStrategy":
        return ChainedStrategy([*self.strategies, nxt])


class BaseReplicaMovementStrategy(ReplicaMovementStrategy):
    """Execution-id (proposal) order (ref BaseReplicaMovementStrategy.java)."""

    name = "BaseReplicaMovementStrategy"

    def key(self, task, ctx):
        return task.execution_id


class PrioritizeSmallReplicaMovementStrategy(ReplicaMovementStrategy):
    """Small partitions first — quick wins drain the queue fast (ref
    PrioritizeSmallReplicaMovementStrategy.java)."""

    name = "PrioritizeSmallReplicaMovementStrategy"

    def key(self, task, ctx):
        return ctx.partition_size_mb.get(task.topic_partition, 0.0)


class PrioritizeLargeReplicaMovementStrategy(ReplicaMovementStrategy):
    """Large partitions first — start the long poles early (ref
    PrioritizeLargeReplicaMovementStrategy.java)."""

    name = "PrioritizeLargeReplicaMovementStrategy"

    def key(self, task, ctx):
        return -ctx.partition_size_mb.get(task.topic_partition, 0.0)


class PostponeUrpReplicaMovementStrategy(ReplicaMovementStrategy):
    """Move healthy (non-under-replicated) partitions first (ref
    PostponeUrpReplicaMovementStrategy.java)."""

    name = "PostponeUrpReplicaMovementStrategy"

    def key(self, task, ctx):
        return 1 if task.topic_partition in ctx.urp else 0


class PrioritizeMinIsrWithOfflineReplicasStrategy(ReplicaMovementStrategy):
    """(At/under)-min-ISR partitions with offline replicas first: these are
    one failure from unavailability (ref
    PrioritizeMinIsrWithOfflineReplicasStrategy.java)."""

    name = "PrioritizeMinIsrWithOfflineReplicasStrategy"

    def key(self, task, ctx):
        return 0 if task.topic_partition in ctx.min_isr_with_offline else 1


class PrioritizeOneAboveMinIsrWithOfflineReplicasStrategy(ReplicaMovementStrategy):
    """Partitions exactly one above min-ISR with offline replicas next (ref
    PrioritizeOneAboveMinIsrWithOfflineReplicasStrategy.java)."""

    name = "PrioritizeOneAboveMinIsrWithOfflineReplicasStrategy"

    def key(self, task, ctx):
        return 0 if task.topic_partition in ctx.one_above_min_isr_with_offline else 1


STRATEGY_REGISTRY: dict[str, Callable[[], ReplicaMovementStrategy]] = {
    cls.name: cls for cls in (
        BaseReplicaMovementStrategy,
        PrioritizeSmallReplicaMovementStrategy,
        PrioritizeLargeReplicaMovementStrategy,
        PostponeUrpReplicaMovementStrategy,
        PrioritizeMinIsrWithOfflineReplicasStrategy,
        PrioritizeOneAboveMinIsrWithOfflineReplicasStrategy,
    )
}


def strategy_chain(names: Sequence[str] | None) -> ReplicaMovementStrategy:
    """Build a chained strategy from config names, always terminated by the
    base strategy (ref default.replica.movement.strategies resolution)."""
    strategies = [STRATEGY_REGISTRY[n]() for n in (names or [])]
    strategies.append(BaseReplicaMovementStrategy())
    return ChainedStrategy(strategies)
