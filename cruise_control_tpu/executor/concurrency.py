"""Execution concurrency control (ref ``ExecutionConcurrencyManager.java``,
``ConcurrencyType.java``, and the ``ConcurrencyAdjuster`` inner class of
``Executor.java:493-644``).

Per-broker and cluster-wide caps bound how many movements run at once; the
adjuster is the feedback controller that scales the caps from live broker
health (additive increase on healthy polls, multiplicative decrease when a
broker looks stressed or partitions sit (at/under) min-ISR).
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field


class ConcurrencyType(enum.Enum):
    """ref ConcurrencyType.java."""

    INTER_BROKER_REPLICA = "INTER_BROKER_REPLICA"
    INTRA_BROKER_REPLICA = "INTRA_BROKER_REPLICA"
    LEADERSHIP_CLUSTER = "LEADERSHIP_CLUSTER"
    LEADERSHIP_BROKER = "LEADERSHIP_BROKER"


@dataclass
class ConcurrencyConfig:
    """Defaults mirror ExecutorConfig (ref config/constants/ExecutorConfig:
    num.concurrent.partition.movements.per.broker=5,
    num.concurrent.intra.broker.partition.movements=2,
    num.concurrent.leader.movements=1000,
    max.num.cluster.[partition.]movements caps, and the adjuster's
    min/max bounds)."""

    num_concurrent_partition_movements_per_broker: int = 5
    num_concurrent_intra_broker_partition_movements: int = 2
    num_concurrent_leader_movements: int = 1000
    num_concurrent_leader_movements_per_broker: int = 1000
    max_num_cluster_partition_movements: int = 1250
    # Adjuster bounds (ref min/max.num.concurrency config keys).
    min_partition_movements_per_broker: int = 1
    max_partition_movements_per_broker: int = 12
    min_leader_movements: int = 100
    max_leader_movements: int = 1000
    # Broker-health thresholds the adjuster reacts to (ref
    # concurrency.adjuster.* configs: request-queue size, log-flush time...).
    limit_request_queue_size: float = 1000.0
    limit_log_flush_time_ms: float = 1000.0
    limit_produce_local_time_ms: float = 1000.0


class ExecutionConcurrencyManager:
    """Tracks current caps, per broker and cluster-wide (ref
    ExecutionConcurrencyManager.java). Thread-safe: the adjuster thread
    writes while the planner reads."""

    def __init__(self, config: ConcurrencyConfig | None = None,
                 broker_ids: list[int] | None = None) -> None:
        self.config = config or ConcurrencyConfig()
        self._lock = threading.RLock()
        c = self.config
        self._inter_per_broker: dict[int, int] = {
            b: c.num_concurrent_partition_movements_per_broker
            for b in (broker_ids or [])}
        self._default_inter = c.num_concurrent_partition_movements_per_broker
        self._intra = c.num_concurrent_intra_broker_partition_movements
        self._leadership_cluster = c.num_concurrent_leader_movements
        self._leadership_broker = c.num_concurrent_leader_movements_per_broker

    # ----------------------------------------------------------- reads
    def inter_broker_cap(self, broker_id: int) -> int:
        with self._lock:
            return self._inter_per_broker.get(broker_id, self._default_inter)

    @property
    def intra_broker_cap(self) -> int:
        return self._intra

    @property
    def leadership_cluster_cap(self) -> int:
        with self._lock:
            return self._leadership_cluster

    @property
    def leadership_broker_cap(self) -> int:
        with self._lock:
            return self._leadership_broker

    @property
    def cluster_movement_cap(self) -> int:
        return self.config.max_num_cluster_partition_movements

    # ----------------------------------------------------------- writes
    def set_inter_broker_cap(self, broker_id: int, cap: int) -> None:
        c = self.config
        with self._lock:
            self._inter_per_broker[broker_id] = max(
                c.min_partition_movements_per_broker,
                min(cap, c.max_partition_movements_per_broker))

    def set_cluster_leadership_cap(self, cap: int) -> None:
        c = self.config
        with self._lock:
            self._leadership_cluster = max(c.min_leader_movements,
                                           min(cap, c.max_leader_movements))

    def summary(self) -> dict:
        with self._lock:
            return {
                "interBrokerPerBroker": dict(self._inter_per_broker),
                "defaultInterBroker": self._default_inter,
                "intraBroker": self._intra,
                "leadershipCluster": self._leadership_cluster,
                "leadershipBroker": self._leadership_broker,
            }


#: adjuster-controllable concurrency types (ref
#: (DISABLE|ENABLE)_CONCURRENCY_ADJUSTER_FOR_PARAM value set)
VALID_ADJUSTER_TYPES = frozenset({"inter_broker_replica", "leadership"})


class ConcurrencyAdjuster:
    """Auto-scales movement concurrency from broker health metrics (ref
    ``Executor.ConcurrencyAdjuster`` ``Executor.java:493-644``).

    Call :meth:`refresh` once per progress-check cycle with the latest
    per-broker metrics (request-queue size, log-flush time) and the set of
    (at/under) min-ISR partitions; it applies AIMD per broker:

    - any stress signal -> halve that broker's cap (multiplicative decrease);
    - cluster-wide (at/under)-min-ISR partitions -> halve every cap
      (ref ``:560-584`` min-ISR based adjustment);
    - otherwise -> +1 (additive increase) up to the configured max.
    """

    def __init__(self, manager: ExecutionConcurrencyManager) -> None:
        self.manager = manager
        #: concurrency types the adjuster must leave alone (ref
        #: (DISABLE|ENABLE)_CONCURRENCY_ADJUSTER_FOR_PARAM; values from
        #: {"inter_broker_replica", "leadership"}).
        self.disabled_types: set[str] = set()

    def set_enabled_for(self, concurrency_type: str, enabled: bool) -> None:
        key = concurrency_type.strip().lower()
        if key not in VALID_ADJUSTER_TYPES:
            raise ValueError(
                f"unknown concurrency type {concurrency_type!r} "
                f"(want one of {sorted(VALID_ADJUSTER_TYPES)})")
        (self.disabled_types.discard if enabled
         else self.disabled_types.add)(key)

    def refresh(self, broker_metrics: dict[int, dict[str, float]],
                num_min_isr_partitions: int = 0) -> dict[int, int]:
        cfg = self.manager.config
        new_caps: dict[int, int] = {}
        cluster_stressed = num_min_isr_partitions > 0
        if "inter_broker_replica" not in self.disabled_types:
            for broker_id, metrics in broker_metrics.items():
                cap = self.manager.inter_broker_cap(broker_id)
                stressed = (
                    cluster_stressed
                    or metrics.get("request_queue_size", 0.0)
                    > cfg.limit_request_queue_size
                    or metrics.get("log_flush_time_ms", 0.0)
                    > cfg.limit_log_flush_time_ms
                    or metrics.get("produce_local_time_ms", 0.0)
                    > cfg.limit_produce_local_time_ms)
                cap = max(cfg.min_partition_movements_per_broker, cap // 2) \
                    if stressed else cap + 1
                self.manager.set_inter_broker_cap(broker_id, cap)
                new_caps[broker_id] = self.manager.inter_broker_cap(broker_id)
        # Leadership cap follows the same cluster-level signal (ref :614-onw).
        if "leadership" not in self.disabled_types:
            lead = self.manager.leadership_cluster_cap
            self.manager.set_cluster_leadership_cap(
                max(cfg.min_leader_movements, lead // 2) if cluster_stressed
                else lead + max(1, lead // 10))
        return new_caps
