"""In-process simulated Kafka cluster implementing the admin SPI.

The reference tests its executor against embedded in-JVM Kafka brokers
(``CCKafkaIntegrationTestHarness`` / ``CCEmbeddedBroker``); this is the
equivalent test double for a Python control plane: a deterministic,
clock-driven cluster model with bandwidth-limited reassignment progress,
broker death, ISR tracking, preferred-leader election, logdir moves, and
dynamic configs (throttles). The executor is exercised end-to-end against
it with zero wall-clock sleeps — time advances only via :meth:`advance_to`.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .admin import PartitionInfo, ReassignmentInfo

#: Dynamic config keys (same names Kafka uses; ref
#: ReplicationThrottleHelper.java LEADER_THROTTLED_RATE etc.)
LEADER_THROTTLED_RATE = "leader.replication.throttled.rate"
FOLLOWER_THROTTLED_RATE = "follower.replication.throttled.rate"
LEADER_THROTTLED_REPLICAS = "leader.replication.throttled.replicas"
FOLLOWER_THROTTLED_REPLICAS = "follower.replication.throttled.replicas"


@dataclass
class _Copy:
    """One replica copy in flight: partition data streaming to a broker
    (inter-broker reassignment) or between logdirs (intra-broker)."""

    tp: tuple[str, int]
    dest_broker: int
    remaining_mb: float
    intra_target_logdir: str | None = None


@dataclass
class _BrokerSim:
    broker_id: int
    alive: bool = True
    #: replication bandwidth available for incoming copies, MB/s
    reassignment_rate_mb_s: float = 100.0
    logdirs: tuple[str, ...] = ("logdir0",)
    failed_logdirs: set[str] = field(default_factory=set)
    config: dict[str, str] = field(default_factory=dict)
    metrics: dict[str, float] = field(default_factory=dict)


class SimulatedKafkaCluster:
    """Deterministic cluster sim behind :class:`ClusterAdminClient`."""

    def __init__(self, now_ms: int = 0) -> None:
        self._now_ms = now_ms
        self._brokers: dict[int, _BrokerSim] = {}
        self._partitions: dict[tuple[str, int], PartitionInfo] = {}
        self._topic_configs: dict[str, dict[str, str]] = {}
        self._reassign: dict[tuple[str, int], list[int]] = {}   # tp -> target
        self._copies: list[_Copy] = []
        self.num_reassignment_batches = 0
        self.num_leader_elections = 0

    # ------------------------------------------------------------- build
    def add_broker(self, broker_id: int, *, rate_mb_s: float = 100.0,
                   logdirs: tuple[str, ...] = ("logdir0",)) -> None:
        self._brokers[broker_id] = _BrokerSim(broker_id,
                                              reassignment_rate_mb_s=rate_mb_s,
                                              logdirs=logdirs)

    def add_partition(self, topic: str, partition: int, replicas: list[int],
                      size_mb: float = 100.0,
                      logdir_by_broker: dict[int, str] | None = None) -> None:
        info = PartitionInfo(topic=topic, partition=partition,
                             replicas=list(replicas), leader=replicas[0],
                             isr=set(replicas), size_mb=size_mb)
        for b in replicas:
            info.logdirs[b] = (logdir_by_broker or {}).get(
                b, self._brokers[b].logdirs[0])
        self._partitions[(topic, partition)] = info

    @classmethod
    def from_spec(cls, spec, *, rate_mb_s: float = 100.0,
                  now_ms: int = 0) -> "SimulatedKafkaCluster":
        """Build from a :class:`~cruise_control_tpu.model.spec.ClusterSpec`
        (partition size = DISK load, matching the model's units)."""
        from ..core.resources import Resource
        sim = cls(now_ms=now_ms)
        for b in spec.brokers:
            sim.add_broker(b.broker_id, rate_mb_s=rate_mb_s)
            if not b.alive:
                sim.kill_broker(b.broker_id)
        for p in spec.partitions:
            sim.add_partition(p.topic, p.partition, list(p.replicas),
                              size_mb=float(p.leader_load[Resource.DISK]))
        return sim

    def _elect_leader(self, info: PartitionInfo) -> None:
        """ISR-based re-election when the leader is lost (one rule, used by
        broker death, logdir failure, and reassignment finalization)."""
        alive_isr = [b for b in info.replicas
                     if b in info.isr and self._brokers[b].alive]
        info.leader = alive_isr[0] if alive_isr else -1

    # ------------------------------------------------------------ faults
    def kill_broker(self, broker_id: int) -> None:
        self._brokers[broker_id].alive = False
        for info in self._partitions.values():
            info.isr.discard(broker_id)
            if info.leader == broker_id:
                self._elect_leader(info)

    def fail_logdir(self, broker_id: int, logdir: str) -> None:
        """A disk dies: replicas on that logdir go offline (ref the
        offline-logdir state DiskFailureDetector scans for)."""
        broker = self._brokers[broker_id]
        broker.failed_logdirs.add(logdir)
        for info in self._partitions.values():
            if info.logdirs.get(broker_id) == logdir:
                info.isr.discard(broker_id)
                if info.leader == broker_id:
                    self._elect_leader(info)

    def offline_logdirs(self) -> dict[int, list[str]]:
        return {b.broker_id: sorted(b.failed_logdirs)
                for b in self._brokers.values() if b.failed_logdirs}

    def describe_logdirs(self) -> dict[int, list[str]]:
        """All LIVE configured logdirs per broker, including empty ones
        (ref AdminClient.describeLogDirs, which omits offline dirs) —
        empty disks are valid drain destinations the replica placement
        alone can't reveal; failed ones are not."""
        return {b.broker_id: sorted(set(b.logdirs) - b.failed_logdirs)
                for b in self._brokers.values()}

    def offline_replicas(self) -> set[tuple[str, int, int]]:
        """Replicas currently offline: hosted on a dead broker or a failed
        logdir (feeds the monitor's per-replica offline marks)."""
        out: set[tuple[str, int, int]] = set()
        for (t, p), info in self._partitions.items():
            for b in info.replicas:
                broker = self._brokers[b]
                if (not broker.alive
                        or info.logdirs.get(b) in broker.failed_logdirs):
                    out.add((t, p, b))
        return out

    def create_partitions(self, topic: str, additional: int,
                          rf: int = 2, size_mb: float = 0.0) -> None:
        """Expand a topic (ref PartitionProvisioner's actuation path)."""
        existing = [p for (t, p) in self._partitions if t == topic]
        next_id = max(existing, default=-1) + 1
        alive = sorted(b.broker_id for b in self._brokers.values() if b.alive)
        if not alive:
            raise RuntimeError("no alive brokers to place partitions on")
        rf = min(rf, len(alive))   # replica lists must be duplicate-free
        for i in range(additional):
            offset = (next_id + i) % len(alive)
            replicas = [alive[(offset + j) % len(alive)] for j in range(rf)]
            self.add_partition(topic, next_id + i, replicas, size_mb=size_mb)

    def restart_broker(self, broker_id: int) -> None:
        self._brokers[broker_id].alive = True
        for info in self._partitions.values():
            if broker_id in info.replicas:
                info.isr.add(broker_id)
                if info.leader == -1:
                    info.leader = broker_id

    # -------------------------------------------------------------- time
    @property
    def now_ms(self) -> int:
        return self._now_ms

    def advance_to(self, now_ms: int) -> None:
        """Progress in-flight copies with per-broker fair-shared bandwidth,
        bounded by the follower throttle when set."""
        dt_s = max(0, now_ms - self._now_ms) / 1000.0
        self._now_ms = now_ms
        if dt_s == 0 or not self._copies:
            return
        by_dest: dict[int, list[_Copy]] = {}
        for c in self._copies:
            by_dest.setdefault(c.dest_broker, []).append(c)
        for broker_id, copies in by_dest.items():
            broker = self._brokers[broker_id]
            if not broker.alive:
                continue  # stalled
            rate = broker.reassignment_rate_mb_s
            throttle = broker.config.get(FOLLOWER_THROTTLED_RATE)
            if throttle is not None:
                # Kafka throttle configs are bytes/s.
                rate = min(rate, float(throttle) / 1e6)
            share = rate / len(copies) * dt_s
            for c in copies:
                c.remaining_mb -= share
        finished = [c for c in self._copies if c.remaining_mb <= 0]
        self._copies = [c for c in self._copies if c.remaining_mb > 0]
        for c in finished:
            self._finish_copy(c)

    def _healthy_logdir(self, broker_id: int) -> str:
        broker = self._brokers[broker_id]
        for d in broker.logdirs:
            if d not in broker.failed_logdirs:
                return d
        return broker.logdirs[0]

    def _finish_copy(self, c: _Copy) -> None:
        info = self._partitions[c.tp]
        if c.intra_target_logdir is not None:
            info.logdirs[c.dest_broker] = c.intra_target_logdir
            return
        info.isr.add(c.dest_broker)
        info.logdirs.setdefault(c.dest_broker,
                                self._healthy_logdir(c.dest_broker))
        target = self._reassign.get(c.tp)
        # Reassignment completes when every adding replica is in ISR.
        if target is not None and all(b in info.isr for b in target):
            self._finalize_reassignment(c.tp)

    def _finalize_reassignment(self, tp: tuple[str, int]) -> None:
        info = self._partitions[tp]
        target = self._reassign.pop(tp)
        removed = [b for b in info.replicas if b not in target]
        info.replicas = list(target)
        info.isr = {b for b in info.replicas if self._brokers[b].alive}
        for b in removed:
            info.logdirs.pop(b, None)
        for b in info.replicas:
            info.logdirs.setdefault(b, self._healthy_logdir(b))
        if info.leader not in target or not self._brokers[info.leader].alive:
            self._elect_leader(info)

    # --------------------------------------------------- admin SPI (reads)
    def describe_cluster(self) -> dict[int, bool]:
        return {b.broker_id: b.alive for b in self._brokers.values()}

    def describe_partitions(self) -> dict[tuple[str, int], PartitionInfo]:
        return dict(self._partitions)

    def list_partition_reassignments(self) -> dict[tuple[str, int], ReassignmentInfo]:
        out = {}
        for tp, target in self._reassign.items():
            info = self._partitions[tp]
            out[tp] = ReassignmentInfo(
                target=list(target),
                adding=[b for b in target if b not in info.replicas],
                removing=[b for b in info.replicas if b not in target])
        return out

    def describe_replica_log_dirs(self) -> dict[tuple[str, int, int], str]:
        return {(t, p, b): d
                for (t, p), info in self._partitions.items()
                for b, d in info.logdirs.items()}

    def broker_metrics(self, broker_id: int) -> dict[str, float]:
        b = self._brokers[broker_id]
        inflight = sum(1 for c in self._copies if c.dest_broker == broker_id)
        metrics = {"request_queue_size": 10.0 * inflight,
                   "log_flush_time_ms": 5.0 * inflight}
        metrics.update(b.metrics)  # test-injected overrides win
        return metrics

    # -------------------------------------------------- admin SPI (writes)
    def alter_partition_reassignments(
            self, targets: dict[tuple[str, int], list[int] | None]
    ) -> dict[tuple[str, int], str | None]:
        self.num_reassignment_batches += 1
        results: dict[tuple[str, int], str | None] = {}
        for tp, target in targets.items():
            info = self._partitions.get(tp)
            if info is None:
                results[tp] = "UNKNOWN_TOPIC_OR_PARTITION"
                continue
            if target is None:  # cancellation
                if tp in self._reassign:
                    del self._reassign[tp]
                    self._copies = [c for c in self._copies if c.tp != tp]
                    results[tp] = None
                else:
                    results[tp] = "NO_REASSIGNMENT_IN_PROGRESS"
                continue
            if any(b not in self._brokers for b in target):
                results[tp] = "INVALID_REPLICA_ASSIGNMENT"
                continue
            self._reassign[tp] = list(target)
            for b in target:
                if b not in info.replicas and not any(
                        c.tp == tp and c.dest_broker == b
                        for c in self._copies):
                    self._copies.append(_Copy(tp=tp, dest_broker=b,
                                              remaining_mb=info.size_mb))
            # Reorder-only (or already-caught-up) reassignments complete
            # immediately — Kafka applies them as pure metadata updates.
            if all(b in info.isr for b in target):
                self._finalize_reassignment(tp)
            results[tp] = None
        return results

    def elect_preferred_leaders(self, tps: list[tuple[str, int]]
                                ) -> dict[tuple[str, int], str | None]:
        self.num_leader_elections += 1
        results: dict[tuple[str, int], str | None] = {}
        for tp in tps:
            info = self._partitions.get(tp)
            if info is None:
                results[tp] = "UNKNOWN_TOPIC_OR_PARTITION"
                continue
            preferred = info.replicas[0]
            if preferred in info.isr and self._brokers[preferred].alive:
                info.leader = preferred
                results[tp] = None
            else:
                results[tp] = "PREFERRED_LEADER_NOT_AVAILABLE"
        return results

    def alter_replica_log_dirs(self, moves: dict[tuple[str, int, int], str]
                               ) -> dict[tuple[str, int, int], str | None]:
        results: dict[tuple[str, int, int], str | None] = {}
        for (t, p, b), logdir in moves.items():
            info = self._partitions.get((t, p))
            if info is None or b not in info.replicas:
                results[(t, p, b)] = "REPLICA_NOT_AVAILABLE"
                continue
            if logdir not in self._brokers[b].logdirs:
                results[(t, p, b)] = "LOG_DIR_NOT_FOUND"
                continue
            self._copies.append(_Copy(tp=(t, p), dest_broker=b,
                                      remaining_mb=info.size_mb,
                                      intra_target_logdir=logdir))
            results[(t, p, b)] = None
        return results

    def alter_broker_config(self, broker_id: int,
                            config: dict[str, str | None]) -> None:
        cfg = self._brokers[broker_id].config
        for k, v in config.items():
            if v is None:
                cfg.pop(k, None)
            else:
                cfg[k] = v

    def describe_broker_config(self, broker_id: int) -> dict[str, str]:
        return dict(self._brokers[broker_id].config)

    def alter_topic_config(self, topic: str,
                           config: dict[str, str | None]) -> None:
        cfg = self._topic_configs.setdefault(topic, {})
        for k, v in config.items():
            if v is None:
                cfg.pop(k, None)
            else:
                cfg[k] = v

    def describe_topic_config(self, topic: str) -> dict[str, str]:
        return dict(self._topic_configs.get(topic, {}))


class SimClock:
    """Deterministic clock whose ``sleep`` advances the simulated cluster —
    executor tests run in milliseconds of wall time."""

    def __init__(self, cluster: SimulatedKafkaCluster):
        self.cluster = cluster

    def now_ms(self) -> int:
        return self.cluster.now_ms

    def sleep_ms(self, ms: int) -> None:
        self.cluster.advance_to(self.cluster.now_ms + ms)
