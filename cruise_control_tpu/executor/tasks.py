"""Execution task lifecycle (ref ``executor/ExecutionTask.java``,
``ExecutionTaskTracker.java``, ``ExecutionTaskManager.java``).

An :class:`ExecutionTask` wraps one ``ExecutionProposal`` with a task type
and a state machine::

    PENDING -> IN_PROGRESS -> COMPLETED
                           -> ABORTING -> ABORTED
                           -> DEAD

(ref ``ExecutionTask.State``; valid transitions ``ExecutionTask.java:45-60``).
The tracker keeps per-type/per-state sets and counts for ``ExecutorState``
serialization (ref ``ExecutionTaskTracker.java``).
"""

from __future__ import annotations

import enum
import itertools
import threading
from dataclasses import dataclass, field

from ..model.proposals import ExecutionProposal


class TaskType(enum.Enum):
    """ref ``ExecutionTask.TaskType``."""

    INTER_BROKER_REPLICA_ACTION = "INTER_BROKER_REPLICA_ACTION"
    INTRA_BROKER_REPLICA_ACTION = "INTRA_BROKER_REPLICA_ACTION"
    LEADER_ACTION = "LEADER_ACTION"


class TaskState(enum.Enum):
    """ref ``ExecutionTask.State``."""

    PENDING = "PENDING"
    IN_PROGRESS = "IN_PROGRESS"
    ABORTING = "ABORTING"
    ABORTED = "ABORTED"
    DEAD = "DEAD"
    COMPLETED = "COMPLETED"


_VALID_TRANSITIONS: dict[TaskState, set[TaskState]] = {
    TaskState.PENDING: {TaskState.IN_PROGRESS},
    TaskState.IN_PROGRESS: {TaskState.ABORTING, TaskState.DEAD,
                            TaskState.COMPLETED},
    TaskState.ABORTING: {TaskState.ABORTED, TaskState.DEAD},
    TaskState.ABORTED: set(),
    TaskState.DEAD: set(),
    TaskState.COMPLETED: set(),
}

#: Terminal states (ref ExecutionTask.IN_EXECUTION_STATES complement).
COMPLETED_STATES = {TaskState.COMPLETED, TaskState.ABORTED, TaskState.DEAD}


@dataclass
class ExecutionTask:
    """One unit of executor work (ref ``ExecutionTask.java``)."""

    execution_id: int
    proposal: ExecutionProposal
    task_type: TaskType
    state: TaskState = TaskState.PENDING
    start_time_ms: int | None = None
    end_time_ms: int | None = None
    alert_time_ms: int | None = None

    def transition(self, new_state: TaskState, now_ms: int) -> None:
        if new_state not in _VALID_TRANSITIONS[self.state]:
            raise ValueError(
                f"illegal task transition {self.state.value} -> "
                f"{new_state.value} (task {self.execution_id})")
        self.state = new_state
        if new_state is TaskState.IN_PROGRESS:
            self.start_time_ms = now_ms
        elif new_state in COMPLETED_STATES:
            self.end_time_ms = now_ms

    @property
    def done(self) -> bool:
        return self.state in COMPLETED_STATES

    @property
    def topic_partition(self) -> tuple[str, int]:
        return (self.proposal.topic, self.proposal.partition)

    def to_json(self) -> dict:
        return {"executionId": self.execution_id,
                "type": self.task_type.value,
                "state": self.state.value,
                "proposal": self.proposal.to_json()}


@dataclass(frozen=True)
class IntraBrokerReplicaMove:
    """One replica's move between logdirs of a broker (ref the disk-aware
    ``ExecutionProposal`` variant used by IntraBrokerDiskUsageDistribution)."""

    topic: str
    partition: int
    broker_id: int
    source_logdir: str
    dest_logdir: str
    size_mb: float = 0.0

    @property
    def tp(self) -> tuple[str, int]:
        return (self.topic, self.partition)

    def to_json(self) -> dict:
        return {"topicPartition": {"topic": self.topic,
                                   "partition": self.partition},
                "brokerId": self.broker_id,
                "sourceLogdir": self.source_logdir,
                "destLogdir": self.dest_logdir}


class ExecutionTaskTracker:
    """Counts/sets of tasks by (type, state) (ref ExecutionTaskTracker.java).

    Thread-safe: the executor's runnable mutates while the API layer reads
    for ``/state``.
    """

    def __init__(self, tracer=None) -> None:
        self._tasks: dict[TaskType, dict[TaskState, dict[int, ExecutionTask]]] = {
            t: {s: {} for s in TaskState} for t in TaskType}
        self._lock = threading.RLock()
        #: span tracer: a task reaching a terminal state records an
        #: ``executor.task`` lifecycle span (duration = its
        #: IN_PROGRESS→terminal window on the executor's clock)
        if tracer is None:
            from ..core.tracing import default_tracer
            tracer = default_tracer()
        self._tracer = tracer

    def add(self, task: ExecutionTask) -> None:
        with self._lock:
            self._tasks[task.task_type][task.state][task.execution_id] = task

    def transition(self, task: ExecutionTask, new_state: TaskState,
                   now_ms: int) -> None:
        with self._lock:
            del self._tasks[task.task_type][task.state][task.execution_id]
            task.transition(new_state, now_ms)
            self._tasks[task.task_type][new_state][task.execution_id] = task
        if task.done and task.start_time_ms is not None:
            # Reconstructed lifecycle span (the executor's now_ms clock may
            # be simulated; only the duration is trusted — the span ends
            # "now" on the tracer's clock). Parent = whatever phase span
            # the executing thread currently holds.
            proposal = task.proposal
            self._tracer.record(
                "executor.task",
                max((task.end_time_ms or now_ms) - task.start_time_ms, 0)
                / 1000.0,
                attrs={"type": task.task_type.value,
                       "state": task.state.value,
                       "topic": getattr(proposal, "topic", None),
                       "partition": getattr(proposal, "partition", None),
                       "executionId": task.execution_id})

    def tasks_in(self, task_type: TaskType,
                 state: TaskState) -> list[ExecutionTask]:
        with self._lock:
            return list(self._tasks[task_type][state].values())

    def num_in(self, task_type: TaskType, state: TaskState) -> int:
        with self._lock:
            return len(self._tasks[task_type][state])

    def num_remaining(self, task_type: TaskType) -> int:
        with self._lock:
            return sum(len(self._tasks[task_type][s]) for s in
                       (TaskState.PENDING, TaskState.IN_PROGRESS,
                        TaskState.ABORTING))

    def all_tasks(self) -> list[ExecutionTask]:
        with self._lock:
            return [t for by_state in self._tasks.values()
                    for tasks in by_state.values() for t in tasks.values()]

    def summary(self) -> dict:
        """Per-type per-state counts (feeds ExecutorState, ref
        ExecutionTasksSummary)."""
        with self._lock:
            return {t.value: {s.value: len(self._tasks[t][s])
                              for s in TaskState if self._tasks[t][s]}
                    for t in TaskType}


class ExecutionTaskManager:
    """Creates tasks from proposals and hands them to the planner/tracker
    (ref ExecutionTaskManager.java)."""

    def __init__(self, tracer=None) -> None:
        self._id_gen = itertools.count()
        self.tracker = ExecutionTaskTracker(tracer=tracer)

    def add_execution_proposals(self, proposals: list[ExecutionProposal]
                                ) -> list[ExecutionTask]:
        """Split proposals into inter-broker / leadership tasks (ref
        ExecutionTaskManager.addExecutionProposals; intra-broker tasks come
        from the disk-aware path)."""
        tasks: list[ExecutionTask] = []
        for p in proposals:
            if p.has_replica_action:
                tasks.append(ExecutionTask(next(self._id_gen), p,
                                           TaskType.INTER_BROKER_REPLICA_ACTION))
            elif p.has_leader_action:
                tasks.append(ExecutionTask(next(self._id_gen), p,
                                           TaskType.LEADER_ACTION))
        for t in tasks:
            self.tracker.add(t)
        return tasks

    def add_intra_broker_tasks(self, moves) -> list[ExecutionTask]:
        """Intra-broker (disk) movement tasks (ref
        ExecutionTaskManager's intra-broker path). ``moves`` is a list of
        IntraBrokerReplicaMove-like objects carrying a proposal."""
        tasks = [ExecutionTask(next(self._id_gen), m,
                               TaskType.INTRA_BROKER_REPLICA_ACTION)
                 for m in moves]
        for t in tasks:
            self.tracker.add(t)
        return tasks
