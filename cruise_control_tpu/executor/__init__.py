"""Executor layer (L7): applies optimization proposals to the cluster.

Rebuild of ``cruise-control/.../executor/`` — see :mod:`.executor` for the
phase driver, :mod:`.planner`/:mod:`.strategy` for batch planning,
:mod:`.concurrency` for caps + the adaptive adjuster, :mod:`.throttle` for
replication throttling, and :mod:`.simulated` for the in-process cluster
double used by tests and demos.
"""

from .admin import ClusterAdminClient, PartitionInfo, ReassignmentInfo
from .kafka_admin import (AdminAuthorizationError, AdminOperationError,
                          AdminTimeoutError, KafkaAdminClusterClient,
                          KafkaAdminWire, KafkaWireError,
                          MockKafkaAdminWire)
from .concurrency import (ConcurrencyAdjuster, ConcurrencyConfig,
                          ConcurrencyType, ExecutionConcurrencyManager)
from .executor import (ExecutionResult, Executor, ExecutorConfig,
                       ExecutorNotifier, ExecutorState, OngoingExecutionError)
from .planner import ExecutionTaskPlanner
from .schedule import (DeviceMoveScheduler, MoveSchedule,
                       ScheduleAuditError, forecast_filter)
from .simulated import SimClock, SimulatedKafkaCluster
from .strategy import (StrategyContext, ReplicaMovementStrategy,
                       STRATEGY_REGISTRY, strategy_chain)
from .tasks import (ExecutionTask, ExecutionTaskManager, ExecutionTaskTracker,
                    IntraBrokerReplicaMove, TaskState, TaskType)

__all__ = [
    "ClusterAdminClient", "PartitionInfo", "ReassignmentInfo",
    "AdminAuthorizationError", "AdminOperationError", "AdminTimeoutError",
    "KafkaAdminClusterClient", "KafkaAdminWire", "KafkaWireError",
    "MockKafkaAdminWire",
    "ConcurrencyAdjuster", "ConcurrencyConfig", "ConcurrencyType",
    "ExecutionConcurrencyManager", "ExecutionResult", "Executor",
    "ExecutorConfig", "ExecutorNotifier", "ExecutorState",
    "OngoingExecutionError", "ExecutionTaskPlanner",
    "DeviceMoveScheduler", "MoveSchedule", "ScheduleAuditError",
    "forecast_filter", "SimClock",
    "SimulatedKafkaCluster", "StrategyContext", "ReplicaMovementStrategy",
    "STRATEGY_REGISTRY", "strategy_chain", "ExecutionTask",
    "ExecutionTaskManager", "ExecutionTaskTracker", "IntraBrokerReplicaMove",
    "TaskState", "TaskType",
]
