"""Replication throttling around executions
(ref ``executor/ReplicationThrottleHelper.java``).

Before inter-broker movements start, set the leader/follower throttled-rate
config on every participating broker and mark the moving replicas in each
topic's throttled-replicas lists; after execution (or on stop), remove
exactly what we added — configs set by operators are left intact (ref
``ReplicationThrottleHelper`` only clears values it wrote).
"""

from __future__ import annotations

from .admin import ClusterAdminClient
from .simulated import (FOLLOWER_THROTTLED_RATE, FOLLOWER_THROTTLED_REPLICAS,
                        LEADER_THROTTLED_RATE, LEADER_THROTTLED_REPLICAS)
from .tasks import ExecutionTask


class ReplicationThrottleHelper:
    def __init__(self, admin: ClusterAdminClient,
                 throttle_rate_bytes: int | None):
        self.admin = admin
        self.rate = throttle_rate_bytes
        self._touched_brokers: set[tuple[int, str]] = set()  # (broker, key)
        #: topic -> key -> replica entries ("partition:broker") we added
        self._touched_topics: dict[str, dict[str, set[str]]] = {}

    def set_throttles(self, tasks: list[ExecutionTask],
                      excluded_brokers: set[int] | None = None) -> None:
        """``excluded_brokers`` never receive throttle configs or replica
        entries (ref THROTTLE_ADDED/REMOVED_BROKER_PARAM=false: copies to
        a fresh broker / off a draining broker run at full speed)."""
        if self.rate is None:
            return
        skip = excluded_brokers or set()
        brokers: set[int] = set()
        by_topic: dict[str, dict[str, set[str]]] = {}
        for t in tasks:
            p = t.proposal
            # Old replicas serve the copies (leader-side throttle), new ones
            # receive them (follower-side) — all participate. Keeping the
            # two lists separate matters: putting an existing in-sync
            # follower in the follower list would throttle its ordinary
            # replication fetches and risk dropping it out of ISR.
            for b in (*p.old_replicas, *p.replicas_to_add):
                if b not in skip:
                    brokers.add(b)
            lists = by_topic.setdefault(
                p.topic, {LEADER_THROTTLED_REPLICAS: set(),
                          FOLLOWER_THROTTLED_REPLICAS: set()})
            # Kafka's "partition:broker" entry format.
            for b in p.old_replicas:
                if b not in skip:
                    lists[LEADER_THROTTLED_REPLICAS].add(
                        f"{p.partition}:{b}")
            for b in p.replicas_to_add:
                if b not in skip:
                    lists[FOLLOWER_THROTTLED_REPLICAS].add(
                        f"{p.partition}:{b}")
        for b in brokers:
            existing = self.admin.describe_broker_config(b)
            cfg: dict[str, str | None] = {}
            # Don't override an operator-set rate; only fill absent keys
            # (and later clear exactly the keys we wrote).
            for key in (LEADER_THROTTLED_RATE, FOLLOWER_THROTTLED_RATE):
                if key not in existing:
                    cfg[key] = str(self.rate)
                    self._touched_brokers.add((b, key))
            if cfg:
                self.admin.alter_broker_config(b, cfg)
        for topic, lists in by_topic.items():
            existing = self.admin.describe_topic_config(topic)
            added = self._touched_topics.setdefault(
                topic, {LEADER_THROTTLED_REPLICAS: set(),
                        FOLLOWER_THROTTLED_REPLICAS: set()})
            for key, entries in lists.items():
                prev = set(filter(None, existing.get(key, "").split(",")))
                new = prev | entries
                if new != prev:
                    added[key] |= entries - prev
                    self.admin.alter_topic_config(
                        topic, {key: ",".join(sorted(new))})

    def clear_throttles(self) -> None:
        for b, key in self._touched_brokers:
            self.admin.alter_broker_config(b, {key: None})
        self._touched_brokers.clear()
        for topic, added in self._touched_topics.items():
            existing = self.admin.describe_topic_config(topic)
            for key, entries in added.items():
                prev = set(filter(None, existing.get(key, "").split(",")))
                remaining = prev - entries
                self.admin.alter_topic_config(
                    topic, {key: ",".join(sorted(remaining)) if remaining
                            else None})
        self._touched_topics.clear()
