"""Cluster admin SPI — the executor's boundary to the managed cluster.

The reference executor drives Kafka through ``AdminClient``
(``ExecutionUtils.submitReplicaReassignmentTasks`` ``ExecutionUtils.java:485``,
``electLeaders`` ``:435``, ``alterReplicaLogDirs``). This module defines the
minimal protocol those call sites need, so the executor logic is testable
against :class:`~cruise_control_tpu.executor.simulated.SimulatedKafkaCluster`
and deployable against a real Kafka by implementing the same protocol with
confluent-kafka/kafka-python (not bundled in this environment).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Protocol


@dataclass
class PartitionInfo:
    """Current state of one partition (subset of Kafka metadata)."""

    topic: str
    partition: int
    replicas: list[int]          # broker ids, preferred leader first
    leader: int                  # broker id, -1 if none
    isr: set[int] = field(default_factory=set)
    size_mb: float = 0.0
    #: broker id -> logdir name hosting this partition's replica
    logdirs: dict[int, str] = field(default_factory=dict)

    @property
    def tp(self) -> tuple[str, int]:
        return (self.topic, self.partition)


@dataclass
class ReassignmentInfo:
    """In-flight reassignment (ref AdminClient.listPartitionReassignments)."""

    target: list[int]
    adding: list[int]
    removing: list[int]


class ClusterAdminClient(Protocol):
    """The executor's required admin surface."""

    def describe_cluster(self) -> dict[int, bool]:
        """broker id -> alive."""
        ...

    def describe_partitions(self) -> dict[tuple[str, int], PartitionInfo]:
        ...

    def alter_partition_reassignments(
            self, targets: dict[tuple[str, int], list[int] | None]
    ) -> dict[tuple[str, int], str | None]:
        """Start (list) or cancel (None) reassignments; returns per-partition
        error string or None (ref ExecutionUtils.java:485)."""
        ...

    def list_partition_reassignments(self) -> dict[tuple[str, int], ReassignmentInfo]:
        ...

    def elect_preferred_leaders(self, tps: list[tuple[str, int]]
                                ) -> dict[tuple[str, int], str | None]:
        """ref ExecutionUtils.java:435."""
        ...

    def alter_replica_log_dirs(self, moves: dict[tuple[str, int, int], str]
                               ) -> dict[tuple[str, int, int], str | None]:
        """(topic, partition, broker) -> target logdir (intra-broker move)."""
        ...

    def describe_replica_log_dirs(self) -> dict[tuple[str, int, int], str]:
        ...

    # Optional (not part of the required Protocol surface):
    # ``describe_logdirs() -> dict[int, list[str]]`` — all LIVE configured
    # logdirs per broker, including empty ones (ref
    # AdminClient.describeLogDirs, which omits offline dirs). Callers fall
    # back to the dirs observed in replica placement when absent.

    def alter_broker_config(self, broker_id: int, config: dict[str, str | None]
                            ) -> None:
        """Set (or delete, value None) dynamic broker configs (throttles)."""
        ...

    def describe_broker_config(self, broker_id: int) -> dict[str, str]:
        ...

    def alter_topic_config(self, topic: str, config: dict[str, str | None]
                           ) -> None:
        ...

    def describe_topic_config(self, topic: str) -> dict[str, str]:
        ...

    def broker_metrics(self, broker_id: int) -> dict[str, float]:
        """Live health metrics for the concurrency adjuster (request queue
        size, log flush time — ref ConcurrencyAdjuster's metric queries)."""
        ...
