"""Device-side move scheduling: batched conflict-aware batching.

The host greedy planner (``planner.ExecutionTaskPlanner.inter_broker_batch``)
walks the strategy-ordered task list once per round, occupying per-broker
concurrency slots — O(rounds x moves) Python at the 10Kx1M tier's ~300K
moves/plan. This module computes the ENTIRE batch assignment in one
``lax.fori_loop`` device program: first-fit over the strategy order, where
move *i* lands in the lowest-indexed batch whose touched brokers all have
spare concurrency cap, the batch is under the cluster movement cap, and
(optionally) the per-destination bandwidth budget holds.

First-fit over the strategy order is provably IDENTICAL to running the host
greedy batcher to quiescence batch-by-batch: greedy round *k* takes, in
order, every remaining move whose brokers have spare cap in round *k* —
which is exactly the set first-fit assigns index *k* (a move skipped by
greedy in round *k* is skipped because a slot is full, so first-fit also
rejects batch *k* for it; induction over the order). The bit-identical
parity is regression-tested (``tests/test_schedule.py``) and makes the host
planner the drop-in degrade path.

Intermediate-placement safety (arxiv 1602.03770's integrated
reconfiguration planning): every batch boundary's placement — the initial
model with the first *c* scheduled moves applied — is scored through the
UNMODIFIED what-if machinery (``make_scenario_scorer`` with no-op scenario
parameters, the same ``violated_matrix`` ulp cutoff) against the
registered hard-goal audit set, all boundaries in one vmapped dispatch.
A violating boundary triggers bisection repair: the first offending batch
splits in two (a subset of a cap-feasible batch stays cap-feasible), the
boundaries re-audit, bounded rounds.

Both programs ride tracked compile accounting (``executor.schedule`` /
``executor.schedule.audit``) so the bench's zero-warm-recompile gate
covers them.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

import numpy as np

from ..model.flat import FlatClusterModel
from ..model.proposals import ExecutionProposal
from ..parallel.batching import ProgramCache, pow2_bucket
from .concurrency import ExecutionConcurrencyManager
from .planner import ExecutionTaskPlanner
from .strategy import StrategyContext
from .tasks import ExecutionTask, TaskType

logger = logging.getLogger(__name__)

#: Sentinel per-broker cap for the padding broker row — large enough to
#: never constrain, small enough to stay an exact int32.
_PAD_CAP = 1 << 30


@dataclass
class MoveSchedule:
    """A full batch assignment for one execution's inter-broker moves.

    ``batches`` holds tuples of indices into the ORIGINAL proposal list
    the scheduler was given (not task ids — the executor re-attaches its
    own tasks by proposal identity). Batch order is execution order; the
    order within a batch is the strategy order, same as the host planner
    emits.
    """

    batches: list[tuple[int, ...]]
    #: per-batch estimated copy time (max over destination brokers of
    #: inbound MB / throttled rate), None when no throttle rate is known
    eta_ms: list[float | None]
    stats: dict = field(default_factory=dict)

    @property
    def num_moves(self) -> int:
        return sum(len(b) for b in self.batches)

    def to_json(self) -> dict:
        return {"numBatches": len(self.batches),
                "numMoves": self.num_moves,
                "batchSizes": [len(b) for b in self.batches],
                "etaMs": [None if e is None else round(e, 1)
                          for e in self.eta_ms],
                "stats": dict(self.stats)}


def _first_fit_program(M: int, W: int, K: int):
    """Build the batched first-fit assignment fn for static shapes
    (M moves x W touched-broker slots, K batch slots).

    State: ``count int32[B1, K]`` per-(broker row, batch) occupied slots,
    ``size int32[K]`` per-batch move count, ``mb float32[B1, K]``
    per-(destination row, batch) inbound MB, ``assign int32[M]``. Per
    move: gather the touched rows' occupancy, test every batch at once,
    take the first feasible index (``argmax`` over the bool row), scatter
    the occupancy back. Infeasible-everywhere (possible only under a
    finite bandwidth budget — the cap-only bound below guarantees a slot)
    assigns the sentinel ``K``; the host spills those to trailing
    singleton batches.
    """
    import jax
    import jax.numpy as jnp

    def run(rows, dest_rows, sizes_mb, valid, caps, cluster_cap,
            bw_budget):
        B1 = caps.shape[0]

        def body(i, st):
            count, size, mb, assign = st
            r = rows[i]                              # int32[W]
            occ = count[r]                           # [W, K]
            cap_ok = jnp.all(occ < caps[r][:, None], axis=0)     # [K]
            size_ok = size < cluster_cap                          # [K]
            d = dest_rows[i]                          # int32[W]
            dmb = mb[d]                               # [W, K]
            is_dest = (d < B1 - 1)[:, None]
            # Bandwidth: a destination under budget, OR carrying nothing
            # yet (the first move into a broker is always admitted — a
            # single oversized partition must not become unschedulable).
            bw_ok = jnp.all(~is_dest
                            | (dmb + sizes_mb[i] <= bw_budget)
                            | (dmb == 0.0), axis=0)               # [K]
            ok = cap_ok & size_ok & bw_ok & valid[i]
            k = jnp.where(ok.any(), jnp.argmax(ok), K)
            count = count.at[r, k].add(1, mode="drop")
            size = size.at[k].add(jnp.where(valid[i], 1, 0),
                                  mode="drop")
            mb = mb.at[d, k].add(jnp.where(is_dest[:, 0],
                                           sizes_mb[i], 0.0),
                                 mode="drop")
            assign = assign.at[i].set(
                jnp.where(valid[i], k.astype(jnp.int32), K))
            return count, size, mb, assign

        init = (jnp.zeros((B1, K), jnp.int32),
                jnp.zeros((K,), jnp.int32),
                jnp.zeros((B1, K), jnp.float32),
                jnp.full((M,), K, jnp.int32))
        *_, assign = jax.lax.fori_loop(0, M, body, init)
        return assign

    return run


class DeviceMoveScheduler:
    """Batched move scheduling + intermediate-placement audit.

    One instance per facade/executor wiring; program caches are bounded
    and keyed on pow2-bucketed shapes so steady-state executions reuse
    compiled programs (the bench gates zero warm recompiles across
    pipelined batches).
    """

    def __init__(self, collector=None, tracer=None) -> None:
        from ..core.runtime_obs import default_collector
        from ..core.tracing import default_tracer
        self.collector = collector or default_collector()
        self.tracer = tracer or default_tracer()
        self._programs = ProgramCache(capacity=8)
        self._audit_programs = ProgramCache(capacity=8)

    # ------------------------------------------------------------ schedule
    def schedule(self, proposals: list[ExecutionProposal],
                 concurrency: ExecutionConcurrencyManager,
                 *,
                 model: FlatClusterModel | None = None,
                 metadata=None,
                 goals=(),
                 capacity_threshold=None,
                 strategy=None,
                 strategy_context: StrategyContext | None = None,
                 throttle_bytes: int | None = None,
                 bandwidth_mb_per_batch: float | None = None,
                 max_repair_rounds: int = 4,
                 strict: bool = False) -> MoveSchedule:
        """Compute the full batch assignment for ``proposals``'
        inter-broker moves.

        ``model``/``metadata``/``goals`` enable the intermediate-boundary
        hard-goal audit (skipped when absent — e.g. the parity tests);
        ``goals`` are BOUND goal kernels (the facade passes the
        optimizer's registered hard-goal audit set). ``strict`` raises
        when repair cannot clear a boundary violation; otherwise the
        schedule ships with ``stats['unrepaired_violations']`` set and
        the executor's caller decides.
        """
        ctx = strategy_context or StrategyContext()
        with self.tracer.span("executor.schedule",
                              moves=len(proposals)):
            order = self._strategy_order(proposals, strategy, ctx)
            if not order:
                return MoveSchedule(batches=[], eta_ms=[],
                                    stats={"moves": 0, "batches": 0})
            assign = self._assign(order, proposals, concurrency,
                                  metadata, ctx,
                                  bandwidth_mb_per_batch)
            batches = self._group(order, assign)
            stats = {"moves": len(order), "batches": len(batches),
                     "boundaries_audited": 0, "repair_rounds": 0,
                     "unrepaired_violations": 0, "spilled_moves":
                     int((assign >= _SPILL).sum()) if len(assign) else 0}
            if goals and model is not None and metadata is not None:
                batches = self._audit_and_repair(
                    batches, proposals, model, metadata, goals,
                    capacity_threshold, stats,
                    max_repair_rounds=max_repair_rounds, strict=strict)
            eta = [self._batch_eta_ms(b, proposals, ctx, throttle_bytes)
                   for b in batches]
            stats["batches"] = len(batches)
            return MoveSchedule(batches=batches, eta_ms=eta, stats=stats)

    # ------------------------------------------------------ strategy order
    def _strategy_order(self, proposals, strategy, ctx):
        """Indices of the inter-broker proposals in strategy order —
        EXACTLY the order the host planner's ``begin_phase`` would sort
        the corresponding tasks into (shim tasks carry list positions as
        execution ids; the inter subset's relative id order matches the
        task manager's interleaved sequential ids)."""
        planner = ExecutionTaskPlanner(strategy)
        shims = [ExecutionTask(i, p, TaskType.INTER_BROKER_REPLICA_ACTION)
                 for i, p in enumerate(proposals)
                 if p.has_replica_action]
        shims.sort(key=lambda t: planner.sort_key(t, ctx))
        return [t.execution_id for t in shims]

    # ------------------------------------------------------------- assign
    def _assign(self, order, proposals, concurrency, metadata, ctx,
                bandwidth_mb_per_batch):
        """Run the device first-fit program; returns ``int32[len(order)]``
        batch indices aligned with ``order``."""
        import jax.numpy as jnp

        M = len(order)
        # Broker-row universe: metadata rows when available (aligns with
        # the audit model), else a dense local index over the ids seen.
        if metadata is not None:
            bindex = metadata.broker_index
            row_ids = list(metadata.broker_ids)
            B = len(row_ids)
        else:
            row_ids = sorted({b for i in order
                              for b in (*proposals[i].replicas_to_add,
                                        *proposals[i].replicas_to_remove)})
            bindex = {b: r for r, b in enumerate(row_ids)}
            B = len(row_ids)
        touched = [tuple(proposals[i].replicas_to_add)
                   + tuple(proposals[i].replicas_to_remove)
                   for i in order]
        dests = [tuple(proposals[i].replicas_to_add) for i in order]
        W = max((len(t) for t in touched), default=1)
        rows = np.full((M, W), B, np.int32)
        dest_rows = np.full((M, W), B, np.int32)
        touch_count: dict[int, int] = {}
        for m, t in enumerate(touched):
            for j, b in enumerate(t):
                r = bindex[b]
                rows[m, j] = r
                touch_count[r] = touch_count.get(r, 0) + 1
            for j, b in enumerate(dests[m]):
                dest_rows[m, j] = bindex[b]
        sizes = np.array(
            [float(ctx.partition_size_mb.get(
                (proposals[i].topic, proposals[i].partition), 0.0))
             for i in order], np.float32)
        caps = np.full((B + 1,), _PAD_CAP, np.int32)
        for r in range(B):
            caps[r] = min(concurrency.inter_broker_cap(row_ids[r]),
                          _PAD_CAP)
        ccap = max(int(concurrency.cluster_movement_cap), 1)

        # First-fit batch-index bound under caps alone: move i can be
        # rejected from batch k only by a full batch (at most
        # floor((M-1)/ccap) of those precede its slot) or by one of its
        # brokers at cap (broker b fills at most floor((touch_b-1)/cap_b)
        # batches with EARLIER moves). K = 1 + the worst move's bound.
        full_b = (M - 1) // ccap
        worst = 0
        for m in range(M):
            s = sum((touch_count[r] - 1) // max(int(caps[r]), 1)
                    for r in set(int(x) for x in rows[m] if x < B))
            worst = max(worst, s)
        # A finite bandwidth budget can split batches the caps alone
        # admit; the first-move-per-destination rule bounds the extra
        # batches by the busiest destination's move count.
        bw_extra = 0
        if bandwidth_mb_per_batch:
            dest_count: dict[int, int] = {}
            for m in range(M):
                for b in set(int(x) for x in dest_rows[m] if x < B):
                    dest_count[b] = dest_count.get(b, 0) + 1
            bw_extra = max(dest_count.values(), default=1) - 1
        K = min(M, 1 + full_b + worst + bw_extra)
        K = min(pow2_bucket(K), pow2_bucket(M))
        M_pad = pow2_bucket(M)
        rows_p = np.full((M_pad, W), B, np.int32)
        rows_p[:M] = rows
        dest_p = np.full((M_pad, W), B, np.int32)
        dest_p[:M] = dest_rows
        sizes_p = np.zeros((M_pad,), np.float32)
        sizes_p[:M] = sizes
        valid = np.zeros((M_pad,), bool)
        valid[:M] = True
        bw = (np.float32(bandwidth_mb_per_batch)
              if bandwidth_mb_per_batch else np.float32(np.inf))

        key = (M_pad, W, B + 1, K)
        program = self._programs.get_or_build(
            key, lambda: self.collector.track(
                "executor.schedule",
                _jit_first_fit(M_pad, W, K)))
        self.collector.record_h2d(rows_p.nbytes + dest_p.nbytes
                                  + sizes_p.nbytes + valid.nbytes
                                  + caps.nbytes)
        assign = np.array(program(
            jnp.asarray(rows_p), jnp.asarray(dest_p),
            jnp.asarray(sizes_p), jnp.asarray(valid),
            jnp.asarray(caps), jnp.int32(ccap), jnp.asarray(bw)))[:M]
        # Spilled moves (finite-bandwidth corner): sentinel K → trailing
        # singleton batches, marked for stats via the _SPILL offset.
        if (assign >= K).any():
            nxt = int(assign[assign < K].max(initial=-1)) + 1
            for m in np.nonzero(assign >= K)[0]:
                assign[m] = _SPILL + nxt
                nxt += 1
        return assign

    @staticmethod
    def _group(order, assign) -> list[tuple[int, ...]]:
        """Batch index array -> ordered list of original-index tuples."""
        by_k: dict[int, list[int]] = {}
        for pos, k in enumerate(assign):
            by_k.setdefault(int(k) % _SPILL, []).append(order[pos])
        return [tuple(by_k[k]) for k in sorted(by_k)]

    # -------------------------------------------------------------- audit
    def _audit_and_repair(self, batches, proposals, model, metadata,
                          goals, capacity_threshold, stats, *,
                          max_repair_rounds, strict):
        """Score every batch boundary's placement against the hard-goal
        audit set; bisect-split offending batches, bounded rounds."""
        from ..whatif.engine import violated_matrix
        goals = tuple(goals)
        if capacity_threshold is None:
            capacity_threshold = np.ones(4, np.float32)
        for rnd in range(max_repair_rounds + 1):
            bad = self._violating_boundaries(
                batches, proposals, model, metadata, goals,
                capacity_threshold, violated_matrix)
            stats["boundaries_audited"] += len(batches)
            if not bad:
                return batches
            if rnd == max_repair_rounds:
                break
            stats["repair_rounds"] += 1
            first = bad[0]
            batch = batches[first]
            if len(batch) <= 1:
                # A single move violating a hard goal mid-flight cannot
                # be split further — the plan itself walks through the
                # violation. Record and stop burning rounds.
                break
            mid = len(batch) // 2
            batches = (batches[:first]
                       + [tuple(batch[:mid]), tuple(batch[mid:])]
                       + batches[first + 1:])
            logger.info("executor.schedule: boundary %d violated hard "
                        "goals; split batch into %d+%d (round %d)",
                        first, mid, len(batch) - mid, rnd + 1)
        stats["unrepaired_violations"] = len(bad)
        msg = (f"move schedule leaves {len(bad)} batch boundaries in "
               f"hard-goal violation after {max_repair_rounds} repair "
               f"rounds")
        if strict:
            raise ScheduleAuditError(msg)
        logger.warning("executor.schedule: %s", msg)
        return batches

    def _violating_boundaries(self, batches, proposals, model, metadata,
                              goals, capacity_threshold, violated_matrix):
        """Indices of batches whose post-batch placement violates any
        audit goal — one vmapped device dispatch over all boundaries."""
        import jax.numpy as jnp

        P, R = model.replica_broker.shape
        B = model.num_brokers_padded
        # Apply-order: moves sorted by (batch, in-batch position) — the
        # boundary after batch k is then a PREFIX of this order, so the
        # whole audit vmaps over one int count per boundary.
        flat = [i for b in batches for i in b]
        M = len(flat)
        prop_rows = np.full((max(M, 1),), P, np.int32)     # OOB = dropped
        new_rb = np.full((max(M, 1), R), B, np.int32)
        for m, i in enumerate(flat):
            p = proposals[i]
            row = metadata.partition_index.get((p.topic, p.partition))
            if row is None:
                continue           # stale proposal; executor validates
            prop_rows[m] = row
            for j, b in enumerate(p.new_replicas[:R]):
                new_rb[m, j] = metadata.broker_index.get(b, B)
        counts = np.cumsum([len(b) for b in batches]).astype(np.int32)
        Kb = len(counts)
        Kb_pad = pow2_bucket(Kb)
        counts_p = np.zeros((Kb_pad,), np.int32)
        counts_p[:Kb] = counts

        needs_tlc = any(g.uses_topic_leader_counts for g in goals)
        needs_topics = needs_tlc or any(g.uses_topic_counts
                                        for g in goals)
        num_topics = metadata.num_topics
        key = (pow2_bucket(max(M, 1)), Kb_pad, (P, R), B,
               tuple((g.name, g.bind_signature()) for g in goals),
               num_topics if needs_topics else None, needs_tlc)
        M_pad = pow2_bucket(max(M, 1))
        rows_p = np.full((M_pad,), P, np.int32)
        rows_p[:len(prop_rows)] = prop_rows
        rb_p = np.full((M_pad, R), B, np.int32)
        rb_p[:len(new_rb)] = new_rb
        program = self._audit_programs.get_or_build(
            key, lambda: self._build_audit_program(
                goals, capacity_threshold, num_topics=num_topics,
                needs_topics=needs_topics, needs_tlc=needs_tlc))
        self.collector.record_h2d(rows_p.nbytes + rb_p.nbytes
                                  + counts_p.nbytes)
        viol, vscale = program(model, jnp.asarray(rows_p),
                               jnp.asarray(rb_p),
                               jnp.asarray(counts_p))
        violated = violated_matrix(np.asarray(viol)[:Kb],
                                   np.asarray(vscale)[:Kb])
        return [k for k in range(Kb) if violated[k].any()]

    def _build_audit_program(self, goals, capacity_threshold, *,
                             num_topics, needs_topics, needs_tlc):
        """jit(vmap(boundary count -> audit-goal violations)) through the
        UNMODIFIED what-if scorer (no-op scenario parameters): one
        scoring convention for proposals, simulations, and schedules."""
        import jax
        import jax.numpy as jnp

        from ..whatif.engine import make_scenario_scorer
        one = make_scenario_scorer(
            goals, capacity_threshold, num_topics=num_topics,
            needs_topics=needs_topics, needs_tlc=needs_tlc)

        def boundary(mdl, prop_rows, new_rb, count):
            P, R = mdl.replica_broker.shape
            B = mdl.num_brokers_padded
            applied = (jnp.arange(prop_rows.shape[0]) < count)[:, None]
            cur = mdl.replica_broker.at[prop_rows].get(mode="fill",
                                                       fill_value=B)
            rb = mdl.replica_broker.at[prop_rows].set(
                jnp.where(applied, new_rb, cur), mode="drop")
            pref = mdl.replica_pref_pos.at[prop_rows].set(
                jnp.where(applied,
                          jnp.arange(R, dtype=jnp.int32)[None, :],
                          mdl.replica_pref_pos.at[prop_rows].get(
                              mode="fill", fill_value=0)),
                mode="drop")
            off = mdl.replica_offline.at[prop_rows].set(
                jnp.where(applied, False,
                          mdl.replica_offline.at[prop_rows].get(
                              mode="fill", fill_value=False)),
                mode="drop")
            m2 = mdl.replace(replica_broker=rb, replica_pref_pos=pref,
                             replica_offline=off)
            nb = m2.broker_capacity.shape[0]
            viol, vscale, *_ = one(
                m2,
                jnp.zeros((nb,), bool), jnp.zeros((nb,), bool),
                jnp.ones_like(m2.broker_capacity),
                jnp.ones((P,), jnp.float32),
                m2.partition_valid)
            return viol, vscale

        return self.collector.track(
            "executor.schedule.audit",
            jax.jit(jax.vmap(boundary,
                             in_axes=(None, None, None, 0))))

    # ---------------------------------------------------------------- eta
    @staticmethod
    def _batch_eta_ms(batch, proposals, ctx, throttle_bytes):
        """Estimated batch copy time: worst destination broker's inbound
        MB over the throttled replication rate. The executor uses it to
        SKIP poll RPCs while copies are provably still in flight (an
        underestimate just costs extra poll rounds)."""
        if not throttle_bytes:
            return None
        rate_mb_s = float(throttle_bytes) / 1e6
        if rate_mb_s <= 0:
            return None
        inbound: dict[int, float] = {}
        for i in batch:
            p = proposals[i]
            mb = float(ctx.partition_size_mb.get(
                (p.topic, p.partition), 0.0))
            for b in p.replicas_to_add:
                inbound[b] = inbound.get(b, 0.0) + mb
        if not inbound:
            return 0.0
        return max(inbound.values()) / rate_mb_s * 1000.0


class ScheduleAuditError(RuntimeError):
    """Raised (strict mode) when bisection repair cannot produce a
    schedule whose every batch boundary passes the hard-goal audit."""


#: Spilled-move batch-index offset (see ``_assign``): indices >= _SPILL
#: encode trailing singleton batches for bandwidth-infeasible moves.
_SPILL = 1 << 20


def _jit_first_fit(M: int, W: int, K: int):
    import jax
    return jax.jit(_first_fit_program(M, W, K))


def forecast_filter(proposals: list[ExecutionProposal], scenario, *,
                    shrink_below: float, hot_above: float):
    """PR 13 follow-up: partition the proposal list by the forecast's
    projected per-topic load factors.

    ``scenario`` is a ``TrajectoryScale`` (``forecast.engine
    .trajectory_scenario``). Topics projected to shrink below
    ``shrink_below`` get their heals DEFERRED (the imbalance they fix is
    predicted to dissolve — executing it now moves data twice); topics
    projected above ``hot_above`` are returned as the hot set the
    executor pre-positions leaders for first. Returns ``(kept, deferred,
    hot_topics)``; ``kept``/``deferred`` preserve input order.
    """
    factors = dict(getattr(scenario, "factors", ()) or ())
    shrink = {t for t, f in factors.items() if f < shrink_below}
    hot = {t for t, f in factors.items() if f >= hot_above}
    kept, deferred = [], []
    for p in proposals:
        (deferred if p.topic in shrink else kept).append(p)
    return kept, deferred, hot
