"""Cross-language optimizer boundary (SURVEY §5.8): the Optimize sidecar
server; the wire contract lives in ``sidecar/optimize.proto`` and the C++
client shim in ``sidecar/cc_client.cc``."""

from .server import OptimizerSidecar

__all__ = ["OptimizerSidecar"]
