"""Optimizer sidecar server — the framework's cross-language boundary.

Rebuild of the SURVEY §5.8 contract: external (JVM) callers keep their own
monitor/executor and delegate only the search —
``Optimize(FlattenedClusterModel, GoalConfig) -> MoveList`` — to this
process sitting next to the TPU. Frames are 4-byte big-endian
length-prefixed protobuf messages over TCP (the gRPC unary wire shape
without the grpc runtime, which is not in this image; ``sidecar/
optimize.proto`` is drop-in for a grpc service definition). The C++ client
shim (``sidecar/cc_client.cc``) is the native half a JVM/broker-side
integration links against.
"""

from __future__ import annotations

import socketserver
import struct
import threading
import time

import numpy as np

# protoc output lives in sidecar/ at the repo root
import importlib
import os
import sys

_SIDECAR_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "sidecar")
if _SIDECAR_DIR not in sys.path:
    sys.path.insert(0, _SIDECAR_DIR)
optimize_pb2 = importlib.import_module("optimize_pb2")


def _model_from_proto(m) -> tuple:
    import jax.numpy as jnp
    from ..model.flat import FlatClusterModel
    from ..model.spec import ClusterMetadata, _round_up
    B, P, R = m.num_brokers, m.num_partitions, m.max_replication_factor
    Bpad, Ppad = _round_up(B, 8), _round_up(P, 128)
    rb = np.full((Ppad, R), Bpad, np.int32)
    raw = np.asarray(m.replica_broker, np.int32).reshape(P, R)
    rb[:P] = np.where(raw < 0, Bpad, raw)
    lead = np.zeros((Ppad, 4), np.float32)
    lead[:P] = np.asarray(m.leader_load, np.float32).reshape(P, 4)
    foll = np.zeros((Ppad, 4), np.float32)
    foll[:P] = np.asarray(m.follower_load, np.float32).reshape(P, 4)
    cap = np.zeros((Bpad, 4), np.float32)
    cap[:B] = np.asarray(m.broker_capacity, np.float32).reshape(B, 4)
    rack = np.zeros(Bpad, np.int32)
    rack[:B] = np.asarray(m.broker_rack, np.int32)
    alive = np.zeros(Bpad, bool)
    alive[:B] = np.asarray(m.broker_alive, bool)
    ptopic = np.full(Ppad, -1, np.int32)
    ptopic[:P] = np.asarray(m.partition_topic, np.int32)
    offline = np.zeros((Ppad, R), bool)
    if m.replica_offline:
        offline[:P] = np.asarray(m.replica_offline, bool).reshape(P, R)
    model = FlatClusterModel(
        replica_broker=jnp.asarray(rb), leader_load=jnp.asarray(lead),
        follower_load=jnp.asarray(foll), partition_topic=jnp.asarray(ptopic),
        partition_valid=jnp.asarray(np.arange(Ppad) < P),
        replica_offline=jnp.asarray(offline),
        replica_pref_pos=jnp.asarray(
            np.tile(np.arange(R, dtype=np.int32), (Ppad, 1))),
        broker_capacity=jnp.asarray(cap), broker_rack=jnp.asarray(rack),
        broker_host=jnp.asarray(np.arange(Bpad, dtype=np.int32)),
        broker_set=jnp.full((Bpad,), -1, jnp.int32),
        broker_alive=jnp.asarray(alive),
        broker_new=jnp.zeros((Bpad,), bool),
        broker_demoted=jnp.zeros((Bpad,), bool),
        broker_broken_disk=jnp.zeros((Bpad,), bool),
        broker_valid=jnp.asarray(np.arange(Bpad) < B))
    num_topics = max(int(ptopic[:P].max()) + 1, 1) if P else 1
    topics = [f"t{i}" for i in range(num_topics)]
    keys = [(topics[ptopic[i]] if ptopic[i] >= 0 else "t0", i)
            for i in range(P)]
    metadata = ClusterMetadata(
        broker_ids=list(range(B)), broker_index={i: i for i in range(B)},
        topics=topics, topic_index={t: i for i, t in enumerate(topics)},
        partition_keys=keys, partition_index={k: i for i, k
                                              in enumerate(keys)},
        racks=[], hosts=[], broker_sets=[])
    return model, metadata


class OptimizeHandler(socketserver.BaseRequestHandler):
    def handle(self):
        while True:
            header = self._recv_exact(4)
            if header is None:
                return
            (length,) = struct.unpack(">I", header)
            payload = self._recv_exact(length)
            if payload is None:
                return
            reply = self.server.app.optimize(payload)   # type: ignore
            self.request.sendall(struct.pack(">I", len(reply)) + reply)

    def _recv_exact(self, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            chunk = self.request.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf


class OptimizerSidecar:
    """One Optimize endpoint; reuses compiled chains across requests."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        from ..analyzer import TpuGoalOptimizer
        self._optimizers: dict[tuple, TpuGoalOptimizer] = {}
        self._server = socketserver.ThreadingTCPServer((host, port),
                                                       OptimizeHandler)
        self._server.app = self
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="sidecar")
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()   # release the listening socket

    def optimize(self, payload: bytes) -> bytes:
        from ..analyzer import (OptimizationOptions, TpuGoalOptimizer,
                                goals_by_name)
        reply = optimize_pb2.MoveList()
        try:
            req = optimize_pb2.OptimizeRequest()
            req.ParseFromString(payload)
            t0 = time.monotonic()
            model, metadata = _model_from_proto(req.model)
            key = tuple(req.config.goals)
            opt = self._optimizers.get(key)
            if opt is None:
                opt = TpuGoalOptimizer(
                    goals=goals_by_name(list(req.config.goals))
                    if req.config.goals else None)
                self._optimizers[key] = opt
            res = opt.optimize(model, metadata, OptimizationOptions(
                seed=int(req.config.seed),
                fast_mode=req.config.fast_mode,
                excluded_topics=frozenset(req.config.excluded_topics),
                skip_hard_goal_check=req.config.skip_hard_goal_check))
            for p in res.proposals:
                mv = reply.moves.add()
                mv.partition = metadata.partition_index[(p.topic,
                                                         p.partition)]
                mv.old_replicas.extend(p.old_replicas)
                mv.new_replicas.extend(p.new_replicas)
            for g in res.goal_results:
                st = reply.goal_stats.add()
                st.name = g.name
                st.violation_before = g.violation_before
                st.violation_after = g.violation_after
            reply.duration_s = time.monotonic() - t0
        except Exception as e:
            reply.error = f"{type(e).__name__}: {e}"
        return reply.SerializeToString()


def main(argv=None) -> int:   # pragma: no cover - thin CLI
    import argparse
    ap = argparse.ArgumentParser(description="tpu-cruise optimizer sidecar")
    ap.add_argument("--port", type=int, default=9096)
    args = ap.parse_args(argv)
    from ..utils.platform import ensure_live_backend
    ensure_live_backend()
    sidecar = OptimizerSidecar(port=args.port)
    sidecar.start()
    print(f"sidecar listening on {sidecar.port}", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        sidecar.stop()
    return 0


if __name__ == "__main__":   # pragma: no cover
    sys.exit(main())
