"""cruise_control_tpu — a TPU-native rebuild of Cruise Control.

Cruise Control (reference: /root/reference, LinkedIn/Shopify) is a control plane
that keeps large Apache Kafka clusters balanced and healthy: it ingests broker /
partition metrics, aggregates them into a windowed workload model, searches for
replica/leader movement proposals that satisfy a prioritized list of *goals*,
executes those proposals against the cluster, and runs anomaly detection with
self-healing on top.

This package keeps the product shape (monitor -> model -> analyzer -> executor
-> detector -> API) but is designed TPU-first:

- the in-memory ``ClusterModel`` (reference: ``model/ClusterModel.java``) is a
  *flattened*, immutable pytree of device arrays
  (``model/flat.py:FlatClusterModel``) instead of a rack->host->broker->replica
  object graph;
- the sequential per-replica greedy ``GoalOptimizer``
  (reference: ``analyzer/GoalOptimizer.java``) is a *batched candidate-plan
  search* (``analyzer/optimizer.py``): thousands of candidate replica/leader
  moves are proposed, masked by vectorized hard-goal legality kernels, scored
  by vmapped soft-goal cost kernels, and applied in jit-compiled
  ``lax.scan`` rounds;
- scale-out over the partition axis uses ``jax.sharding`` / ``shard_map`` over
  a device Mesh (``parallel/``), not threads.

Host-side subsystems (monitor ingestion, executor phases, detectors, REST API)
remain I/O-bound Python, mirroring the reference's behavior contract.
"""

__version__ = "0.1.0"
