"""Core, Kafka-free library layer (reference: cruise-control-core).

Contains the typed config framework, metric definitions, resource model and
the windowed metric-sample aggregator that is the numeric substrate of the
cluster model.
"""
