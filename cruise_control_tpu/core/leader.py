"""Warm-standby leader election + fencing through the admin backend.

One leader owns optimization and execution; standby processes restore
from the shared snapshot (core/snapshot.py) and serve the read endpoints.
The election medium is the **existing admin backend** — the lease record
lives in the dynamic config of a reserved topic (``__cruise_control_ha``),
so any backend implementing the :class:`~cruise_control_tpu.executor.
admin.ClusterAdminClient` SPI (the simulated cluster, a real Kafka via a
plugin) carries it with no extra dependency, and chaos-injected admin
faults exercise the election path like every other RPC.

**Fencing.** Each takeover increments a monotonic ``fencing epoch``; the
executor captures the epoch at execution start and re-checks
:meth:`LeaderElector.is_current` at every phase boundary and progress
poll — a deposed leader's in-flight execution aborts instead of dueling
with the new leader. ``is_current`` is *local*: it compares against the
lease deadline this process last wrote, so a paused/partitioned leader
stops mutating the moment its own lease runs out even when it cannot
reach the admin backend (the classic GC-pause double-leader scenario).
The new leader only acquires after that same deadline passes, so the two
can never overlap (modulo clock skew — ``ha.lease.ms`` must dominate it).

The record is read-modify-write (the admin SPI has no compare-and-set);
two standbys racing the same expired lease within one read-write window
could both claim it. Ticks are cheap, leases are many ticks long, and the
epoch still totally orders any such overlap — acceptable for a control
plane whose mutations are additionally epoch-fenced, and documented in
docs/operations.md.
"""

from __future__ import annotations

import logging
import time as _time

LOG = logging.getLogger(__name__)

#: reserved topic whose dynamic config carries the lease record.
HA_TOPIC = "__cruise_control_ha"

#: lease record keys (stored as strings, like every dynamic config).
_K_LEADER = "ha.leader.id"
_K_EPOCH = "ha.leader.epoch"
_K_UNTIL = "ha.lease.until.ms"

#: sensor group for the HA series (``HA.*``).
HA_SENSOR = "HA"


class NotLeaderError(RuntimeError):
    """An execution endpoint was called on a standby replica. Carries the
    current leader's identity so the API layer can answer 503 with a
    redirect hint (the reference pattern for follower-serving systems)."""

    def __init__(self, message: str, leader_id: str | None = None) -> None:
        super().__init__(message)
        self.leader_id = leader_id


class LeaderElector:
    """Lease-based election over the admin backend's topic-config store.

    Drive :meth:`tick` on the serving cadence (``facade.ha_tick``); read
    :meth:`is_leader` / :attr:`epoch` between ticks. Single-writer per
    process; not thread-safe against concurrent ticks (the facade ticks
    from one loop)."""

    def __init__(self, admin, identity: str, *, lease_ms: int = 15_000,
                 now_ms=None, registry=None, eligible: bool = True) -> None:
        import threading

        from .sensors import MetricRegistry
        self.admin = admin
        self.identity = identity
        self.lease_ms = int(lease_ms)
        #: may this process ever TAKE leadership? An ineligible elector
        #: (a pure read replica: ``replication.replica.promotable=false``)
        #: still ticks — it observes the holder/epoch for /state and the
        #: executor's fence floor — but the takeover branch is closed, so
        #: it can never become the writer no matter how long the lease
        #: stays vacant.
        self.eligible = bool(eligible)
        self._now_ms = now_ms or (lambda: int(_time.time() * 1000))
        #: serializes tick/keepalive/resign — the serving loop ticks from
        #: the main thread while a blocked execution keepalives from its
        #: worker thread.
        self._tick_lock = threading.Lock()
        self._role = "standby"
        #: fencing epoch under which THIS process last held leadership
        #: (0 = never led); stable across renewals, bumps on takeover.
        self.epoch = 0
        #: highest epoch ever observed in the record — the monotonicity
        #: floor a takeover must exceed (snapshot restore seeds it too,
        #: so a restarted leader can never reuse a pre-crash epoch even
        #: when the admin record was lost with the cluster).
        self.observed_epoch = 0
        self._lease_until = 0
        self._last_leader_id: str | None = None
        #: decision journal (core/events.py), attached by the facade —
        #: epoch transitions are THE election decisions worth recording.
        self.journal = None
        self.registry = registry or MetricRegistry()
        name = MetricRegistry.name
        self._takeovers = self.registry.counter(name(HA_SENSOR,
                                                     "takeovers"))
        self._election_errors = self.registry.meter(
            name(HA_SENSOR, "election-error-rate"))
        self.registry.gauge(name(HA_SENSOR, "is-leader"),
                            lambda: int(self.is_leader()))
        self.registry.gauge(name(HA_SENSOR, "fencing-epoch"),
                            lambda: self.epoch or None)

    # ------------------------------------------------------------- reads
    @property
    def role(self) -> str:
        return self._role

    def is_leader(self) -> bool:
        """Leader AND inside the lease we last wrote. Local-only: a
        leader that cannot renew self-demotes at its own deadline."""
        return (self._role == "leader"
                and self._now_ms() < self._lease_until)

    def is_current(self, token: int | None) -> bool:
        """The executor's fencing check: does this process still hold
        leadership under the epoch captured at execution start?"""
        return token is not None and self.epoch == token \
            and self.is_leader()

    def leader_id(self) -> str | None:
        """Last observed leader identity (ourselves when leading)."""
        return self.identity if self.is_leader() else self._last_leader_id

    def observe_epoch_floor(self, epoch: int) -> None:
        """Raise the takeover floor (snapshot restore: a pre-crash epoch
        must never be reused by the restarted process)."""
        self.observed_epoch = max(self.observed_epoch, int(epoch or 0))

    # -------------------------------------------------------------- tick
    def tick(self, now_ms: int | None = None) -> str:
        """One election round: renew our lease, or take over an expired /
        vacant one, or observe the current leader. Returns the role."""
        with self._tick_lock:
            return self._tick_locked(now_ms)

    def keepalive(self, now_ms: int | None = None) -> None:
        """Pure lease renewal — called from the executor's fence check so
        a leader blocked in a long execution keeps its lease alive for as
        long as it is actually running and can reach the admin backend.
        Strictly weaker than :meth:`tick`: it only ever EXTENDS a lease
        that is still current, never takes over — a leader that wakes up
        past its own deadline (the GC-pause scenario) finds its lease
        gone and the fence check aborts the execution."""
        now = now_ms if now_ms is not None else self._now_ms()
        with self._tick_lock:
            if self._role == "leader" and now < self._lease_until:
                if self._write(self.epoch, now + self.lease_ms):
                    self._lease_until = now + self.lease_ms

    def _tick_locked(self, now_ms: int | None = None) -> str:
        now = now_ms if now_ms is not None else self._now_ms()
        try:
            record = self.admin.describe_topic_config(HA_TOPIC)
        except Exception as exc:   # noqa: BLE001 — admin faults are chaos fodder
            self._election_errors.mark()
            LOG.warning("leader-election read failed (%s: %s); %s",
                        type(exc).__name__, exc,
                        "holding lease locally" if self._role == "leader"
                        else "staying standby")
            # Cannot see the record: a leader keeps leading only while
            # its own lease holds (is_leader() checks the deadline);
            # a standby stays standby.
            if self._role == "leader" and now >= self._lease_until:
                self._demote("lease expired during election outage")
            return self._role
        holder = record.get(_K_LEADER) or None
        epoch = int(record.get(_K_EPOCH, "0") or 0)
        until = int(record.get(_K_UNTIL, "0") or 0)
        self.observed_epoch = max(self.observed_epoch, epoch)
        self._last_leader_id = holder

        if holder == self.identity and self._role == "leader" \
                and now < until:
            # Renewal: same epoch, extended lease.
            if self._write(self.epoch, now + self.lease_ms):
                self._lease_until = now + self.lease_ms
            elif now >= self._lease_until:
                self._demote("lease expired and renewal failed")
        elif not self.eligible:
            # Not promotable: observe only. The vacancy is someone
            # else's to claim.
            self._role = "standby"
        elif holder is None or now >= until or holder == self.identity:
            # Vacant, expired, or OUR OWN lease from a previous
            # incarnation (a leader that crashed and restarted under the
            # same identity within its lease): reclaimable immediately —
            # nobody else can hold it — but only under a strictly higher
            # epoch, never by "renewing" with this incarnation's epoch 0
            # (which would both wedge leadership forever and regress the
            # recorded epoch below the predecessor's mutations).
            new_epoch = max(epoch, self.observed_epoch, self.epoch) + 1
            if self._write(new_epoch, now + self.lease_ms):
                was = self._role
                self.epoch = new_epoch
                self.observed_epoch = max(self.observed_epoch, new_epoch)
                self._lease_until = now + self.lease_ms
                self._role = "leader"
                self._last_leader_id = self.identity
                self._takeovers.inc()
                if self.journal is not None:
                    self.journal.record(
                        "election", "took-leadership", severity="warn",
                        epoch=new_epoch,
                        detail={"identity": self.identity,
                                "previousHolder": holder,
                                "previousEpoch": epoch, "wasRole": was})
                LOG.warning(
                    "%s took leadership (fencing epoch %d, previous "
                    "holder %s, was %s)", self.identity, new_epoch,
                    holder or "<none>", was)
        else:
            if self._role == "leader":
                self._demote(f"deposed by {holder} (epoch {epoch})")
            self._role = "standby"
        return self._role

    def resign(self, now_ms: int | None = None) -> None:
        """Clean-shutdown handoff: expire our lease NOW (epoch kept in
        the record for the successor's floor) so a standby takes over on
        its next tick instead of waiting out ``ha.lease.ms``."""
        with self._tick_lock:
            if self._role != "leader":
                return
            if self._write(self.epoch, 0, holder=""):
                LOG.info("%s resigned leadership (epoch %d)",
                         self.identity, self.epoch)
            self._demote("resigned")

    # ----------------------------------------------------------- helpers
    def _demote(self, why: str) -> None:
        if self._role == "leader":
            if self.journal is not None:
                self.journal.record(
                    "election", "stepped-down", severity="warn",
                    epoch=self.epoch,
                    detail={"identity": self.identity, "why": why})
            LOG.warning("%s stepping down to standby: %s (epoch %d)",
                        self.identity, why, self.epoch)
        self._role = "standby"
        self._lease_until = 0

    def _write(self, epoch: int, until_ms: int,
               holder: str | None = None) -> bool:
        try:
            self.admin.alter_topic_config(HA_TOPIC, {
                _K_LEADER: self.identity if holder is None else holder,
                _K_EPOCH: str(epoch),
                _K_UNTIL: str(int(until_ms)),
            })
            return True
        except Exception as exc:   # noqa: BLE001
            self._election_errors.mark()
            LOG.warning("leader-election write failed (%s: %s)",
                        type(exc).__name__, exc)
            return False

    def to_json(self) -> dict:
        return {"identity": self.identity,
                "role": "leader" if self.is_leader() else "standby",
                "promotable": self.eligible,
                "leaderId": self.leader_id(),
                "fencingEpoch": self.epoch or None,
                "observedEpoch": self.observed_epoch or None,
                "leaseUntilMs": self._lease_until or None,
                "takeovers": self._takeovers.count}
