"""Generic anomaly SPIs (ref ``cruise-control-core``'s ``detector/`` package:
``Anomaly.java``, ``AnomalyType.java``, ``MetricAnomalyFinder.java`` and
``metricanomaly/PercentileMetricAnomalyFinder.java``)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Hashable, Protocol, Sequence

import numpy as np


class Anomaly(Protocol):
    """ref Anomaly.java:51."""

    anomaly_id: str

    def fix(self) -> bool: ...

    def reason(self) -> str: ...


@dataclass(frozen=True)
class MetricAnomaly:
    """One detected metric anomaly (ref MetricAnomaly SPI)."""

    entity: Hashable
    metric_id: int
    current_value: float
    threshold: float
    description: str


class PercentileMetricAnomalyFinder:
    """ref metricanomaly/PercentileMetricAnomalyFinder.java:201.

    An entity's *current* (latest-window) metric value is anomalous when it
    exceeds ``upper_percentile`` of its own history times
    ``upper_margin`` (or sinks below ``lower_percentile`` divided by
    ``lower_margin``). Needs at least ``min_history_windows`` of history.
    Vectorized: one call scores every entity x metric at once.
    """

    def __init__(self, *, upper_percentile: float = 95.0,
                 lower_percentile: float = 2.0, upper_margin: float = 0.5,
                 lower_margin: float = 0.2, min_history_windows: int = 3,
                 interested_metrics: Sequence[int] | None = None) -> None:
        self.upper_percentile = upper_percentile
        self.lower_percentile = lower_percentile
        self.upper_margin = upper_margin
        self.lower_margin = lower_margin
        self.min_history_windows = min_history_windows
        self.interested_metrics = (None if interested_metrics is None
                                   else list(interested_metrics))

    def anomalies(self, windows_by_entity: dict[Hashable, np.ndarray]
                  ) -> list[MetricAnomaly]:
        """``windows_by_entity``: entity -> [num_metrics, num_windows] with
        the newest window last (history = all but last)."""
        out: list[MetricAnomaly] = []
        for entity, values in windows_by_entity.items():
            if values.shape[1] < self.min_history_windows + 1:
                continue
            history = values[:, :-1]
            current = values[:, -1]
            upper = np.percentile(history, self.upper_percentile, axis=1)
            lower = np.percentile(history, self.lower_percentile, axis=1)
            metric_ids = (range(values.shape[0])
                          if self.interested_metrics is None
                          else self.interested_metrics)
            for m in metric_ids:
                hi = upper[m] * (1.0 + self.upper_margin)
                lo = lower[m] * (1.0 - self.lower_margin)
                if current[m] > hi and upper[m] > 0:
                    out.append(MetricAnomaly(
                        entity, m, float(current[m]), float(hi),
                        f"metric {m} of {entity} = {current[m]:.2f} above "
                        f"p{self.upper_percentile:.0f} margin {hi:.2f}"))
                elif current[m] < lo:
                    out.append(MetricAnomaly(
                        entity, m, float(current[m]), float(lo),
                        f"metric {m} of {entity} = {current[m]:.2f} below "
                        f"p{self.lower_percentile:.0f} margin {lo:.2f}"))
        return out
