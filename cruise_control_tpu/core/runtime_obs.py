"""Device runtime observability: compile lifecycle, memory/padding, and
host<->device transfer accounting.

The span tracer (:mod:`core.tracing`) answers "where did this request's
latency go" and the sensors answer "how long do proposals take" — but the
JAX runtime underneath both stayed a black box: a pass-signature change
silently invalidates every persistent-cache entry (the PR 3 incident), a
shape drift quietly recompiles a 15-goal chain, and nobody can say how
many bytes a propose cycle ships across the host<->device boundary. This
module makes those costs first-class observables:

- **Compile lifecycle.** Every jit/AOT program in the repo is wrapped in
  a :class:`TrackedProgram` (the optimizer pass chain, the fused/aux
  programs, hard-goal audit fns, the branched shard_map search, the
  what-if sweep programs). Each dispatch checks the program's in-process
  jit cache size before/after the call — growth means XLA specialized a
  new executable — and records a :class:`CompileEvent` carrying the
  shape-bucket key, wall time, the *trigger* (``cold`` = first compile
  for that bucket, ``aot-warmup`` = an ahead-of-time warmup compile or
  its follow-up dispatch-cache fill, ``signature-change`` = a RECOMPILE
  of a bucket this process had already compiled — the alarming one), and
  whether the persistent compilation cache answered (``persistent-hit``
  vs ``miss``, read from ``jax.monitoring`` events when available). Every
  event also lands as a ``compile.<program>`` span in the tracer, so
  recompile storms are visible in /trace next to the work they stall.
- **Transfer accounting.** ``record_h2d``/``record_d2h`` counters fed by
  the known boundary crossings (``FlatClusterModel.from_numpy`` uploads,
  the optimizer's end-of-chain fetches, the proposal diff's host reads,
  the what-if batch upload + result fetch). :meth:`DeviceStatsCollector.cycle`
  brackets one propose cycle and snapshots the per-cycle deltas.
- **Device memory.** ``memory_snapshot`` reads the backend allocator's
  ``memory_stats()`` (bytes_in_use / peak_bytes_in_use on TPU/GPU).
  **CPU fallback:** the CPU PJRT client reports no allocator stats
  (``memory_stats() is None``), so live bytes are summed over
  ``jax.live_arrays()`` — logical array bytes, which miss XLA scratch
  but track model/state residency faithfully; ``source`` names which
  path produced the numbers.
- **Padding waste.** The flat model is padded to static shape buckets;
  :meth:`observe_padding` (fed host-side by the monitor's assemblers,
  zero device syncs) and :meth:`padding_from_model` (reads the valid
  masks — a device fetch, debug/test surface) record what fraction of
  the partition/broker/replica-slot axes is padding.

Surfaced four ways: ``DeviceRuntime.*`` Prometheus families on
``/metrics``, ``compile.<program>`` spans in /trace, the ``/devicestats``
endpoint (JSON + plaintext), and the ``DeviceStats`` substate of
``/state``. One process-wide default collector (:func:`default_collector`)
keeps wiring optional, exactly like :func:`~.tracing.default_tracer`.

Design constraints (same bar as the tracer): **zero extra device syncs**
on the hot path — shape keys come from ``.shape``/``.dtype`` metadata,
transfer bytes from ``nbytes`` of already-fetched host arrays, and the
memory gauges only run at scrape time; overhead on the warm propose path
is gated <2% by ``bench.py`` (``run_device_stats_bench``).
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from collections import deque

from .sensors import MetricRegistry

LOG = logging.getLogger(__name__)

#: sensor group for every collector-owned series (``DeviceRuntime.*``).
DEVICE_RUNTIME_SENSOR = "DeviceRuntime"

#: compile-event triggers (the taxonomy /devicestats reports).
TRIGGER_COLD = "cold"
TRIGGER_AOT = "aot-warmup"
TRIGGER_SIGNATURE = "signature-change"

# --------------------------------------------------------------------------
# jax.monitoring capture: compile events fire on the thread doing the
# compile, so a thread-local capture record (installed around every
# tracked call) attributes backend-compile durations and persistent-cache
# hit/miss counters to the program that triggered them. The listeners are
# registered once per process and are inert (one attribute read) when no
# tracked program is active on the thread.
# --------------------------------------------------------------------------

_tls = threading.local()
_listeners_installed = False
_install_lock = threading.Lock()


def _active_capture():
    return getattr(_tls, "capture", None)


def _begin_capture():
    prev = getattr(_tls, "capture", None)
    rec = {"hits": 0, "misses": 0, "backend_s": 0.0}
    _tls.capture = rec
    return rec, prev


def _end_capture(prev) -> None:
    _tls.capture = prev


def _event_listener(name, *args, **kwargs):
    rec = _active_capture()
    if rec is None:
        return
    if name.endswith("cache_hits"):
        rec["hits"] += 1
    elif name.endswith("cache_misses"):
        rec["misses"] += 1


def _duration_listener(name, duration, *args, **kwargs):
    rec = _active_capture()
    if rec is None:
        return
    if name.endswith("backend_compile_duration"):
        rec["backend_s"] += float(duration)


def _install_listeners() -> None:
    global _listeners_installed
    with _install_lock:
        if _listeners_installed:
            return
        try:
            import jax.monitoring as monitoring
            monitoring.register_event_listener(_event_listener)
            monitoring.register_event_duration_secs_listener(
                _duration_listener)
        except Exception:  # pragma: no cover — monitoring API drift
            LOG.debug("jax.monitoring unavailable; compile cache hit/miss "
                      "classification degraded to 'unknown'", exc_info=True)
        _listeners_installed = True


# --------------------------------------------------------------------------
# shape buckets
# --------------------------------------------------------------------------

def shape_key(*trees) -> tuple:
    """Hashable (shape, dtype, sharding) signature over the pytree leaves
    — the same bucket notion the engine's warmup events key on. Metadata
    only: never touches device buffers.

    Sharding is part of the bucket: jit specializes per input layout, so
    dispatching the same shapes under a different mesh (or device count)
    genuinely compiles a NEW executable — without the sharding in the
    key that compile would be misclassified as an alarming
    ``signature-change`` recompile of an already-compiled bucket. Host
    numpy leaves (no ``sharding``) key as None."""
    import jax
    return tuple((tuple(getattr(x, "shape", ())),
                  str(getattr(x, "dtype", type(x).__name__)),
                  getattr(x, "sharding", None))
                 for x in jax.tree_util.tree_leaves(trees))


def bucket_label(key: tuple) -> str:
    """Compact stable label for a shape bucket (full keys are dozens of
    leaves): leaf count + a hash. Humans correlate events by equality, not
    by reading the shapes back."""
    return f"leaves{len(key)}-{abs(hash(key)) % 0xFFFFFF:06x}"


def device_bytes(leaf) -> int:
    """Actual allocated bytes for one array (metadata read, no sync).

    For a sharded ``jax.Array``, ``.nbytes`` reports the GLOBAL logical
    size — as if every device held the whole thing — which is wrong in
    both directions under a mesh: a partition-sharded [P, 4] plane costs
    each device only 1/Nth of it, while a replicated [B, 4] aggregate
    costs N whole copies. Summing the addressable shards' sizes reports
    what the allocator actually holds (sharded -> logical total split
    across devices, replicated -> N x logical). Host numpy arrays fall
    through to plain ``nbytes``."""
    shards = getattr(leaf, "addressable_shards", None)
    if shards is not None:
        try:
            return sum(int(s.data.nbytes) for s in shards)
        except Exception:  # pragma: no cover — deleted/donated buffers
            pass
    nbytes = getattr(leaf, "nbytes", None)
    return int(nbytes) if nbytes is not None else 0


def tree_bytes(tree) -> int:
    """Total actual bytes over the pytree leaves (host numpy or device
    arrays; metadata read, no sync). Device leaves are counted at their
    addressable-shard sizes — see :func:`device_bytes`."""
    import jax
    return sum(device_bytes(leaf)
               for leaf in jax.tree_util.tree_leaves(tree))


class CompileEvent:
    """One observed compilation (or AOT warmup compile)."""

    __slots__ = ("program", "bucket", "trigger", "cache", "duration_s",
                 "backend_compile_s", "time_s", "thread_name")

    def __init__(self, program: str, bucket: str, trigger: str, cache: str,
                 duration_s: float, backend_compile_s: float,
                 time_s: float, thread_name: str) -> None:
        self.program = program
        self.bucket = bucket
        self.trigger = trigger
        self.cache = cache
        self.duration_s = duration_s
        self.backend_compile_s = backend_compile_s
        self.time_s = time_s
        self.thread_name = thread_name

    def to_json(self) -> dict:
        return {"program": self.program, "shapeBucket": self.bucket,
                "trigger": self.trigger, "cache": self.cache,
                "durationMs": round(self.duration_s * 1e3, 3),
                "backendCompileMs": round(self.backend_compile_s * 1e3, 3),
                "thread": self.thread_name}


class _ProgramStats:
    __slots__ = ("name", "compiles", "aot_compiles", "dispatches",
                 "buckets")

    def __init__(self, name: str) -> None:
        self.name = name
        self.compiles = 0
        self.aot_compiles = 0
        self.dispatches = 0
        #: distinct shape buckets observed under this name (display only;
        #: recompile classification is per TrackedProgram INSTANCE — two
        #: chains built with different configs legitimately share a
        #: program name, and the second's first compile is cold, not a
        #: signature change).
        self.buckets: set = set()

    def to_json(self) -> dict:
        return {"compiles": self.compiles, "aotCompiles": self.aot_compiles,
                "dispatches": self.dispatches,
                "shapeBuckets": len(self.buckets)}


class _TrackedLowered:
    """``TrackedProgram.lower(...)`` result: ``.compile()`` records the
    AOT compile event (kept for callers that use the lower/compile idiom
    directly; :meth:`TrackedProgram.aot_compile` is the ergonomic form)."""

    __slots__ = ("_program", "_lowered", "_key", "_parent_id")

    def __init__(self, program: "TrackedProgram", lowered, key,
                 parent_id) -> None:
        self._program = program
        self._lowered = lowered
        self._key = key
        self._parent_id = parent_id

    def compile(self, *args, **kwargs):
        p = self._program
        rec, prev = _begin_capture()
        t0 = time.perf_counter()
        try:
            out = self._lowered.compile(*args, **kwargs)
        finally:
            _end_capture(prev)
        with p.collector._lock:
            p.aot_seen.add(self._key)
        p.collector._on_compile(p.name, self._key,
                                time.perf_counter() - t0, rec,
                                trigger=TRIGGER_AOT,
                                parent_id=self._parent_id)
        return out


class TrackedProgram:
    """Wrapper around one jitted callable: counts dispatches, detects
    compiles via the program's in-process jit cache size (``_cache_size``
    where available, first-seen shape buckets otherwise), and forwards
    ``lower``/AOT compiles with the same bookkeeping. Transparent: args,
    donation, and outputs pass straight through; a disabled collector
    reduces a call to one attribute check.

    The seen/aot-warmed bucket sets live HERE, not on the name-keyed
    stats: recompile classification must match the cache the delta was
    measured on (this instance's), or two chains sharing a program name
    would flag each other's cold compiles as signature changes."""

    __slots__ = ("collector", "name", "fn", "seen", "aot_seen")

    def __init__(self, collector: "DeviceStatsCollector", name: str,
                 fn) -> None:
        self.collector = collector
        self.name = name
        self.fn = fn
        #: buckets whose executable THIS wrapper's jit cache already
        #: holds — a compile for a member is a genuine recompile.
        self.seen: set = set()
        #: buckets warmed ahead of time (AOT executables bypass the jit
        #: dispatch cache, so the first dispatch still "compiles" — that
        #: fill is warmup, not a recompile).
        self.aot_seen: set = set()

    def _cache_size(self):
        try:
            return self.fn._cache_size()
        except Exception:
            return None

    def __call__(self, *args):
        c = self.collector
        if not c.enabled:
            return self.fn(*args)
        key = shape_key(args)
        before = self._cache_size()
        rec, prev = _begin_capture()
        t0 = time.perf_counter()
        try:
            out = self.fn(*args)
        finally:
            _end_capture(prev)
        duration = time.perf_counter() - t0
        after = self._cache_size()
        c._on_dispatch(self, key, before, after, duration, rec)
        return out

    def lower(self, *args, parent_id="current", **kwargs):
        """AOT entry: the returned handle's ``.compile()`` records an
        ``aot-warmup`` compile event (and a ``compile.<program>`` span,
        parented at ``parent_id`` — warmup pool workers have no active
        span of their own)."""
        if not self.collector.enabled:
            return self.fn.lower(*args, **kwargs)
        return _TrackedLowered(self, self.fn.lower(*args, **kwargs),
                               shape_key(args), parent_id)

    def aot_compile(self, args: tuple, parent_id="current") -> None:
        """``lower(*args).compile()`` with AOT bookkeeping — the warmup
        pools' per-job entry point."""
        self.lower(*args, parent_id=parent_id).compile()


class DeviceStatsCollector:
    """The process's device-runtime ledger (see module docstring).

    Thread-safe; ``enabled = False`` turns every hook into a no-op (the
    bench's overhead A/B switch, mirroring ``SpanTracer.enabled``).
    """

    def __init__(self, registry: MetricRegistry | None = None,
                 tracer=None, max_events: int = 256) -> None:
        from .tracing import default_tracer
        _install_listeners()
        self.registry = registry or MetricRegistry()
        self.tracer = tracer or default_tracer()
        self.enabled = True
        self._lock = threading.Lock()
        self._programs: dict[str, _ProgramStats] = {}
        self._events: deque[CompileEvent] = deque(maxlen=max_events)
        self._epoch = time.perf_counter()
        self._h2d_bytes = 0
        self._d2h_bytes = 0
        self._last_cycle: dict | None = None
        #: bumps when an outermost cycle() records — the /devicestats
        #: render cache keys on it so cached reads republish per cycle.
        self.cycle_seq = 0
        self._padding: dict | None = None
        self._peak_live_bytes = 0
        #: high-water allocator peak (bytes_in_use peaks include XLA
        #: scratch the live-arrays sum cannot see) — the budget gate
        #: compares against the worst of the per-device peaks.
        self._peak_alloc_bytes = 0
        #: high-water PER-DEVICE live bytes (max over devices of the
        #: bytes its addressable shards hold). The HBM budget is a
        #: per-device quantity: an N-way-sharded model's cross-device
        #: total never shrinks under sharding, so gating on the total
        #: would flag models that fit each device comfortably.
        self._peak_device_live_bytes = 0
        #: configured budgets (None = unenforced): padding waste as a
        #: max pct over the observed axes, device memory as peak bytes.
        #: serve.py wires them from device.padding.waste.budget.pct /
        #: device.hbm.budget.bytes; the 10Kx1M bench tier asserts them.
        self._padding_budget_pct: float | None = None
        self._hbm_budget_bytes: int | None = None
        name = MetricRegistry.name
        g = DEVICE_RUNTIME_SENSOR
        self._compile_counter = self.registry.counter(
            name(g, "compile-events"))
        self._recompile_counter = self.registry.counter(
            name(g, "recompile-events"))
        self._aot_counter = self.registry.counter(
            name(g, "aot-compile-events"))
        self._compile_timer = self.registry.timer(name(g, "compile-timer"))
        self._h2d_counter = self.registry.counter(
            name(g, "h2d-transfer-bytes"))
        self._d2h_counter = self.registry.counter(
            name(g, "d2h-transfer-bytes"))
        self.registry.gauge(name(g, "last-cycle-h2d-bytes"),
                            lambda: (self._last_cycle or {}).get("h2dBytes"))
        self.registry.gauge(name(g, "last-cycle-d2h-bytes"),
                            lambda: (self._last_cycle or {}).get("d2hBytes"))
        self.registry.gauge(
            name(g, "last-cycle-compile-events"),
            lambda: (self._last_cycle or {}).get("compileEvents"))
        self.registry.gauge(name(g, "device-live-bytes"),
                            lambda: self.memory_snapshot()["liveBytes"])
        # Cached read only: the live gauge above (rendered first — sorted
        # name order) already refreshed the peak; re-running a full
        # snapshot here would enumerate jax.live_arrays() twice per
        # scrape.
        self.registry.gauge(name(g, "device-peak-live-bytes"),
                            lambda: self._peak_live_bytes or None)
        self.registry.gauge(
            name(g, "padding-waste-partition-pct"),
            lambda: (self._padding or {}).get("partitionWastePct"))
        self.registry.gauge(
            name(g, "padding-waste-broker-pct"),
            lambda: (self._padding or {}).get("brokerWastePct"))
        self.registry.gauge(
            name(g, "padding-waste-replica-slot-pct"),
            lambda: (self._padding or {}).get("replicaSlotWastePct"))

    # -------------------------------------------------------- programs
    def track(self, name: str, fn) -> TrackedProgram:
        """Wrap a jitted callable under ``name``. Stats are keyed by name,
        so re-built chains (new config, same programs) accumulate into one
        ledger row; the wrapper itself is stateless."""
        with self._lock:
            self._programs.setdefault(name, _ProgramStats(name))
        return TrackedProgram(self, name, fn)

    def _stats(self, name: str) -> _ProgramStats:
        with self._lock:
            st = self._programs.get(name)
            if st is None:
                st = self._programs[name] = _ProgramStats(name)
            return st

    def _dispatch_counter_for(self, name: str):
        return self.registry.counter(MetricRegistry.name(
            DEVICE_RUNTIME_SENSOR, f"program-{name}-dispatch-count"))

    def _compile_counter_for(self, name: str):
        return self.registry.counter(MetricRegistry.name(
            DEVICE_RUNTIME_SENSOR, f"program-{name}-compile-count"))

    def _on_dispatch(self, program: "TrackedProgram", key, cache_before,
                     cache_after, duration_s: float, rec: dict) -> None:
        st = self._stats(program.name)
        if cache_before is not None and cache_after is not None:
            compiled = cache_after > cache_before
        else:
            # Fallback when the jit wrapper exposes no cache introspection
            # (API drift): first sight of a bucket = compile. Misses
            # same-bucket recompiles — documented degradation.
            with self._lock:
                compiled = (key not in program.seen
                            and key not in program.aot_seen)
        with self._lock:
            st.dispatches += 1
            st.buckets.add(key)
        self._dispatch_counter_for(program.name).inc()
        if compiled:
            with self._lock:
                if key in program.seen:
                    trigger = TRIGGER_SIGNATURE
                elif key in program.aot_seen:
                    # Dispatch-cache fill after an AOT warmup: the
                    # executable was compiled ahead of time, this dispatch
                    # re-specializes into the jit cache (persistent cache
                    # makes it a deserialize) — warmup, not a recompile.
                    trigger = TRIGGER_AOT
                else:
                    trigger = TRIGGER_COLD
            self._on_compile(program.name, key, duration_s, rec,
                             trigger=trigger, parent_id="current",
                             aot=False)
        with self._lock:
            program.seen.add(key)

    def _on_compile(self, name: str, key, duration_s: float, rec: dict,
                    *, trigger: str, parent_id=None, aot=None) -> None:
        """Record one compile event (dispatch-detected or AOT)."""
        aot = trigger == TRIGGER_AOT if aot is None else aot
        if rec["misses"]:
            cache = "miss"
        elif rec["hits"]:
            cache = "persistent-hit"
        elif rec["backend_s"] > 0:
            cache = "miss"          # compiled with no persistent cache on
        else:
            cache = "unknown"
        event = CompileEvent(
            program=name, bucket=bucket_label(key), trigger=trigger,
            cache=cache, duration_s=duration_s,
            backend_compile_s=rec["backend_s"],
            time_s=time.perf_counter() - self._epoch,
            thread_name=threading.current_thread().name)
        st = self._stats(name)
        with self._lock:
            self._events.append(event)
            st.buckets.add(key)
            if aot:
                st.aot_compiles += 1
            else:
                st.compiles += 1
        (self._aot_counter if aot else self._compile_counter).inc()
        if trigger == TRIGGER_SIGNATURE:
            self._recompile_counter.inc()
            LOG.warning(
                "program %s RECOMPILED for an already-compiled shape "
                "bucket %s (%.2fs, cache=%s) — pass-signature change?",
                name, event.bucket, duration_s, cache)
        self._compile_counter_for(name).inc()
        self._compile_timer.update(duration_s)
        # Visible next to the work it stalled: a compile.<program> span.
        self.tracer.record(f"compile.{name}", duration_s,
                           parent_id=parent_id,
                           attrs={"trigger": trigger, "cache": cache,
                                  "shapeBucket": event.bucket})

    # -------------------------------------------------------- transfers
    def record_h2d(self, nbytes: int) -> None:
        if not self.enabled or not nbytes:
            return
        with self._lock:
            self._h2d_bytes += int(nbytes)
        self._h2d_counter.inc(int(nbytes))

    def record_d2h(self, nbytes: int) -> None:
        if not self.enabled or not nbytes:
            return
        with self._lock:
            self._d2h_bytes += int(nbytes)
        self._d2h_counter.inc(int(nbytes))

    #: staticmethod re-exports so call sites need only the collector.
    tree_bytes = staticmethod(tree_bytes)
    device_bytes = staticmethod(device_bytes)

    @contextlib.contextmanager
    def cycle(self, label: str = "propose"):
        """Bracket one logical cycle (a propose, a sweep): on exit the
        h2d/d2h/compile deltas land in ``last_cycle`` (and its gauges).
        Reentrant per thread — only the outermost cycle records, so the
        facade can wrap monitor+optimize while the optimizer wraps
        itself. Concurrent cycles on different threads share the global
        counters; attribution is last-writer-wins (documented)."""
        if not self.enabled:
            yield
            return
        depth = getattr(_tls, "cycle_depth", 0)
        _tls.cycle_depth = depth + 1
        if depth:
            try:
                yield
            finally:
                _tls.cycle_depth = depth
            return
        with self._lock:
            h2d0, d2h0 = self._h2d_bytes, self._d2h_bytes
        compiles0 = self.compile_count() + self.aot_compile_count()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            _tls.cycle_depth = depth
            with self._lock:
                h2d, d2h = self._h2d_bytes - h2d0, self._d2h_bytes - d2h0
            self._last_cycle = {
                "label": label,
                "h2dBytes": h2d, "d2hBytes": d2h,
                "transferBytes": h2d + d2h,
                "compileEvents": (self.compile_count()
                                  + self.aot_compile_count() - compiles0),
                "durationMs": round((time.perf_counter() - t0) * 1e3, 3)}
            self.cycle_seq += 1

    @property
    def last_cycle(self) -> dict | None:
        return self._last_cycle

    # ----------------------------------------------------------- memory
    def memory_snapshot(self) -> dict:
        """Live/peak device memory. Backend allocator stats where the
        platform provides them (TPU/GPU ``memory_stats()``); CPU fallback
        sums ``jax.live_arrays()`` (see module docstring)."""
        live = peak_alloc = in_use = None
        source = "unavailable"
        num_live = None
        device_live = None
        try:
            import jax
            arrays = jax.live_arrays()
            num_live = len(arrays)
            # Addressable-shard sizes, not logical nbytes: under a mesh a
            # replicated array really holds N copies and a sharded one
            # 1/Nth per device — see device_bytes. Per-device buckets as
            # well: the HBM budget compares against the WORST single
            # device, not the cross-device total (which sharding never
            # shrinks).
            live = 0
            per_device: dict = {}
            for a in arrays:
                shards = getattr(a, "addressable_shards", None)
                if shards is None:
                    live += device_bytes(a)
                    continue
                try:
                    arr_per_device: dict = {}
                    for s in shards:
                        nbytes = int(s.data.nbytes)
                        arr_per_device[s.device] = (
                            arr_per_device.get(s.device, 0) + nbytes)
                except Exception:
                    # Deleted/donated buffer mid-walk (same guard as
                    # device_bytes): fall back to what nbytes reports,
                    # losing only this array's per-device attribution —
                    # the snapshot (and the allocator read below) must
                    # not abort on one bad array.
                    live += device_bytes(a)
                else:
                    for d, b in arr_per_device.items():
                        live += b
                        per_device[d] = per_device.get(d, 0) + b
            device_live = max(per_device.values(), default=live)
            source = "live_arrays"
            stats = jax.devices()[0].memory_stats()
            if stats:
                in_use = stats.get("bytes_in_use")
                peak_alloc = stats.get("peak_bytes_in_use")
                source = "device_memory_stats"
        except Exception:  # pragma: no cover — backend quirks
            pass
        if live is not None:
            with self._lock:
                self._peak_live_bytes = max(self._peak_live_bytes, live)
                self._peak_device_live_bytes = max(
                    self._peak_device_live_bytes, device_live or 0)
        if peak_alloc:
            with self._lock:
                self._peak_alloc_bytes = max(self._peak_alloc_bytes,
                                             int(peak_alloc))
        return {"liveBytes": live, "numLiveArrays": num_live,
                "peakLiveBytes": self._peak_live_bytes or None,
                "maxDeviceLiveBytes": device_live,
                "peakDeviceLiveBytes": self._peak_device_live_bytes or None,
                "allocatorBytesInUse": in_use,
                "allocatorPeakBytes": peak_alloc,
                "source": source}

    # ---------------------------------------------------------- padding
    def observe_padding(self, *, partitions: int, partitions_padded: int,
                        brokers: int, brokers_padded: int,
                        replica_slots_used: int | None = None,
                        replica_slots_total: int | None = None) -> dict:
        """Record padding-waste ratios from host-side counts (the
        monitor's assemblers know them before any device upload — zero
        syncs)."""
        def waste(real, padded):
            if not padded:
                return 0.0
            return round(100.0 * (1.0 - real / padded), 3)
        padding = {
            "partitions": partitions, "partitionsPadded": partitions_padded,
            "partitionWastePct": waste(partitions, partitions_padded),
            "brokers": brokers, "brokersPadded": brokers_padded,
            "brokerWastePct": waste(brokers, brokers_padded),
        }
        if replica_slots_total:
            padding.update(
                replicaSlotsUsed=replica_slots_used,
                replicaSlotsTotal=replica_slots_total,
                replicaSlotWastePct=waste(replica_slots_used,
                                          replica_slots_total))
        self._padding = padding
        budget = self._padding_budget_pct
        if budget is not None:
            worst = max(padding["partitionWastePct"],
                        padding["brokerWastePct"])
            if worst > budget:
                LOG.warning(
                    "padding waste %.1f%% exceeds the configured budget "
                    "of %.1f%% (partitions %d/%d, brokers %d/%d) — "
                    "check the model.*.pad.multiple knobs "
                    "(docs/scaling.md)", worst, budget,
                    partitions, partitions_padded, brokers, brokers_padded)
        return padding

    def padding_from_model(self, model) -> dict:
        """Padding waste straight from a ``FlatClusterModel``'s valid
        masks. Fetches the masks to host (a device sync) — debug/test/
        bench surface; the serving path feeds counts via
        :meth:`observe_padding` instead."""
        import numpy as np
        pvalid = np.asarray(model.partition_valid)
        bvalid = np.asarray(model.broker_valid)
        rvalid = np.asarray(model.replica_valid)
        return self.observe_padding(
            partitions=int(pvalid.sum()), partitions_padded=pvalid.size,
            brokers=int(bvalid.sum()), brokers_padded=bvalid.size,
            replica_slots_used=int(rvalid.sum()),
            replica_slots_total=int(rvalid.size))

    # ---------------------------------------------------------- budgets
    def set_budgets(self, *, padding_waste_pct: float | None = None,
                    hbm_bytes: int | None = None) -> None:
        """Configure the padding/memory budgets (0/None = unenforced).
        Budgets never fail the serving path — they surface on
        /devicestats (``budget`` section), warn in the log, and GATE the
        10Kx1M bench tier; the won't-fit degrade path is operator policy
        (docs/scaling.md)."""
        self._padding_budget_pct = padding_waste_pct or None
        self._hbm_budget_bytes = hbm_bytes or None

    def budget_status(self, *, refresh_memory: bool = False) -> dict:
        """Current standing against the configured budgets. The padding
        reading is the worst of the partition/broker axes (replica-slot
        waste is workload-shaped — RF variance — not a pad-multiple
        choice, so it informs but does not gate). ``refresh_memory``
        re-snapshots memory (a live_arrays walk on CPU); the default
        reads the cached peaks. The memory reading is PER-DEVICE — the
        HBM budget is one device's capacity, and sharding never shrinks
        the cross-device total — taken as the worst of the per-device
        live peak and the backend allocator's peak (peak_bytes_in_use
        includes XLA scratch/temporaries the live sum cannot see)."""
        padding = self._padding or {}
        waste = None
        if padding:
            waste = max(padding.get("partitionWastePct") or 0.0,
                        padding.get("brokerWastePct") or 0.0)
        if refresh_memory:
            self.memory_snapshot()
        peak = max(self._peak_device_live_bytes,
                   self._peak_alloc_bytes) or None
        out = {
            "paddingWastePct": waste,
            "paddingWasteBudgetPct": self._padding_budget_pct,
            "peakBytes": peak,
            "hbmBudgetBytes": self._hbm_budget_bytes,
        }
        out["paddingOverBudget"] = bool(
            self._padding_budget_pct is not None and waste is not None
            and waste > self._padding_budget_pct)
        out["hbmOverBudget"] = bool(
            self._hbm_budget_bytes is not None and peak is not None
            and peak > self._hbm_budget_bytes)
        return out

    # ------------------------------------------------------------ reads
    def compile_count(self) -> int:
        return self._compile_counter.count

    def recompile_count(self) -> int:
        return self._recompile_counter.count

    def aot_compile_count(self) -> int:
        return self._aot_counter.count

    def events(self) -> list[CompileEvent]:
        with self._lock:
            return list(self._events)

    def snapshot(self) -> dict:
        """Cheap counter snapshot for before/after diffing (the
        zero-recompile gate's surface)."""
        with self._lock:
            h2d, d2h = self._h2d_bytes, self._d2h_bytes
        return {"compileEvents": self.compile_count(),
                "aotCompileEvents": self.aot_compile_count(),
                "recompileEvents": self.recompile_count(),
                "h2dBytes": h2d, "d2hBytes": d2h}

    def to_json(self, recent_events: int = 64) -> dict:
        """The /devicestats payload."""
        with self._lock:
            programs = {name: st.to_json()
                        for name, st in sorted(self._programs.items())}
            events = list(self._events)[-recent_events:]
            h2d, d2h = self._h2d_bytes, self._d2h_bytes
        return {
            "enabled": self.enabled,
            "compile": {
                "totalEvents": self.compile_count(),
                "aotEvents": self.aot_compile_count(),
                "recompileEvents": self.recompile_count(),
                "byProgram": programs,
                "recentEvents": [e.to_json() for e in events],
            },
            "transfers": {
                "h2dBytesTotal": h2d,
                "d2hBytesTotal": d2h,
                "lastCycle": self._last_cycle,
            },
            "memory": self.memory_snapshot(),
            "padding": self._padding,
            "budget": self.budget_status(),
        }


#: process-wide default (the analog of default_tracer): subsystems built
#: with ``collector=None`` share it, so one /devicestats dump covers the
#: whole pipeline.
_DEFAULT = DeviceStatsCollector()


def default_collector() -> DeviceStatsCollector:
    return _DEFAULT
