"""SLO burn-rate evaluation over the control plane's freshness signals.

Gauges tell an operator the *current* proposal-freshness lag, replication
stream lag, and standby snapshot staleness; they do not tell them when to
page. This module closes that gap with the standard multi-window,
multi-burn-rate recipe: each objective keeps a **fast** window (is the
error budget burning *right now*) and a **slow** window (has it been
burning *long enough to matter*), and a breach fires only when **both**
windows' violation fractions exceed their thresholds — fast-only spikes
and slow-decaying history alone don't page, which is what keeps the
alert anti-flappy.

On a new breach the evaluator journals an ``slo`` event (severity warn)
and queues a lowest-priority :class:`SLO_BREACH` anomaly for the
detector manager, which routes it through the existing notifier path
(alert-only: its ``fix()`` declines self-healing). Recovery journals a
cause-linked ``recovered`` event closing the chain.

Windows are sample-based over wall-ms timestamps; ``evaluate`` is
interval-throttled so both ``ha_tick`` (standby processes run no
detector loop but still need standby-staleness alerts) and the detector
manager (leader) can call it at their own cadence without double work.
"""

from __future__ import annotations

import logging
from collections import deque
from typing import Callable

from .sensors import MetricRegistry

LOG = logging.getLogger(__name__)

#: sensor group for the evaluator's series (``SLO.*``).
SLO_SENSOR = "SLO"


class _Objective:
    __slots__ = ("name", "read_fn", "target_ms", "fast", "slow",
                 "breached", "breach_seq", "last_observed")

    def __init__(self, name: str, read_fn: Callable[[], float | None],
                 target_ms: float) -> None:
        self.name = name
        self.read_fn = read_fn
        self.target_ms = float(target_ms)
        self.fast: "deque[tuple[int, bool]]" = deque()
        self.slow: "deque[tuple[int, bool]]" = deque()
        self.breached = False
        self.breach_seq: int | None = None
        self.last_observed: float | None = None


def _burn(window: "deque[tuple[int, bool]]") -> float:
    """Violation fraction in the window — the budget burn rate
    normalized to [0, 1] (1.0 = every sample over target)."""
    if not window:
        return 0.0
    return sum(1 for _, bad in window if bad) / len(window)


class SLOEvaluator:
    """Multi-window burn-rate evaluator feeding journal + anomaly path.

    ``add_objective`` registers a named signal (a callable returning the
    observed lag in ms, or None when there is no data yet — no-data is
    *not* a violation). ``evaluate(now_ms)`` samples every objective and
    returns newly-fired breach dicts; ``detect(now_ms)`` adapts that to
    the AnomalyDetectorManager detector protocol, draining pending
    breaches as :class:`~cruise_control_tpu.detector.anomalies.SLOBreach`
    anomalies."""

    def __init__(self, *, journal=None,
                 registry: MetricRegistry | None = None,
                 fast_window_ms: int = 60_000,
                 slow_window_ms: int = 600_000,
                 fast_burn_threshold: float = 0.5,
                 slow_burn_threshold: float = 0.25,
                 interval_ms: int = 5_000) -> None:
        self.journal = journal
        self.enabled = True
        self.fast_window_ms = int(fast_window_ms)
        self.slow_window_ms = int(slow_window_ms)
        self.fast_burn_threshold = float(fast_burn_threshold)
        self.slow_burn_threshold = float(slow_burn_threshold)
        self.interval_ms = int(interval_ms)
        self._last_eval_ms: int | None = None
        self._objectives: dict[str, _Objective] = {}
        self._pending_breaches: list[dict] = []
        self.registry = registry or MetricRegistry()
        name = MetricRegistry.name
        self._breaches = self.registry.counter(name(SLO_SENSOR, "breaches"))
        self._recoveries = self.registry.counter(
            name(SLO_SENSOR, "recoveries"))
        self.registry.gauge(
            name(SLO_SENSOR, "objectives-breached"),
            lambda: sum(1 for o in self._objectives.values() if o.breached))

    def add_objective(self, name_: str,
                      read_fn: Callable[[], float | None],
                      target_ms: float) -> None:
        obj = _Objective(name_, read_fn, target_ms)
        self._objectives[name_] = obj
        name = MetricRegistry.name
        self.registry.gauge(name(SLO_SENSOR, f"{name_}-fast-burn"),
                            lambda o=obj: _burn(o.fast))
        self.registry.gauge(name(SLO_SENSOR, f"{name_}-slow-burn"),
                            lambda o=obj: _burn(o.slow))
        self.registry.gauge(
            name(SLO_SENSOR, f"{name_}-observed-ms"),
            lambda o=obj: -1.0 if o.last_observed is None else o.last_observed)

    @property
    def objectives(self) -> dict:
        return self._objectives

    # ---------------------------------------------------------- evaluation
    def evaluate(self, now_ms: int, *, force: bool = False) -> list[dict]:
        """Sample every objective once; fire/clear breaches on the
        two-window rule. Interval-throttled unless ``force``. Returns
        the breach dicts fired by *this* call."""
        if not self.enabled:
            return []
        if (not force and self._last_eval_ms is not None
                and now_ms - self._last_eval_ms < self.interval_ms):
            return []
        self._last_eval_ms = now_ms
        fired: list[dict] = []
        for obj in self._objectives.values():
            try:
                observed = obj.read_fn()
            except Exception as exc:   # noqa: BLE001 — a broken signal
                LOG.warning("SLO objective %s read failed: %s", obj.name, exc)
                observed = None
            obj.last_observed = (float(observed)
                                 if observed is not None else None)
            if observed is not None:
                bad = float(observed) > obj.target_ms
                obj.fast.append((now_ms, bad))
                obj.slow.append((now_ms, bad))
            for window, span in ((obj.fast, self.fast_window_ms),
                                 (obj.slow, self.slow_window_ms)):
                while window and window[0][0] < now_ms - span:
                    window.popleft()
            fast_burn = _burn(obj.fast)
            slow_burn = _burn(obj.slow)
            breaching = (len(obj.fast) > 0 and len(obj.slow) > 0
                         and fast_burn >= self.fast_burn_threshold
                         and slow_burn >= self.slow_burn_threshold)
            if breaching and not obj.breached:
                obj.breached = True
                self._breaches.inc()
                breach = {"objective": obj.name,
                          "observedMs": obj.last_observed,
                          "targetMs": obj.target_ms,
                          "fastBurn": round(fast_burn, 4),
                          "slowBurn": round(slow_burn, 4),
                          "nowMs": now_ms}
                if self.journal is not None:
                    obj.breach_seq = self.journal.record(
                        "slo", "breach", severity="warn", detail=breach)
                breach["journalSeq"] = obj.breach_seq
                self._pending_breaches.append(breach)
                fired.append(breach)
                LOG.warning("SLO breach: %s observed=%.0fms target=%.0fms "
                            "fast-burn=%.2f slow-burn=%.2f", obj.name,
                            obj.last_observed or -1, obj.target_ms,
                            fast_burn, slow_burn)
            elif obj.breached and not breaching:
                obj.breached = False
                self._recoveries.inc()
                if self.journal is not None:
                    self.journal.record(
                        "slo", "recovered", cause=obj.breach_seq,
                        detail={"objective": obj.name,
                                "fastBurn": round(fast_burn, 4),
                                "slowBurn": round(slow_burn, 4)})
                obj.breach_seq = None
                LOG.info("SLO recovered: %s", obj.name)
        return fired

    # ------------------------------------------------- detector protocol
    def detect(self, now_ms: int) -> list:
        """AnomalyDetectorManager hook: evaluate, then drain pending
        breaches as SLO_BREACH anomalies (alert-only via the notifier
        path; lowest priority so real faults always heal first)."""
        self.evaluate(now_ms)
        if not self._pending_breaches:
            return []
        # Local import: detector package pulls in the notifier stack;
        # core modules must not import it at module load.
        from ..detector.anomalies import SLOBreach
        out = []
        for b in self._pending_breaches:
            out.append(SLOBreach(
                detected_ms=now_ms, objective=b["objective"],
                observed_ms=b.get("observedMs"),
                target_ms=b["targetMs"], fast_burn=b["fastBurn"],
                slow_burn=b["slowBurn"],
                journal_seq=b.get("journalSeq")))
        self._pending_breaches = []
        return out

    def to_json(self) -> dict:
        return {"enabled": self.enabled,
                "fastWindowMs": self.fast_window_ms,
                "slowWindowMs": self.slow_window_ms,
                "fastBurnThreshold": self.fast_burn_threshold,
                "slowBurnThreshold": self.slow_burn_threshold,
                "objectives": [
                    {"name": o.name, "targetMs": o.target_ms,
                     "observedMs": o.last_observed,
                     "fastBurn": round(_burn(o.fast), 4),
                     "slowBurn": round(_burn(o.slow), 4),
                     "breached": o.breached}
                    for o in self._objectives.values()]}
