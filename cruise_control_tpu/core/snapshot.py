"""Crash-safe serving-state snapshots: versioned, checksummed, atomic.

A restarted control plane used to repay the full cold path — model build,
AOT warmup, first proposal computation — before ``/proposals`` was warm
again. :class:`SnapshotManager` persists everything needed to serve warm
(the resident host mirrors + epoch, the monitor generation, the
``ProposalCache`` entry with its freshness stamps, the HA fencing epoch)
so ``facade.start_up`` can restore it *before* ``prewarm()`` and a
restarted process serves generation-valid cached proposals within
seconds; restore composes with the persistent ``.jax_cache/v<N>`` so no
XLA compiles are repaid either (arxiv 1602.03770's stance: restart is a
stateful reconfiguration, not a cold start).

File format (one file, written atomically — tmp + fsync + ``os.replace``,
the same discipline as ``analyzer/tuning.py``)::

    <header JSON line>\n<pickle payload bytes>

The header carries the format version, the payload byte length and its
SHA-256 — a truncated, bit-flipped, or version-skewed file is **detected
at restore time**, metered (``Snapshot.restore-corrupt`` /
``-version-skew`` / ``-stale``), logged loudly, and refused: the caller
then falls back to the cold path. A bad snapshot is never silently
served.

The payload is an opaque dict — composition lives on the facade
(:meth:`~cruise_control_tpu.api.facade.KafkaCruiseControl.snapshot_payload`)
so this module stays free of model/API imports.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import pickle
import threading
import time as _time
import weakref

LOG = logging.getLogger(__name__)

#: bump when the payload composition changes incompatibly; a restore from
#: any other version is refused (metered) and the process starts cold.
SNAPSHOT_VERSION = 1

_MAGIC = "ccsnap"

#: sensor group for the snapshot series (``Snapshot.*``).
SNAPSHOT_SENSOR = "Snapshot"


class SnapshotError(Exception):
    """A snapshot that must not be restored. ``reason`` is one of
    ``missing | corrupt | version-skew | stale | cluster-mismatch`` —
    the restore-fallback meter it lands on."""

    def __init__(self, reason: str, message: str) -> None:
        super().__init__(message)
        self.reason = reason


def atomic_write_bytes(path: str, data: bytes) -> None:
    """tmp + fsync + ``os.replace``: the file at ``path`` is always either
    the previous complete version or the new complete version — a crash
    mid-write can never leave a torn file (the discipline
    ``analyzer/tuning.py`` established, with the fsync the durable-state
    contract additionally requires)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def atomic_write_json(path: str, obj) -> None:
    """Atomic JSON persistence for the small durable side files
    (failed-broker stamps, idempotence cache): a crash mid-``json.dump``
    straight onto the live file used to leave a torn document that
    crashed the next load."""
    atomic_write_bytes(path, json.dumps(obj).encode("utf-8"))


#: module prefixes the snapshot payload may legitimately reference:
#: this package's dataclasses, numpy/jax array reconstruction, and the
#: stdlib pieces their reduce protocols use. Everything else —
#: ``os.system``, ``subprocess``, ``builtins.eval`` and the rest of the
#: classic pickle gadget surface — is refused at unpickle time, so a
#: writable snapshot path is not arbitrary code execution. (The file is
#: still part of the control plane's trust boundary, like
#: ``.jax_cache``: keep it writable by the serving user only; see
#: docs/operations.md.)
_ALLOWED_MODULE_PREFIXES = ("cruise_control_tpu.", "numpy", "jax.",
                            "jaxlib.", "collections", "copyreg",
                            "_codecs")

#: the only builtins a legitimate payload reduce needs (no getattr /
#: eval / exec / open / __import__).
_ALLOWED_BUILTINS = frozenset({
    "dict", "list", "tuple", "set", "frozenset", "bytearray", "complex",
    "slice", "range", "object", "int", "float", "bool", "str", "bytes",
    "NoneType"})


class _RestrictedUnpickler(pickle.Unpickler):
    """Allowlisted unpickling for snapshot payloads (see
    ``_ALLOWED_MODULE_PREFIXES``)."""

    def find_class(self, module, name):
        if module == "builtins":
            if name in _ALLOWED_BUILTINS:
                return super().find_class(module, name)
        elif any(module == p.rstrip(".") or module.startswith(p)
                 for p in _ALLOWED_MODULE_PREFIXES):
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"snapshot payload references forbidden global "
            f"{module}.{name} (not in the snapshot allowlist)")


def write_snapshot(path: str, payload: dict, *,
                   now_ms: int | None = None) -> int:
    """Serialize ``payload`` and write it atomically. Returns the total
    bytes written. Raises OSError/pickle errors to the caller (the
    manager meters them)."""
    body = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
    header = {
        "magic": _MAGIC,
        "version": SNAPSHOT_VERSION,
        "payloadBytes": len(body),
        "sha256": hashlib.sha256(body).hexdigest(),
        "createdMs": int(now_ms if now_ms is not None
                         else _time.time() * 1000),
    }
    blob = json.dumps(header, sort_keys=True).encode("utf-8") + b"\n" + body
    atomic_write_bytes(path, blob)
    return len(blob)


def read_snapshot(path: str, *, max_age_ms: int = 0,
                  now_ms: int | None = None) -> tuple[dict, dict]:
    """Read + validate a snapshot. Returns ``(header, payload)``; raises
    :class:`SnapshotError` (with a classified ``reason``) on anything
    less than a fully-verified, version-current, age-current file.

    Validation order matters: the checksum is verified BEFORE the
    version/age checks so a corrupt file can never masquerade as a clean
    version skew (its header bytes are untrusted until the body hash —
    which covers the declared length — holds)."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        raise SnapshotError("missing", f"no snapshot at {path}")
    except OSError as exc:
        raise SnapshotError("corrupt", f"unreadable snapshot {path}: {exc}")
    head, sep, body = raw.partition(b"\n")
    try:
        header = json.loads(head)
        if not isinstance(header, dict) or header.get("magic") != _MAGIC:
            raise ValueError("bad magic")
    except ValueError:
        raise SnapshotError("corrupt",
                            f"snapshot {path}: unparseable header")
    if not sep or len(body) != header.get("payloadBytes"):
        raise SnapshotError(
            "corrupt",
            f"snapshot {path}: truncated payload ({len(body)} of "
            f"{header.get('payloadBytes')} bytes)")
    if hashlib.sha256(body).hexdigest() != header.get("sha256"):
        raise SnapshotError("corrupt",
                            f"snapshot {path}: checksum mismatch")
    if header.get("version") != SNAPSHOT_VERSION:
        raise SnapshotError(
            "version-skew",
            f"snapshot {path}: version {header.get('version')} != "
            f"{SNAPSHOT_VERSION} (format changed; starting cold)")
    if max_age_ms and now_ms is not None:
        age = now_ms - int(header.get("createdMs", 0))
        if age > max_age_ms:
            raise SnapshotError(
                "stale",
                f"snapshot {path}: {age} ms old exceeds "
                f"snapshot.max.age.ms={max_age_ms} (topology has likely "
                "moved on; starting cold)")
    try:
        payload = _RestrictedUnpickler(io.BytesIO(body)).load()
    except Exception as exc:   # noqa: BLE001 — any unpickle failure = corrupt
        raise SnapshotError("corrupt",
                            f"snapshot {path}: payload unpickle failed "
                            f"({type(exc).__name__}: {exc})")
    if not isinstance(payload, dict):
        raise SnapshotError("corrupt",
                            f"snapshot {path}: payload is not a dict")
    return header, payload


class SnapshotManager:
    """Cadenced, metered snapshot persistence for one serving process.

    Best-effort on IO like :class:`~cruise_control_tpu.analyzer.tuning.
    TunedConfigStore`: a write failure is metered + logged (the serving
    loop must not die for a full disk), a restore failure is metered per
    reason and the caller starts cold. Thread-safe."""

    #: every live manager in this process — a successful write notifies
    #: same-path peers (the in-process HA harness runs leader + standby
    #: over one file) so a standby's next ha_tick restores immediately
    #: instead of waiting out the poll throttle. Weak: a dropped stack's
    #: manager must not be kept alive by the peer registry.
    _managers: "weakref.WeakSet[SnapshotManager]" = weakref.WeakSet()

    def __init__(self, path: str, *, interval_ms: int = 60_000,
                 max_age_ms: int = 0, registry=None) -> None:
        from .sensors import MetricRegistry
        self.path = path
        self._abspath = os.path.abspath(path)
        self.interval_ms = int(interval_ms)
        #: 0 = no age bound (a restored snapshot is still execution-gated
        #: by the stale-model refusal either way; see facade restore).
        self.max_age_ms = int(max_age_ms)
        #: standby freshness poll cadence: interval/4 halves the expected
        #: write->restore staleness gap vs polling at the write interval,
        #: and the mtime fast path below makes each poll one stat().
        self.standby_poll_interval_ms = max(self.interval_ms // 4, 1)
        #: post-write hooks ``fn(now_ms, nbytes)`` — local-process
        #: subscribers (warm standbys, tests) that want to react to a
        #: published snapshot without polling. Exception-safe.
        self.on_write: list = []
        self._lock = threading.Lock()
        self._last_write_ms: int | None = None
        self._last_bytes = 0
        #: throttle state for :meth:`standby_should_poll`.
        self._next_poll_ms: int | None = None
        self._peer_wrote = False
        #: ((mtime_ns, size, seen) -> bool) memo for
        #: :meth:`newer_snapshot_available` — an unchanged file answers
        #: from one stat() without re-reading the header.
        self._poll_cache: tuple | None = None
        #: how far behind the leader the last restored snapshot was
        #: (restore-time now_ms minus the header's createdMs).
        self._last_staleness_ms: int | None = None
        #: createdMs of the newest snapshot this process has WRITTEN or
        #: RESTORED — the floor `newer_snapshot_available` compares
        #: against, so a just-deposed leader never "refreshes" from its
        #: own older file and regresses its live cache.
        self._seen_created_ms: int | None = None
        #: decision journal (core/events.py), attached by the facade —
        #: snapshot writes/restores/refusals are durability decisions.
        self.journal = None
        self.registry = registry or MetricRegistry()
        name = MetricRegistry.name
        g = SNAPSHOT_SENSOR
        self._writes = self.registry.counter(name(g, "writes"))
        self._write_failures = self.registry.meter(
            name(g, "write-failure-rate"))
        self._restores = self.registry.counter(name(g, "restores"))
        self._hook_failures = self.registry.meter(
            name(g, "on-write-hook-failures"))
        #: one meter per refusal class — the alertable signals an operator
        #: needs to tell "disk bit-rot" from "deploy skew" from "old file"
        self._fallbacks = {
            reason: self.registry.meter(name(g, f"restore-{reason}"))
            for reason in ("corrupt", "version-skew", "stale",
                           "cluster-mismatch")}
        self.registry.gauge(name(g, "last-write-ms"),
                            lambda: self._last_write_ms)
        self.registry.gauge(name(g, "bytes"), lambda: self._last_bytes)
        self.registry.gauge(name(g, "standby-staleness-ms"),
                            lambda: self._last_staleness_ms)
        SnapshotManager._managers.add(self)

    # ------------------------------------------------------------ writes
    def maybe_write(self, now_ms: int, payload_fn) -> bool:
        """Cadenced write: serialize+persist when ``interval_ms`` has
        elapsed since the last successful write. ``payload_fn`` is called
        only when due (payload composition walks the resident mirrors)."""
        with self._lock:
            if (self._last_write_ms is not None
                    and now_ms - self._last_write_ms < self.interval_ms):
                return False
        return self.write(now_ms, payload_fn()) is not None

    def write(self, now_ms: int, payload: dict) -> int | None:
        """Unconditional write (the clean-shutdown path). Returns bytes
        written, or None on (metered, logged) failure."""
        try:
            n = write_snapshot(self.path, payload, now_ms=now_ms)
        except Exception as exc:   # noqa: BLE001 — serving must survive IO
            self._write_failures.mark()
            LOG.warning("snapshot write to %s failed (%s: %s); serving "
                        "continues, restart will be cold", self.path,
                        type(exc).__name__, exc)
            return None
        with self._lock:
            self._last_write_ms = now_ms
            self._last_bytes = n
            self._seen_created_ms = max(self._seen_created_ms or 0,
                                        int(now_ms))
        self._writes.inc()
        if self.journal is not None:
            self.journal.record("snapshot", "write",
                                detail={"bytes": n, "path": self.path})
        LOG.debug("snapshot written to %s (%d bytes)", self.path, n)
        # Local-process fan-out: wake same-file peers (the in-process HA
        # harness's standby) and this manager's subscribers so freshness
        # never waits out the standby poll throttle.
        for peer in list(SnapshotManager._managers):
            if peer is not self and peer._abspath == self._abspath:
                peer._note_peer_write()
        for hook in list(self.on_write):
            try:
                hook(now_ms, n)
            except Exception:   # noqa: BLE001 — hooks must not kill writes
                # Metered + named: a dead stream publisher riding this
                # hook must be an alertable signal, not a silent warning.
                self._hook_failures.mark()
                LOG.warning("snapshot on_write hook %r failed",
                            getattr(hook, "__name__", repr(hook)),
                            exc_info=True)
        return n

    def _note_peer_write(self) -> None:
        """A same-path peer published a snapshot: the next
        :meth:`standby_should_poll` answers True regardless of the
        throttle window."""
        with self._lock:
            self._peer_wrote = True

    def standby_should_poll(self, now_ms: int) -> bool:
        """Standby-side freshness-poll throttle: True at most every
        ``standby_poll_interval_ms`` — or immediately when a same-process
        peer just wrote (the multi-process case pays at worst one quarter
        interval of extra staleness; the sensor above measures it)."""
        with self._lock:
            if self._peer_wrote:
                self._peer_wrote = False
                self._next_poll_ms = now_ms + self.standby_poll_interval_ms
                return True
            if (self._next_poll_ms is not None
                    and now_ms < self._next_poll_ms):
                return False
            self._next_poll_ms = now_ms + self.standby_poll_interval_ms
            return True

    # ----------------------------------------------------------- restore
    def restore(self, now_ms: int, validate=None) -> dict | None:
        """Read+validate the snapshot. Returns the payload, or None after
        metering + loudly logging the refusal (missing file is the quiet
        first-boot case). ``validate(payload)`` — returning ``None`` to
        accept or ``(reason, message)`` to refuse — runs the caller's
        domain checks (cluster identity) BEFORE this manager counts the
        restore or marks the file as seen: a refused snapshot must land
        only on its refusal meter, never on ``restores``."""
        try:
            header, payload = read_snapshot(self.path,
                                            max_age_ms=self.max_age_ms,
                                            now_ms=now_ms)
        except SnapshotError as exc:
            if exc.reason == "missing":
                LOG.info("no snapshot at %s; starting cold", self.path)
            else:
                self._fallbacks[exc.reason].mark()
                if self.journal is not None:
                    self.journal.record(
                        "snapshot", "restore-refused", severity="error",
                        detail={"reason": exc.reason, "message": str(exc)})
                LOG.error("snapshot restore REFUSED (%s): %s — falling "
                          "back to the cold start path", exc.reason, exc)
            return None
        if validate is not None:
            refusal = validate(payload)
            if refusal is not None:
                self.refuse(*refusal)
                return None
        self._restores.inc()
        with self._lock:
            self._seen_created_ms = max(self._seen_created_ms or 0,
                                        int(header.get("createdMs", 0)))
            self._last_staleness_ms = max(
                0, now_ms - int(header.get("createdMs", 0)))
        if self.journal is not None:
            self.journal.record(
                "snapshot", "restore",
                detail={"createdMs": int(header.get("createdMs", 0)),
                        "stalenessMs": self._last_staleness_ms})
        return payload

    def refuse(self, reason: str, message: str) -> None:
        """Domain-level restore refusal (e.g. cluster-id mismatch): same
        metering + loud logging as the format-level checks."""
        self._fallbacks[reason].mark()
        if self.journal is not None:
            self.journal.record(
                "snapshot", "restore-refused", severity="error",
                detail={"reason": reason, "message": message})
        LOG.error("snapshot restore REFUSED (%s): %s — falling back to "
                  "the cold start path", reason, message)

    def newer_snapshot_available(self) -> bool:
        """Whether the file on disk was created after anything this
        manager wrote or restored — the standby's cheap poll (one open +
        one header line read; the payload is not touched). A deposed
        leader polling its OWN last snapshot sees False: restoring it
        would regress the live cache to an interval-old state."""
        with self._lock:
            seen = self._seen_created_ms
            cached = self._poll_cache
        try:
            st = os.stat(self.path)
        except OSError:
            with self._lock:
                self._poll_cache = None
            return False
        # mtime fast path: an unchanged file (same mtime_ns + size) with
        # an unchanged floor answers from the stat alone — the header is
        # re-read only when the file or the floor actually moved, so the
        # interval/4 standby poll costs one stat() in steady state.
        key = (st.st_mtime_ns, st.st_size, seen)
        if cached is not None and cached[0] == key:
            return cached[1]
        try:
            with open(self.path, "rb") as f:
                head = io.BufferedReader(f).readline()
            header = json.loads(head)
            created = int(header.get("createdMs", 0))
        except (OSError, ValueError):
            with self._lock:
                self._poll_cache = None
            return False
        result = seen is None or created > seen
        # Racy-mtime guard (the git index trick): filesystem timestamps
        # have coarse granularity, so a file modified within the last
        # few ticks could be rewritten again without its mtime moving.
        # Only memoize once the mtime is comfortably in the past — fresh
        # files re-read the header on every poll.
        if _time.time_ns() - st.st_mtime_ns > 50_000_000:
            with self._lock:
                self._poll_cache = (key, result)
        else:
            with self._lock:
                self._poll_cache = None
        return result

    def to_json(self) -> dict:
        """The ``snapshot`` section of ``/devicestats``."""
        with self._lock:
            return {
                "path": self.path,
                "intervalMs": self.interval_ms,
                "maxAgeMs": self.max_age_ms or None,
                "writes": self._writes.count,
                "writeFailures": self._write_failures.count,
                "onWriteHookFailures": self._hook_failures.count,
                "restores": self._restores.count,
                "restoreFallbacks": {r: m.count
                                     for r, m in self._fallbacks.items()},
                "lastWriteMs": self._last_write_ms,
                "bytes": self._last_bytes or None,
                "standbyPollIntervalMs": self.standby_poll_interval_ms,
                "standbyStalenessMs": self._last_staleness_ms,
            }
