"""Metric definitions: name -> id -> aggregation strategy registry.

Mirrors the reference's ``metricdef/MetricDef.java`` (core) and
``monitor/metricdefinition/KafkaMetricDef.java:43-61``, which map raw metric
types onto the model-level metrics (CPU_USAGE, DISK_USAGE, LEADER_BYTES_IN,
...) each with an aggregation strategy (AVG / MAX / LATEST) and a
"toPredict" group used when several raw metrics fold into one model resource.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Iterable


class AggregationFunction(enum.Enum):
    AVG = "avg"
    MAX = "max"
    LATEST = "latest"


@dataclass(frozen=True)
class MetricInfo:
    name: str
    id: int
    strategy: AggregationFunction
    group: str | None = None


class MetricDef:
    """Registry mapping metric names to dense integer ids (ref MetricDef.java)."""

    def __init__(self) -> None:
        self._by_name: dict[str, MetricInfo] = {}
        self._by_id: list[MetricInfo] = []

    def define(self, name: str, strategy: AggregationFunction = AggregationFunction.AVG,
               group: str | None = None) -> "MetricDef":
        if name in self._by_name:
            raise ValueError(f"Metric {name!r} already defined")
        info = MetricInfo(name, len(self._by_id), strategy, group)
        self._by_name[name] = info
        self._by_id.append(info)
        return self

    def metric_info(self, name: str) -> MetricInfo:
        return self._by_name[name]

    def metric_info_by_id(self, metric_id: int) -> MetricInfo:
        return self._by_id[metric_id]

    def size(self) -> int:
        return len(self._by_id)

    def all_metrics(self) -> Iterable[MetricInfo]:
        return tuple(self._by_id)

    def names(self) -> tuple[str, ...]:
        return tuple(info.name for info in self._by_id)


# ---------------------------------------------------------------------------
# Kafka model-level metric defs (ref KafkaMetricDef.java)
# ---------------------------------------------------------------------------

class KafkaMetric(enum.IntEnum):
    """Model-level ("common") metric ids, dense, in registry order.

    The first four map 1:1 onto :class:`~cruise_control_tpu.core.resources.Resource`
    axis order so a partition sample's resource vector is ``values[:4]``.
    """

    CPU_USAGE = 0
    LEADER_BYTES_IN = 1
    LEADER_BYTES_OUT = 2
    DISK_USAGE = 3
    PRODUCE_RATE = 4
    FETCH_RATE = 5
    MESSAGE_IN_RATE = 6
    REPLICATION_BYTES_IN_RATE = 7
    REPLICATION_BYTES_OUT_RATE = 8


def partition_metric_def() -> MetricDef:
    """Metric def for per-partition samples (ref KafkaMetricDef.commonMetricDef)."""
    definition = MetricDef()
    definition.define("CPU_USAGE", AggregationFunction.AVG, group="CPU")
    definition.define("LEADER_BYTES_IN", AggregationFunction.AVG, group="NW_IN")
    definition.define("LEADER_BYTES_OUT", AggregationFunction.AVG, group="NW_OUT")
    definition.define("DISK_USAGE", AggregationFunction.LATEST, group="DISK")
    definition.define("PRODUCE_RATE", AggregationFunction.AVG)
    definition.define("FETCH_RATE", AggregationFunction.AVG)
    definition.define("MESSAGE_IN_RATE", AggregationFunction.AVG)
    definition.define("REPLICATION_BYTES_IN_RATE", AggregationFunction.AVG)
    definition.define("REPLICATION_BYTES_OUT_RATE", AggregationFunction.AVG)
    return definition


class BrokerMetric(enum.IntEnum):
    """Model-level broker metric ids (subset of ref brokerMetricDef)."""

    CPU_USAGE = 0
    LEADER_BYTES_IN = 1
    LEADER_BYTES_OUT = 2
    DISK_USAGE = 3
    REPLICATION_BYTES_IN_RATE = 4
    REPLICATION_BYTES_OUT_RATE = 5
    BROKER_PRODUCE_REQUEST_RATE = 6
    BROKER_CONSUMER_FETCH_REQUEST_RATE = 7
    BROKER_FOLLOWER_FETCH_REQUEST_RATE = 8
    BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT = 9
    BROKER_LOG_FLUSH_RATE = 10
    BROKER_LOG_FLUSH_TIME_MS_MEAN = 11
    BROKER_LOG_FLUSH_TIME_MS_999TH = 12


def broker_metric_def() -> MetricDef:
    definition = MetricDef()
    for metric in BrokerMetric:
        strategy = (AggregationFunction.LATEST if metric is BrokerMetric.DISK_USAGE
                    else AggregationFunction.AVG)
        definition.define(metric.name, strategy)
    return definition
