"""Leader→replica snapshot-delta streaming: the replicated serving plane.

The crash-safe snapshot (core/snapshot.py) made ONE process restartable;
the HA elector (core/leader.py) made a warm standby take over. But the
standby's freshness came from polling the snapshot file's mtime — a
whole-payload restore per change, bounded below by the write interval.
This module streams the *increments* instead: the leader publishes
**frames** carrying the resident metric-delta payloads the model layer
already computes (``(idx, rows)`` arrays — see
``ResidentClusterState._metric_delta``) plus the logical-clock stamps the
render cache keys on (monitor generation / resident epoch + ingest seq /
registry mutation count / proposal-cache entry seq), and replicas apply
them in order. Full snapshots remain the bootstrap/resync path — a
replica that falls off the stream restores the file, then rejoins.

Three pieces:

- :class:`ReplicationChannel` — the leader-side bounded frame ring.
  In-process followers (the chaos harness) poll it directly; remote
  followers long-poll it over ``GET /replication_stream`` (the server
  serves :func:`encode_stream_payload` bytes;
  :class:`HttpReplicationClient` is the matching follower-side adapter).
  The chaos engine is wired in as ``fault_source``: its ``stream_cut`` /
  ``stream_delay_ms`` state (the ``cut_stream`` / ``delay_stream``
  actions) drops or delays delivery deterministically.
- :class:`ReplicationSession` — one per process, both roles. The leader
  side publishes a frame whenever the clock tuple moved; the follower
  side runs the explicit resync state machine **SYNCING → STREAMING →
  LAGGING → RESYNC** (every transition metered), maintains the
  ``Replication.stream-lag-ms`` gauge, and **fence-checks every frame**:
  a frame stamped with a fencing epoch below the highest epoch this
  follower has seen is refused outright — a deposed leader's stream is
  never applied. The session is written against narrow callables
  (``clocks`` / ``build_frame`` / ``apply_frame`` / ``resync``) so the
  state machine unit-tests with trivial fakes; the facade wires the real
  adapters (``attach_replication_channel``).
- :class:`ReplicaStamp` — the apply ledger. When a shared list is passed
  in (the chaos harness does), every applied / skipped / refused frame
  and every resync lands on it, and
  :func:`~cruise_control_tpu.chaos.invariants.
  check_replication_invariants` audits the whole run: applied seqs
  strictly increase per node, applied fencing epochs never regress, no
  frame applies twice.

Consistency model: frames carry the resident ``ingest_seq`` chain
(``baseIngest`` → ``ingest`` per delta entry), so a follower applies a
delta only onto the exact state it diffs against; any gap — missed
frames, a structural rebuild (epoch bump), capture overflow — degrades
to RESYNC via the snapshot, never to a silently-divergent model. Reads
on a replica are safe exactly when the session is STREAMING within
``replication.max.staleness.ms`` (:meth:`ReplicationSession.
read_refusal`); the server maps anything else to 503 + ``Retry-After`` +
``leaderId``.
"""

from __future__ import annotations

import io
import logging
import pickle
import threading
import zlib
from collections import deque
from dataclasses import dataclass

LOG = logging.getLogger(__name__)

#: sensor group for the streaming series (``Replication.*``).
REPLICATION_SENSOR = "Replication"

#: wire prefix marking a zlib-compressed stream payload. A raw pickle
#: (protocol >= 2) always starts with ``b"\x80"``, so the prefix is
#: unambiguous — :func:`decode_stream_payload` dispatches on it, which
#: is what lets an upgraded follower decode both forms while an old
#: follower (which never advertises ``compress=1``) only ever receives
#: raw pickles.
COMPRESSED_MAGIC = b"CCZ1"

#: follower state machine states, in the nominal lifecycle order.
SYNCING = "SYNCING"
STREAMING = "STREAMING"
LAGGING = "LAGGING"
RESYNC = "RESYNC"
STATES = (SYNCING, STREAMING, LAGGING, RESYNC)
_STATE_CODE = {s: i for i, s in enumerate(STATES)}


@dataclass
class PollResult:
    """One poll of the frame ring, as seen by a follower."""

    #: frames visible to this cursor (delivery-delayed ones withheld)
    frames: list
    #: newest PUBLISHED seq — including frames a delay fault is hiding,
    #: so a follower can tell "caught up" from "the stream is stalled"
    head_seq: int
    #: oldest seq still retained by the ring
    base_seq: int
    #: leader clock at poll service time — the follower's freshness
    #: reference when fully caught up
    now_ms: int
    #: the cursor fell off the ring (frames were evicted unseen): the
    #: follower must RESYNC from the snapshot, the stream has a hole
    reset: bool


@dataclass
class ReplicaStamp:
    """One follower-side frame decision — the replication apply ledger
    (the streaming analogue of ``chaos.ha.MutationStamp``)."""

    now_ms: int
    node: str
    #: frame seq (``-1`` for resync entries, which are not frame-keyed)
    seq: int
    #: the frame's fencing epoch (resync entries: the follower's floor)
    epoch: int
    #: ``applied | skipped | refused-epoch | resync``
    action: str
    reason: str | None = None


class ReplicationChannel:
    """Bounded in-memory frame ring with long-poll delivery.

    The leader's session publishes; followers poll by cursor (the next
    seq they want). Overflow evicts the oldest frames — a follower whose
    cursor fell below the ring base gets ``reset=True`` and must resync
    from the snapshot. ``fault_source`` (the chaos engine) is consulted
    on every poll: ``stream_cut`` drops delivery wholesale (returns
    ``None`` — no contact, the follower's lag grows),
    ``stream_delay_ms`` withholds frames until they are old enough —
    both seeded, step-keyed faults that replay byte-identically.
    """

    def __init__(self, *, capacity: int = 256, fault_source=None,
                 registry=None, compress_min_bytes: int = 0) -> None:
        from .sensors import MetricRegistry
        self.capacity = int(capacity)
        #: object exposing ``stream_cut`` / ``stream_delay_ms`` (the
        #: chaos engine); None = no fault injection.
        self.fault_source = fault_source
        #: HTTP payload compression threshold
        #: (``replication.compress.min.bytes``): poll responses whose raw
        #: encoding is at least this long are zlib-compressed — but ONLY
        #: for followers that advertised support (``compress=1`` on the
        #: poll query). 0 disables. The serving handler reads this off
        #: the ring it resolved.
        self.compress_min_bytes = int(compress_min_bytes)
        self._cond = threading.Condition()
        self._frames: deque = deque()
        self._next_seq = 1
        self.registry = registry or MetricRegistry()
        name = MetricRegistry.name
        g = REPLICATION_SENSOR
        self._published = self.registry.counter(name(g, "frames-published"))
        self._evicted = self.registry.counter(name(g, "frames-evicted"))
        self._polls = self.registry.counter(name(g, "polls"))
        self._polls_dropped = self.registry.counter(
            name(g, "polls-dropped"))
        self._payload_raw = self.registry.counter(
            name(g, "payload-bytes-raw"))
        self._payload_wire = self.registry.counter(
            name(g, "payload-bytes-wire"))
        self._payloads_compressed = self.registry.counter(
            name(g, "payloads-compressed"))
        self.registry.gauge(name(g, "frames-buffered"),
                            lambda: len(self._frames))
        self.registry.gauge(name(g, "compression-ratio"),
                            self.compression_ratio)

    # ------------------------------------------------------------ leader
    def publish(self, frame: dict, now_ms: int) -> int:
        """Stamp + append one frame; wakes long-poll waiters. Returns
        the assigned seq."""
        with self._cond:
            seq = self._next_seq
            self._next_seq += 1
            frame["seq"] = seq
            frame["stampMs"] = int(now_ms)
            self._frames.append(frame)
            while len(self._frames) > self.capacity:
                self._frames.popleft()
                self._evicted.inc()
            self._cond.notify_all()
        self._published.inc()
        return seq

    @property
    def head_seq(self) -> int:
        return self._next_seq - 1

    @property
    def base_seq(self) -> int:
        with self._cond:
            return self._frames[0]["seq"] if self._frames else self._next_seq

    # ---------------------------------------------------------- follower
    def poll(self, cursor: int, now_ms: int,
             wait_ms: int = 0) -> PollResult | None:
        """Frames from ``cursor`` on (``cursor <= 0`` = from the ring
        base — the post-resync rejoin, never a reset). ``wait_ms > 0``
        long-polls (REAL time — only the HTTP serving path uses it; the
        simulated-clock harness polls with 0). Returns ``None`` when a
        ``cut_stream`` fault is active: no contact at all."""
        fs = self.fault_source
        if fs is not None and getattr(fs, "stream_cut", False):
            self._polls_dropped.inc()
            return None
        delay = int(getattr(fs, "stream_delay_ms", 0) or 0) if fs else 0
        self._polls.inc()
        with self._cond:
            result = self._visible(cursor, now_ms, delay)
            if wait_ms > 0 and not result.frames and not result.reset \
                    and result.head_seq < max(cursor, 1):
                self._cond.wait(timeout=wait_ms / 1000.0)
                # Re-check the fault state: a cut that landed while we
                # were parked must not deliver.
                if fs is not None and getattr(fs, "stream_cut", False):
                    self._polls_dropped.inc()
                    return None
                delay = (int(getattr(fs, "stream_delay_ms", 0) or 0)
                         if fs else 0)
                result = self._visible(cursor, now_ms, delay)
        return result

    def note_payload(self, raw_len: int, wire_len: int) -> None:
        """Meter one encoded poll response: raw vs on-the-wire bytes
        (called by :func:`encode_stream_payload` when this ring is passed
        as ``stats``) — the compression-ratio series."""
        self._payload_raw.inc(int(raw_len))
        self._payload_wire.inc(int(wire_len))
        if wire_len < raw_len:
            self._payloads_compressed.inc()

    def compression_ratio(self) -> float | None:
        """wire/raw byte ratio over all encoded payloads (1.0 = nothing
        saved; None until a payload was served)."""
        raw = self._payload_raw.count
        return (self._payload_wire.count / raw) if raw else None

    def _visible(self, cursor: int, now_ms: int, delay: int) -> PollResult:
        base = (self._frames[0]["seq"] if self._frames else self._next_seq)
        start = cursor if cursor > 0 else base
        frames = [f for f in self._frames
                  if f["seq"] >= start and f["stampMs"] + delay <= now_ms]
        return PollResult(frames=frames, head_seq=self._next_seq - 1,
                          base_seq=base, now_ms=int(now_ms),
                          reset=0 < cursor < base)

    def to_json(self) -> dict:
        with self._cond:
            return {
                "capacity": self.capacity,
                "buffered": len(self._frames),
                "headSeq": self._next_seq - 1,
                "baseSeq": (self._frames[0]["seq"] if self._frames
                            else self._next_seq),
                "published": self._published.count,
                "evicted": self._evicted.count,
                "polls": self._polls.count,
                "pollsDropped": self._polls_dropped.count,
                "compressMinBytes": self.compress_min_bytes,
                "payloadsCompressed": self._payloads_compressed.count,
                "compressionRatio": self.compression_ratio(),
            }


# ------------------------------------------------------- wire encoding
def encode_stream_payload(res: PollResult, *, compress_min_bytes: int = 0,
                          stats=None) -> bytes:
    """Serialize a poll result for the ``/replication_stream`` response
    body (dicts + numpy arrays only — round-trips through the snapshot
    allowlist).

    ``compress_min_bytes > 0`` enables delta compression: a raw encoding
    at least that long is zlib-compressed behind the
    :data:`COMPRESSED_MAGIC` prefix — kept only when it actually shrank
    (metric deltas are float arrays; tiny batches can inflate). The
    caller passes 0 unless the *poller* advertised support
    (``compress=1``), so a pre-compression follower always gets a plain
    pickle. ``stats`` (the serving ring) gets ``note_payload(raw_len,
    wire_len)`` for the compression-ratio series."""
    raw = pickle.dumps(
        {"frames": res.frames, "headSeq": res.head_seq,
         "baseSeq": res.base_seq, "nowMs": res.now_ms, "reset": res.reset},
        protocol=pickle.HIGHEST_PROTOCOL)
    data = raw
    if compress_min_bytes and len(raw) >= int(compress_min_bytes):
        packed = COMPRESSED_MAGIC + zlib.compress(raw)
        if len(packed) < len(raw):
            data = packed
    note = getattr(stats, "note_payload", None)
    if note is not None:
        note(len(raw), len(data))
    return data


def decode_stream_payload(raw: bytes) -> PollResult:
    """Decode a ``/replication_stream`` body with the same restricted
    unpickler the snapshot restore path trusts: the stream shares the
    snapshot's trust boundary (leader-authenticated, allowlisted
    globals), never arbitrary code execution. Transparently inflates
    compressed payloads (the :data:`COMPRESSED_MAGIC` prefix) — the
    decompression happens *before* the restricted unpickle, so the trust
    boundary is unchanged."""
    from .snapshot import _RestrictedUnpickler
    if raw.startswith(COMPRESSED_MAGIC):
        raw = zlib.decompress(raw[len(COMPRESSED_MAGIC):])
    obj = _RestrictedUnpickler(io.BytesIO(raw)).load()
    return PollResult(frames=list(obj["frames"]),
                      head_seq=int(obj["headSeq"]),
                      base_seq=int(obj["baseSeq"]),
                      now_ms=int(obj["nowMs"]), reset=bool(obj["reset"]))


class HttpReplicationClient:
    """Follower-side channel adapter long-polling a leader's
    ``/replication_stream`` endpoint (the multi-process deployment path;
    in-process stacks poll the :class:`ReplicationChannel` directly).
    Satisfies the same ``poll(cursor, now_ms, wait_ms)`` protocol; any
    transport error reads as "no contact" (``None``) — the follower's
    lag grows and the state machine degrades exactly as under a
    ``cut_stream`` fault."""

    def __init__(self, host: str, port: int, *, timeout_s: float = 30.0,
                 headers: dict | None = None) -> None:
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self.headers = dict(headers or {})

    def poll(self, cursor: int, now_ms: int,
             wait_ms: int = 0) -> PollResult | None:
        import http.client
        # compress=1 advertises that THIS follower can inflate
        # COMPRESSED_MAGIC payloads; the leader only compresses for
        # pollers that say so (old followers keep getting raw pickles).
        path = (f"/kafkacruisecontrol/replication_stream?json=true"
                f"&cursor={int(cursor)}&wait_ms={int(wait_ms)}&compress=1")
        try:
            conn = http.client.HTTPConnection(
                self.host, self.port,
                timeout=self.timeout_s + wait_ms / 1000.0)
            try:
                conn.request("GET", path, headers=self.headers)
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    return None
                return decode_stream_payload(body)
            finally:
                conn.close()
        except Exception:   # noqa: BLE001 — transport failure = no contact
            return None


class DualChannel:
    """The multi-process node wiring (serve.py): publish into the local
    ring — served to followers at ``/replication_stream`` — and follow
    the configured peer over HTTP when standing by. The session only
    publishes while leading and only polls while following, so the two
    halves never race; the server endpoint resolves ``.ring`` to serve
    the local buffer rather than proxying the peer."""

    def __init__(self, ring: ReplicationChannel,
                 client: HttpReplicationClient) -> None:
        self.ring = ring
        self.client = client

    def publish(self, frame: dict, now_ms: int) -> int:
        return self.ring.publish(frame, now_ms)

    def poll(self, cursor: int, now_ms: int,
             wait_ms: int = 0) -> PollResult | None:
        return self.client.poll(cursor, now_ms, wait_ms=wait_ms)

    def to_json(self) -> dict:
        return {"ring": self.ring.to_json(),
                "peer": f"{self.client.host}:{self.client.port}"}


class ReplicationSession:
    """One process's end of the stream — leader publisher + follower
    state machine, role-switched every :meth:`tick`.

    The constructor takes narrow callables instead of the facade so the
    state machine is unit-testable with fakes:

    - ``clocks()`` → dict of logical clocks; the leader publishes a new
      frame exactly when this moved since the last publish.
    - ``build_frame()`` → frame body dict (resident delta entries,
      proposal-cache export, generation) or None for nothing-to-say.
    - ``fencing_epoch()`` → this process's current fencing epoch; stamps
      every published frame.
    - ``apply_frame(frame)`` → ``"applied" | "skipped" | "resync"`` —
      the follower-side domain apply (resident deltas, proposal cache,
      generation seed). Must be gap-safe: anything it cannot apply
      contiguously answers ``"resync"``.
    - ``resync()`` → leader-clock ms the restored state is fresh as of,
      or None when no (newer) snapshot was restorable — the full-
      snapshot bootstrap/fallback path.
    - ``on_fence(epoch)`` (optional) → observed-epoch feedthrough to the
      elector, so a follower that has seen epoch E never later ACCEPTS
      a lease takeover below it.
    """

    def __init__(self, *, node_id: str, channel, clocks, build_frame,
                 fencing_epoch, apply_frame, resync,
                 max_staleness_ms: int = 5_000, poll_wait_ms: int = 0,
                 coalesce_ms: int = 0, coalesce_max_entries: int = 256,
                 registry=None, ledger: list | None = None,
                 on_fence=None, now_ms=None) -> None:
        import time as _time

        from .sensors import MetricRegistry
        self.node_id = node_id
        self.channel = channel
        self.clocks = clocks
        self.build_frame = build_frame
        self.fencing_epoch = fencing_epoch
        self.apply_frame = apply_frame
        self.resync = resync
        self.max_staleness_ms = int(max_staleness_ms)
        #: long-poll window handed to the channel (serving deployments;
        #: simulated-clock harnesses keep 0)
        self.poll_wait_ms = int(poll_wait_ms)
        #: merge window for consecutive delta-only frames (0 = publish
        #: every frame immediately). A held frame adds at most
        #: ``coalesce_ms`` to follower freshness, so keep it well under
        #: ``max_staleness_ms``.
        self.coalesce_ms = int(coalesce_ms)
        #: flush a pending merged frame once it carries this many
        #: resident entries, regardless of window age
        self.coalesce_max_entries = int(coalesce_max_entries)
        self._pending_frame: dict | None = None
        self._pending_since_ms: int | None = None
        self.on_fence = on_fence
        self._now_ms = now_ms or (lambda: int(_time.time() * 1000))
        #: shared apply ledger (:class:`ReplicaStamp`) — None = unaudited
        self.ledger = ledger
        self.role = "standby"
        self.state = SYNCING
        #: next frame seq this follower wants (0 = rejoin at ring base)
        self.cursor = 0
        #: leader-clock ms through which this process is known
        #: consistent; None = never synced at all
        self.fresh_ms: int | None = None
        self.stream_lag_ms: int | None = None
        #: highest fencing epoch seen on any frame — the refusal floor
        self.fence_floor = 0
        self._published_clocks = None
        #: decision journal (core/events.py), attached by the facade —
        #: state-machine transitions and fence refusals are recorded
        #: locally on each process (the refusing replica's own journal
        #: is the forensic record of a deposed leader's frames).
        self.journal = None
        self.registry = registry or MetricRegistry()
        name = MetricRegistry.name
        g = REPLICATION_SENSOR
        self._applied = self.registry.counter(name(g, "frames-applied"))
        self._skipped = self.registry.counter(name(g, "frames-skipped"))
        self._refused = self.registry.counter(
            name(g, "frames-refused-epoch"))
        self._resyncs = self.registry.counter(name(g, "resyncs"))
        self._poll_failures = self.registry.counter(
            name(g, "poll-failures"))
        self._coalesced = self.registry.counter(
            name(g, "frames-coalesced"))
        self._read_refusals = self.registry.meter(
            name(g, "read-refusal-rate"))
        self._transitions = {
            s: self.registry.counter(
                name(g, f"transitions-to-{s.lower()}"))
            for s in STATES}
        self.registry.gauge(name(g, "stream-lag-ms"),
                            lambda: self.stream_lag_ms)
        self.registry.gauge(name(g, "state"),
                            lambda: _STATE_CODE[self.state])
        self.registry.gauge(name(g, "fence-floor"),
                            lambda: self.fence_floor)
        self.registry.gauge(name(g, "cursor"), lambda: self.cursor)

    # ----------------------------------------------------- state machine
    def _enter(self, state: str, reason: str = "") -> None:
        if state == self.state:
            return
        LOG.info("replication[%s]: %s -> %s%s", self.node_id, self.state,
                 state, f" ({reason})" if reason else "")
        if self.journal is not None:
            self.journal.record(
                "replication", "state-transition",
                severity="warn" if state in (LAGGING, RESYNC) else "info",
                epoch=self.fence_floor or None,
                detail={"from": self.state, "to": state, "reason": reason})
        self.state = state
        self._transitions[state].inc()

    def tick(self, now_ms: int, role: str) -> None:
        """One HA-loop round. ``role`` comes from the elector tick the
        facade just ran (``leader`` | ``standby``)."""
        if role == "leader":
            if self.role != "leader":
                self.role = "leader"
                # A promoted follower is the source of truth now: its
                # stream position is moot.
                self._enter(STREAMING, "promoted to leader")
            self._leader_tick(now_ms)
            return
        if self.role != "standby":
            self.role = "standby"
            # Deposed (or never-led): rejoin the stream from scratch —
            # the new leader's snapshot is the only safe base. A frame
            # still held for coalescing is from the deposed term; the
            # new leader's stream supersedes it (followers heal any gap
            # through the ingest-chain resync), so drop, never publish.
            self._pending_frame = None
            self._pending_since_ms = None
            self._published_clocks = None
            self.cursor = 0
            self._enter(SYNCING, "demoted to standby")
        self._follower_tick(now_ms)

    # ------------------------------------------------------------ leader
    def _leader_tick(self, now_ms: int) -> None:
        self.fresh_ms = int(now_ms)
        self.stream_lag_ms = 0
        c = self.clocks()
        if c == self._published_clocks:
            # Clocks idle, but a held frame still ages toward its window.
            self._flush_pending_if_due(now_ms)
            return
        frame = self.build_frame()
        if frame is None:
            self._published_clocks = c
            self._flush_pending_if_due(now_ms)
            return
        epoch = int(self.fencing_epoch())
        self.fence_floor = max(self.fence_floor, epoch)
        frame["fencingEpoch"] = epoch
        frame["clocks"] = dict(c)
        frame["node"] = self.node_id
        if self.coalesce_ms > 0 and self._coalescible(frame):
            self._buffer_frame(frame, now_ms)
        else:
            # Structural / snapshot-bearing frames never coalesce; a
            # held delta must go out FIRST so followers apply in ingest
            # order.
            self._flush_pending(now_ms)
            self.channel.publish(frame, now_ms)
        self._published_clocks = c
        self._flush_pending_if_due(now_ms)

    # Under high-churn ingest every window roll emits one small delta
    # frame; at ring capacity that churn evicts older frames and forces
    # follower resyncs. Coalescing merges consecutive delta-only frames
    # (plain resident entries, no structural markers, no proposal-cache
    # body) inside a ``coalesce_ms`` window into one frame before
    # publish. Safe because follower apply is per-entry idempotent and
    # keyed by ingest sequence — a merged frame applies exactly like its
    # constituents in order.
    @staticmethod
    def _coalescible(frame: dict) -> bool:
        if frame.get("proposalCache") is not None:
            return False
        resident = frame.get("resident")
        if resident is None:
            # Clock-only heartbeat: merging is just "keep the newest".
            return True
        return not any(e.get("structural") for e in resident.get(
            "entries", ()))

    def _buffer_frame(self, frame: dict, now_ms: int) -> None:
        pending = self._pending_frame
        if pending is None:
            self._pending_frame = frame
            self._pending_since_ms = int(now_ms)
            return
        if not self._merge_into(pending, frame):
            self._flush_pending(now_ms)
            self._pending_frame = frame
            self._pending_since_ms = int(now_ms)
            return
        self._coalesced.inc()
        if (len((pending.get("resident") or {}).get("entries", ()))
                >= self.coalesce_max_entries):
            self._flush_pending(now_ms)

    @staticmethod
    def _merge_into(pending: dict, frame: dict) -> bool:
        """Merge ``frame`` (newer) into ``pending`` in place; False when
        the two can't merge (different resident epoch — entries from
        different window generations must not share a frame)."""
        pb, fb = pending.get("resident"), frame.get("resident")
        if pb is not None and fb is not None:
            if pb.get("epoch") != fb.get("epoch"):
                return False
            pb["entries"] = list(pb.get("entries", ())) + list(
                fb.get("entries", ()))
            pb["ingest"] = fb.get("ingest", pb.get("ingest"))
        elif fb is not None:
            pending["resident"] = fb
        # Journal deltas append in order — each entry carries its own
        # seq, so a merged frame applies exactly like its constituents.
        fj = frame.get("journal")
        if fj:
            pending["journal"] = list(pending.get("journal") or ()) \
                + list(fj)
        # Newest metadata wins: followers treat the merged frame as the
        # latest word from this leader term.
        for key in ("clusterId", "generation", "fencingEpoch", "clocks",
                    "node"):
            if key in frame:
                pending[key] = frame[key]
        return True

    def _flush_pending(self, now_ms: int) -> None:
        if self._pending_frame is not None:
            self.channel.publish(self._pending_frame, now_ms)
            self._pending_frame = None
            self._pending_since_ms = None

    def _flush_pending_if_due(self, now_ms: int) -> None:
        if (self._pending_frame is not None
                and now_ms - self._pending_since_ms >= self.coalesce_ms):
            self._flush_pending(now_ms)

    # ---------------------------------------------------------- follower
    def _follower_tick(self, now_ms: int) -> None:
        if self.state in (SYNCING, RESYNC):
            as_of = self.resync()
            if as_of is None:
                self._update_lag(now_ms)
                return
            self._resyncs.inc()
            self.fresh_ms = int(as_of)
            self.cursor = 0     # rejoin at the ring base; ingest-chain
            self._stamp(now_ms, -1, self.fence_floor, "resync",
                        "restored from snapshot")
            self._enter(STREAMING, "resynced from snapshot")

        res = self.channel.poll(self.cursor, now_ms,
                                wait_ms=self.poll_wait_ms)
        if res is None:
            self._poll_failures.inc()
            self._update_lag(now_ms)
            return
        if res.reset:
            self._enter(RESYNC, f"cursor {self.cursor} fell off ring "
                                f"(base {res.base_seq})")
            self._update_lag(now_ms)
            return
        for frame in res.frames:
            self.cursor = frame["seq"] + 1
            if not self._handle(frame, now_ms):
                break               # entered RESYNC — stop applying
        else:
            if self.cursor <= 0:
                # Nothing visible yet: park at the ring base (NOT past
                # the head — frames a delay fault is hiding must still
                # deliver once old enough).
                self.cursor = res.base_seq
            if self.cursor > res.head_seq:
                # Fully caught up — fresh as of the leader's poll-time
                # clock, even if no frame arrived this round.
                self.fresh_ms = max(self.fresh_ms or 0, res.now_ms)
        self._update_lag(now_ms)

    def _handle(self, frame: dict, now_ms: int) -> bool:
        """Apply one frame. Returns False when the session entered
        RESYNC (the caller must stop applying this batch)."""
        epoch = int(frame.get("fencingEpoch", 0))
        if epoch < self.fence_floor:
            # A deposed leader's frame: refuse, never apply. The cursor
            # still advances — the frame is dead, not pending.
            self._refused.inc()
            if self.journal is not None:
                # Recorded in the REPLICA's own journal (never applied
                # from the deposed stream) — the post-failover forensic
                # evidence that the fence held.
                self.journal.record(
                    "replication", "frame-refused-epoch", severity="warn",
                    epoch=epoch,
                    detail={"seq": frame.get("seq"),
                            "fenceFloor": self.fence_floor,
                            "fromNode": frame.get("node")})
            self._stamp(now_ms, frame["seq"], epoch, "refused-epoch",
                        f"below fence floor {self.fence_floor}")
            return True
        if epoch > self.fence_floor:
            self.fence_floor = epoch
            if self.on_fence is not None:
                self.on_fence(epoch)
        outcome = self.apply_frame(frame)
        if outcome == "resync":
            self._stamp(now_ms, frame["seq"], epoch, "resync",
                        "frame not contiguously applicable")
            self._enter(RESYNC, f"frame {frame['seq']} not applicable")
            return False
        if outcome == "applied":
            self._applied.inc()
        else:
            self._skipped.inc()
        self._stamp(now_ms, frame["seq"], epoch, outcome)
        self.fresh_ms = max(self.fresh_ms or 0, int(frame["stampMs"]))
        return True

    def _update_lag(self, now_ms: int) -> None:
        if self.fresh_ms is None:
            self.stream_lag_ms = None
            return
        self.stream_lag_ms = max(0, int(now_ms) - self.fresh_ms)
        if self.state == STREAMING \
                and self.stream_lag_ms > self.max_staleness_ms:
            self._enter(LAGGING,
                        f"lag {self.stream_lag_ms}ms > "
                        f"{self.max_staleness_ms}ms")
        elif self.state == LAGGING \
                and self.stream_lag_ms <= self.max_staleness_ms:
            self._enter(STREAMING, "lag back within bound")

    def _stamp(self, now_ms: int, seq: int, epoch: int, action: str,
               reason: str | None = None) -> None:
        if self.ledger is not None:
            self.ledger.append(ReplicaStamp(
                now_ms=int(now_ms), node=self.node_id, seq=seq,
                epoch=epoch, action=action, reason=reason))

    # ------------------------------------------------------------- reads
    def read_refusal(self, now_ms: int | None = None) -> dict | None:
        """The bounded-staleness read contract: ``None`` when this
        process may serve reads (leader always; replica while STREAMING
        within ``max_staleness_ms``), else the refusal descriptor the
        server maps to 503 + ``Retry-After``. Metered."""
        if self.role == "leader":
            return None
        now = int(now_ms if now_ms is not None else self._now_ms())
        lag = (max(0, now - self.fresh_ms)
               if self.fresh_ms is not None else None)
        if self.state == STREAMING and lag is not None \
                and lag <= self.max_staleness_ms:
            return None
        self._read_refusals.mark()
        return {"state": self.state, "streamLagMs": lag,
                "maxStalenessMs": self.max_staleness_ms}

    def to_json(self) -> dict:
        """The ``replication`` section of ``/devicestats``."""
        out = {
            "role": self.role,
            "state": self.state,
            "cursor": self.cursor,
            "streamLagMs": self.stream_lag_ms,
            "maxStalenessMs": self.max_staleness_ms,
            "fenceFloor": self.fence_floor,
            "framesApplied": self._applied.count,
            "framesSkipped": self._skipped.count,
            "framesRefusedEpoch": self._refused.count,
            "resyncs": self._resyncs.count,
            "pollFailures": self._poll_failures.count,
            "framesCoalesced": self._coalesced.count,
            "coalesceMs": self.coalesce_ms,
            "pendingCoalesced": self._pending_frame is not None,
            "readRefusals": self._read_refusals.count,
        }
        chan_json = getattr(self.channel, "to_json", None)
        if chan_json is not None:
            out["channel"] = chan_json()
        return out
