"""Shared retry-with-exponential-backoff+jitter policy.

The reference retries transient admin failures ad hoc per call site
(``ExecutorAdminUtils`` list-reassignment attempts, the sample fetcher's
``fetch.metric.samples.max.retry.count``); this module is the ONE policy
object the executor's setup/poll/abort paths and the facade's admin reads
share, so backoff behavior is tuned (and tested) in a single place.

Design constraints, driven by the chaos harness:

- **Deterministic.** Jitter derives from a hash of ``(seed, attempt)``,
  never from global RNG state or wall clock — a chaos run replayed from
  the same seed produces byte-identical retry schedules.
- **Clock-agnostic.** Sleeping goes through a caller-provided ``sleep_ms``
  (the executor passes its simulated clock), so retried paths stay
  wall-clock free under test.
- **Classification stays at the call site.** ``retry_on`` names the
  retryable exception types; anything else propagates immediately. The
  admin layer's :data:`~cruise_control_tpu.executor.kafka_admin.
  RETRYABLE_ADMIN_ERRORS` is the canonical tuple for admin RPCs.
"""

from __future__ import annotations

import time as _time
import zlib
from dataclasses import dataclass


def deterministic_uniform(seed: int, *key) -> float:
    """Deterministic uniform [0, 1) draw keyed off ``(seed, *key)`` — the
    ONE seeded-draw primitive retry jitter and the chaos engine share, so
    replay determinism cannot drift between the two."""
    h = zlib.crc32(":".join(str(k) for k in (seed, *key)).encode())
    return (h % 10_000) / 10_000.0


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with bounded, deterministic jitter.

    Attempt ``i`` (0-based) that fails retryably sleeps
    ``min(backoff_ms * multiplier**i, max_backoff_ms)`` scaled by
    ``1 ± jitter`` before attempt ``i+1``; after ``max_attempts`` total
    attempts the last exception propagates.
    """

    max_attempts: int = 3
    backoff_ms: int = 100
    backoff_multiplier: float = 2.0
    max_backoff_ms: int = 10_000
    #: fractional jitter band: delay is scaled into [1-j, 1+j]
    jitter: float = 0.2
    #: default jitter seed for calls that don't pass one. 0 (replayable)
    #: for chaos/test policies; production wiring (constants.py) seeds
    #: per process so fleet instances decorrelate their retry waves
    #: instead of re-colliding in sync after a shared controller hiccup.
    seed: int = 0
    #: overall wall-clock budget across ALL attempts of one ``call``
    #: (admin.retry.deadline.ms). Attempts are bounded but elapsed time
    #: is not: a slow-FAILING endpoint can stretch any per-call deadline
    #: through the backoff sleeps. When the budget would be exceeded by
    #: the next backoff, the last exception propagates instead of
    #: sleeping. 0 = unbounded (the pre-existing behavior).
    deadline_ms: int = 0

    def delay_ms(self, attempt: int, seed: int | None = None) -> int:
        """Backoff before the attempt AFTER 0-based ``attempt``."""
        base = min(self.backoff_ms * self.backoff_multiplier ** attempt,
                   float(self.max_backoff_ms))
        frac = deterministic_uniform(
            self.seed if seed is None else seed, attempt)
        scale = 1.0 + self.jitter * (2.0 * frac - 1.0)
        return max(int(base * scale), 0)

    def call(self, fn, *args, retry_on: tuple = (), sleep_ms=None,
             on_retry=None, seed: int | None = None, now_ms=None,
             **kwargs):
        """Invoke ``fn(*args, **kwargs)`` under this policy.

        ``on_retry(attempt, delay_ms, exc)`` fires before each backoff
        sleep (meters/logs hook); a non-``retry_on`` exception — or the
        final retryable one — propagates unchanged. ``now_ms`` is the
        clock the ``deadline_ms`` budget is measured on: pass the same
        simulated clock as ``sleep_ms`` so chaos replays of a
        deadline-cut retry ladder stay byte-identical (defaults to the
        process monotonic clock).
        """
        if sleep_ms is None:
            sleep_ms = lambda ms: _time.sleep(ms / 1000.0)  # noqa: E731
        if now_ms is None:
            now_ms = lambda: int(_time.monotonic() * 1000)  # noqa: E731
        attempts = max(self.max_attempts, 1)
        start = now_ms() if self.deadline_ms else 0
        for attempt in range(attempts):
            try:
                return fn(*args, **kwargs)
            except retry_on as exc:
                if attempt == attempts - 1:
                    raise
                delay = self.delay_ms(attempt, seed)
                if self.deadline_ms:
                    elapsed = now_ms() - start
                    if elapsed + delay > self.deadline_ms:
                        # Sleeping would overshoot the budget; the call
                        # has already consumed its wall-clock allowance.
                        raise
                if on_retry is not None:
                    on_retry(attempt, delay, exc)
                sleep_ms(delay)


#: Retry disabled: one attempt, no sleeps — call sites keep the shared
#: shape while an operator opts out (admin.retry.max.attempts=1).
NO_RETRY = RetryPolicy(max_attempts=1)
