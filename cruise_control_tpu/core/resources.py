"""Resource model (reference: ``common/Resource.java:17-25``).

The four balanced resources and their array-axis order. This ordering is the
contract for every ``[..., 4]`` resource axis in the flattened cluster model
and the analyzer kernels — CPU=0, NW_IN=1, NW_OUT=2, DISK=3, matching the
reference enum order so config defaults and score comparisons line up.
"""

from __future__ import annotations

import enum


class Resource(enum.IntEnum):
    CPU = 0
    NW_IN = 1
    NW_OUT = 2
    DISK = 3

    @property
    def is_host_resource(self) -> bool:
        # ref Resource.java: CPU and NW are host-level, DISK is broker-level
        return self in (Resource.CPU, Resource.NW_IN, Resource.NW_OUT)

    @property
    def is_broker_resource(self) -> bool:
        return self in (Resource.CPU, Resource.NW_OUT, Resource.DISK)

    @property
    def epsilon(self) -> float:
        # ref Resource.java EPSILON: tolerance for utilization comparison
        return 1e-5 if self is Resource.CPU else 1e-3

    @classmethod
    def cached_values(cls) -> tuple["Resource", ...]:
        return _RESOURCES


_RESOURCES = (Resource.CPU, Resource.NW_IN, Resource.NW_OUT, Resource.DISK)

NUM_RESOURCES = 4

RESOURCE_NAMES = ("CPU", "NW_IN", "NW_OUT", "DISK")

# Units (ref config/capacity.json doc): DISK in MB, CPU in percent (0-100 per
# broker by default, cores-aware resolvers normalize), network in KB/s.
RESOURCE_UNITS = ("%", "KB/s", "KB/s", "MB")


class RawAndDerivedResource(enum.IntEnum):
    """Derived per-replica resource split (ref: RawAndDerivedResource.java).

    Used by the partition-load response layer where leader/follower shares of
    network load are reported separately.
    """

    CPU = 0
    NW_IN = 1
    NW_OUT = 2
    DISK = 3
    LEADER_NW_IN = 4
    FOLLOWER_NW_IN = 5
    PWN_NW_OUT = 6
    REPLICAS = 7
