"""Windowed metric sample aggregation.

Rebuild of the reference's core aggregator (``monitor/sampling/aggregator/
MetricSampleAggregator.java:84`` with ``RawMetricValues.java``,
``MetricSampleCompleteness.java``, ``AggregationOptions.java``): raw samples
are rolled into fixed-width time windows per entity (partition or broker),
with extrapolation for windows that have too few samples, and completeness
accounting that gates model generation downstream.

Unlike the reference's per-entity object graph, each entity's raw window
state is a set of numpy ring buffers (``[num_windows+1, num_metrics]``), and
aggregation emits dense ``[num_entities, num_metrics, num_windows]`` arrays
ready to be flattened into the device-side cluster model.
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from collections.abc import Mapping
from typing import Callable, Hashable

import numpy as np

from .metricdef import AggregationFunction, MetricDef


class Extrapolation(enum.Enum):
    """Per-window extrapolation applied when samples are insufficient.

    Mirrors ``Extrapolation.java``: NONE means the window had enough samples;
    the others record how the gap was filled (and count against
    ``max_allowed_extrapolations_per_entity``).
    """

    NONE = 0
    AVG_AVAILABLE = 1
    AVG_ADJACENT = 2
    FORCED_INSUFFICIENT = 3
    NO_VALID_EXTRAPOLATION = 4


#: Extrapolation decoded by its integer code (the dense path stores codes
#: in an ``int8[E, W]`` matrix; views decode lazily through this table).
EXTRAPOLATION_BY_CODE = tuple(Extrapolation)


class NotEnoughValidWindowsError(RuntimeError):
    """Raised when fewer valid windows exist than the caller requires
    (ref MetricSampleAggregator.aggregate -> NotEnoughValidWindowsException)."""


@dataclass(frozen=True)
class MetricSample:
    """One raw sample: an entity, a sample time, and a metric-id->value map."""

    entity: Hashable
    sample_time_ms: int
    values: Mapping[int, float]
    entity_group: Hashable | None = None


class AggregationGranularity(enum.Enum):
    """ref AggregationOptions.Granularity: whether one invalid entity
    invalidates just itself or its whole entity group (topic)."""

    ENTITY = "entity"
    ENTITY_GROUP = "entity_group"


@dataclass
class AggregationOptions:
    min_valid_entity_ratio: float = 0.0
    min_valid_entity_group_ratio: float = 0.0
    min_valid_windows: int = 1
    max_allowed_extrapolations_per_entity: int = 5
    granularity: AggregationGranularity = AggregationGranularity.ENTITY
    interested_entities: set[Hashable] | None = None


@dataclass
class MetricSampleCompleteness:
    """ref MetricSampleCompleteness.java: which windows are valid and how
    much of the entity space they cover."""

    generation: int
    valid_windows: list[int] = field(default_factory=list)
    valid_entity_ratio_by_window: dict[int, float] = field(default_factory=dict)
    valid_entity_group_ratio_by_window: dict[int, float] = field(default_factory=dict)
    valid_entities: set[Hashable] = field(default_factory=set)
    valid_entity_groups: set[Hashable] = field(default_factory=set)
    num_total_entities: int = 0

    @property
    def valid_entity_ratio(self) -> float:
        if not self.num_total_entities:
            return 0.0
        return len(self.valid_entities) / self.num_total_entities


@dataclass
class ValuesAndExtrapolations:
    """Aggregated values for one entity: ``[num_metrics, num_windows]`` plus
    the extrapolation applied per window (ref ValuesAndExtrapolations.java)."""

    values: np.ndarray
    extrapolations: list[Extrapolation]
    window_times_ms: list[int]


@dataclass
class DenseAggregate:
    """The whole-pool aggregation result as dense arrays.

    One ``[num_entities, num_metrics, num_windows]`` value cube plus a
    per-window extrapolation-code matrix, in a single stable entity order
    (``entities[i]`` owns row ``i``; ``row_index`` inverts that). Downstream
    model construction gathers straight out of these arrays — the
    ``entity_values`` dict API on :class:`MetricSampleAggregationResult`
    is a lazy per-entity view over the same memory.
    """

    entities: list[Hashable]
    row_index: dict[Hashable, int]
    values: np.ndarray          # float64[E, M, W]
    extrapolations: np.ndarray  # int8[E, W], Extrapolation codes
    window_valid: np.ndarray    # bool[E, W] (pre-demotion validity)
    window_indices: list[int]
    window_times_ms: list[int]


class _LazyEntityValues(Mapping):
    """``entity -> ValuesAndExtrapolations`` view over a DenseAggregate.

    Keeps the dict API every existing caller uses (``get``/``[]``/
    iteration/``len``) without materializing E per-entity objects: each
    access builds one lightweight wrapper whose ``values`` is a row view
    into the dense cube."""

    __slots__ = ("_dense",)

    def __init__(self, dense: DenseAggregate) -> None:
        self._dense = dense

    def __getitem__(self, entity: Hashable) -> ValuesAndExtrapolations:
        row = self._dense.row_index[entity]
        return ValuesAndExtrapolations(
            values=self._dense.values[row],
            extrapolations=[EXTRAPOLATION_BY_CODE[c]
                            for c in self._dense.extrapolations[row]],
            window_times_ms=self._dense.window_times_ms)

    def __iter__(self):
        return iter(self._dense.entities)

    def __len__(self) -> int:
        return len(self._dense.entities)

    def __contains__(self, entity: Hashable) -> bool:
        return entity in self._dense.row_index


@dataclass
class MetricSampleAggregationResult:
    generation: int
    valid_windows: list[int]
    entity_values: Mapping[Hashable, ValuesAndExtrapolations]
    completeness: MetricSampleCompleteness
    invalid_entities: set[Hashable]
    #: dense array view of the same aggregation (None on the retained
    #: per-entity reference path and on empty-window results)
    dense: DenseAggregate | None = None


class _RawStore:
    """Dense raw window state for ALL entities: one array pool instead of a
    per-entity object graph (the reference's per-entity
    ``RawMetricValues.java`` ring buffers, flattened to ``[entities, slots,
    metrics]`` so batch ingest is a handful of ``np.add.at`` scatters —
    the host-side analog of the device model's struct-of-arrays layout).
    Rows are assigned on first sight and recycled on retain/remove."""

    def __init__(self, num_slots: int, num_metrics: int,
                 initial_capacity: int = 256) -> None:
        self._num_slots = num_slots
        self._num_metrics = num_metrics
        self._rows: dict[Hashable, int] = {}
        self._free: list[int] = []
        self._alloc(initial_capacity)

    def _alloc(self, capacity: int) -> None:
        S, M = self._num_slots, self._num_metrics
        self.sums = np.zeros((capacity, S, M), np.float64)
        self.counts = np.zeros((capacity, S, M), np.int32)
        self.maxes = np.full((capacity, S, M), -np.inf, np.float64)
        self.latest_values = np.zeros((capacity, S, M), np.float64)
        self.latest_times = np.full((capacity, S, M), -1, np.int64)
        self.sample_counts = np.zeros((capacity, S), np.int32)

    @property
    def capacity(self) -> int:
        return self.sums.shape[0]

    def _grow(self, need: int) -> None:
        old = self.capacity
        new = max(old * 2, need)
        for name in ("sums", "counts", "maxes", "latest_values",
                     "latest_times", "sample_counts"):
            arr = getattr(self, name)
            grown = np.empty((new, *arr.shape[1:]), arr.dtype)
            grown[:old] = arr
            grown[old:] = (-np.inf if name == "maxes"
                           else -1 if name == "latest_times" else 0)
            setattr(self, name, grown)

    def row_for(self, entity: Hashable) -> int:
        row = self._rows.get(entity)
        if row is None:
            if self._free:
                row = self._free.pop()
            else:
                row = len(self._rows)
                if row >= self.capacity:
                    self._grow(row + 1)
            self._rows[entity] = row
        return row

    def rows_for(self, entities: list[Hashable]) -> np.ndarray:
        # Steady state (every entity already known — the every-round case
        # at LinkedIn scale): a plain list-comp over dict __getitem__ is
        # ~2x faster than fromiter over .get-with-default at 1M tuple
        # keys; only a miss (KeyError) drops to the allocating path.
        m = self._rows
        try:
            return np.asarray([m[e] for e in entities], np.int64)
        except KeyError:
            pass
        get = m.get
        out = np.fromiter((get(e, -1) for e in entities), np.int64,
                          len(entities))
        missing = out < 0
        idxs = np.nonzero(missing)[0]
        need = len(m) + len(idxs) - len(self._free)
        if need > self.capacity:
            self._grow(need)
        for i in idxs:
            out[i] = self.row_for(entities[i])
        return out

    def get_row(self, entity: Hashable) -> int | None:
        return self._rows.get(entity)

    def lookup_rows(self, entities: list[Hashable]) -> np.ndarray:
        """Row index per entity, ``-1`` for entities with no state.
        Read-only counterpart of :meth:`rows_for` (never allocates rows)."""
        get = self._rows.get
        return np.fromiter((get(e, -1) for e in entities), np.int64,
                           len(entities))

    def entities(self) -> set[Hashable]:
        return set(self._rows)

    def drop(self, entity: Hashable) -> bool:
        row = self._rows.pop(entity, None)
        if row is None:
            return False
        self.clear_slots(np.array([row]), slice(None))
        self._free.append(row)
        return True

    def clear_slots(self, rows, slot) -> None:
        self.sums[rows, slot] = 0.0
        self.counts[rows, slot] = 0
        self.maxes[rows, slot] = -np.inf
        self.latest_values[rows, slot] = 0.0
        self.latest_times[rows, slot] = -1
        self.sample_counts[rows, slot] = 0

    def clear_slot_all(self, slot) -> None:
        self.clear_slots(slice(None), slot)

    # ------------------------------------------------------------- ingest
    def add(self, row: int, slot: int, time_ms: int,
            values: Mapping[int, float]) -> None:
        for metric_id, value in values.items():
            self.sums[row, slot, metric_id] += value
            self.counts[row, slot, metric_id] += 1
            if value > self.maxes[row, slot, metric_id]:
                self.maxes[row, slot, metric_id] = value
            if time_ms >= self.latest_times[row, slot, metric_id]:
                self.latest_times[row, slot, metric_id] = time_ms
                self.latest_values[row, slot, metric_id] = value
        self.sample_counts[row, slot] += 1

    def add_batch(self, rows: np.ndarray, slots: np.ndarray,
                  times: np.ndarray, values: np.ndarray) -> None:
        """Vectorized ingest of N samples x all metrics: ``values`` is
        [N, num_metrics] (NaN = metric absent from the sample)."""
        present = ~np.isnan(values)
        vals = np.where(present, values, 0.0)
        # One sample per (row, slot) — the every-round case — allows plain
        # fancy-indexed accumulation, ~10x faster than the unbuffered
        # np.ufunc.at scatter; duplicates fall back to the exact scatter.
        S = self._num_slots
        unique_targets = (len(np.unique(rows * S + slots)) == len(rows))
        if unique_targets:
            tgt2 = (rows, slots)
            self.sums[tgt2] += vals
            self.counts[tgt2] += present.astype(np.int32)
            self.maxes[tgt2] = np.maximum(self.maxes[tgt2],
                                          np.where(present, values, -np.inf))
            self.sample_counts[tgt2] += 1
        else:
            np.add.at(self.sums, (rows, slots), vals)
            np.add.at(self.counts, (rows, slots), present.astype(np.int32))
            np.maximum.at(self.maxes, (rows, slots),
                          np.where(present, values, -np.inf))
            np.add.at(self.sample_counts, (rows, slots), 1)
        # Latest-wins. Unique targets: one sample per cell, so a direct
        # where() against the stored timestamps suffices (no ordering
        # needed). Duplicates: process in ascending time order so plain
        # indexed assignment leaves the batch's newest value in place —
        # then restore any pre-existing state that is newer still
        # (late-arriving batches must not regress LATEST metrics, matching
        # the scalar guard).
        if unique_targets:
            lt = self.latest_times[tgt2]                     # [N, M]
            upd = present & (times[:, None] >= lt)
            self.latest_values[tgt2] = np.where(
                upd, values, self.latest_values[tgt2])
            self.latest_times[tgt2] = np.where(upd, times[:, None], lt)
            return
        order = np.argsort(times, kind="stable")
        ro, so, po = rows[order], slots[order], present[order]
        idx_e, idx_m = np.nonzero(po)
        tgt = (ro[idx_e], so[idx_e], idx_m)
        prev_t = self.latest_times[tgt].copy()
        prev_v = self.latest_values[tgt].copy()
        self.latest_values[tgt] = values[order][idx_e, idx_m]
        self.latest_times[tgt] = times[order][idx_e]
        newer = prev_t > self.latest_times[tgt]
        if newer.any():
            keep = tuple(a[newer] for a in tgt)
            self.latest_times[keep] = prev_t[newer]
            self.latest_values[keep] = prev_v[newer]


class MetricSampleAggregator:
    """The windowed aggregator (ref MetricSampleAggregator.java:84).

    Thread-safe for concurrent ``add_sample`` / ``aggregate``. Window layout:
    ``num_windows`` stable windows plus one *current* (in-flight) window; the
    current window is never included in aggregation results (ref ``:193``
    aggregates only rolled-out windows). Every window roll-out bumps
    ``generation`` which downstream proposal caches key on
    (ref LongGenerationed.java).
    """

    def __init__(self, num_windows: int, window_ms: int, min_samples_per_window: int,
                 metric_def: MetricDef,
                 entity_group_fn: Callable[[Hashable], Hashable] | None = None,
                 tracer=None) -> None:
        if num_windows <= 0 or window_ms <= 0 or min_samples_per_window <= 0:
            raise ValueError("num_windows, window_ms, min_samples_per_window must be > 0")
        from .tracing import default_tracer
        #: span tracer: every aggregate() emits an ``aggregator.aggregate``
        #: span so model-build latency attributes between aggregation and
        #: flat-model assembly.
        self._tracer = tracer or default_tracer()
        self._num_windows = num_windows
        self._window_ms = window_ms
        self._min_samples = min_samples_per_window
        self._metric_def = metric_def
        self._num_metrics = metric_def.size()
        self._num_slots = num_windows + 1
        self._entity_group_fn = entity_group_fn or (lambda entity: entity)
        self._raw = _RawStore(self._num_slots, self._num_metrics)
        self._oldest_window_index = 0        # window index of slot window_index % slots
        self._current_window_index = 0
        self._initialized = False
        self._generation = 0
        self._lock = threading.RLock()

    # ------------------------------------------------------------------ api
    @property
    def generation(self) -> int:
        # Deliberately lock-free: a single int attribute read is atomic
        # under the GIL and the counter is monotonic, so the serving
        # tier's generation-keyed cache reads never contend with ingest
        # holding the aggregator lock.
        return self._generation

    def seed_generation(self, generation: int) -> None:
        """Raise the generation counter to at least ``generation`` —
        snapshot restore (core/snapshot.py): a restarted process resumes
        the pre-crash numbering so a restored generation-keyed cache is
        valid until real ingest rolls a window, and every later bump is
        strictly greater than anything the pre-crash process issued."""
        with self._lock:
            self._generation = max(self._generation, int(generation))

    @property
    def window_ms(self) -> int:
        return self._window_ms

    @property
    def num_windows(self) -> int:
        return self._num_windows

    @property
    def num_metrics(self) -> int:
        return self._num_metrics

    def window_index(self, time_ms: int) -> int:
        return time_ms // self._window_ms

    def add_sample(self, sample: MetricSample) -> bool:
        """Add one sample; returns False if it falls before the retained range
        (ref addSample MetricSampleAggregator.java:141)."""
        with self._lock:
            index = self.window_index(sample.sample_time_ms)
            if not self._initialized:
                self._initialized = True
                self._current_window_index = index
                self._oldest_window_index = index
            if index > self._current_window_index:
                self._roll_out_to(index)
            if index < self._oldest_window_index:
                return False
            row = self._raw.row_for(sample.entity)
            self._raw.add(row, index % self._num_slots,
                          sample.sample_time_ms, sample.values)
            return True

    def add_samples_dense(self, entities: list[Hashable],
                          times_ms: np.ndarray,
                          values: np.ndarray) -> int:
        """Vectorized bulk ingest: N samples as parallel arrays —
        ``times_ms`` [N] int64, ``values`` [N, num_metrics] float64 with
        NaN marking absent metrics. The scalable ingest path for
        LinkedIn-scale sample volumes (the per-sample dict loop of
        ``add_sample`` costs hours at 1M partitions x windows); windows are
        rolled out in time order exactly as the scalar path would. Returns
        the number of samples retained."""
        times_ms = np.asarray(times_ms, np.int64)
        values = np.asarray(values, np.float64)
        with self._lock:
            windows = times_ms // self._window_ms
            if not self._initialized and len(windows):
                self._initialized = True
                start = int(windows.min())
                self._current_window_index = start
                self._oldest_window_index = start
            if len(windows) and int(windows.max()) > self._current_window_index:
                self._roll_out_to(int(windows.max()))
            keep = windows >= self._oldest_window_index
            if not keep.all():
                times_ms, values = times_ms[keep], values[keep]
                windows = windows[keep]
                entities = [e for e, k in zip(entities, keep) if k]
            if not len(windows):
                return 0
            rows = self._raw.rows_for(entities)
            self._raw.add_batch(rows, (windows % self._num_slots).astype(
                np.int64), times_ms, values)
            return len(windows)

    def retain_entities(self, entities: set[Hashable]) -> None:
        """Drop state for entities no longer in the cluster (ref retainEntities)."""
        with self._lock:
            removed = self._raw.entities() - entities
            for entity in removed:
                self._raw.drop(entity)
            if removed:
                self._generation += 1

    def remove_entities(self, entities: set[Hashable]) -> None:
        with self._lock:
            # Every entity must be dropped; an ``any(gen)`` would stop at
            # the first True and leave the rest of the pool populated, so
            # the no-short-circuit contract is structural here.
            dropped = False
            for entity in entities:
                if self._raw.drop(entity):
                    dropped = True
            if dropped:
                self._generation += 1

    def all_entities(self) -> set[Hashable]:
        with self._lock:
            return self._raw.entities()

    def num_available_windows(self) -> int:
        with self._lock:
            if not self._initialized:
                return 0
            return self._current_window_index - self._oldest_window_index

    def available_window_times(self) -> list[int]:
        with self._lock:
            return [w * self._window_ms
                    for w in range(self._oldest_window_index, self._current_window_index)]

    # ------------------------------------------------------------ aggregate
    @staticmethod
    def _sorted_entities(entities: set[Hashable]) -> list[Hashable]:
        # Entities are homogeneous per aggregator ((topic, partition)
        # tuples or int broker ids), so a plain sort works; ``key=repr``
        # would allocate a string per entity — a million strings per
        # aggregation round at LinkedIn scale. The fallback only exists
        # for exotic mixed-type entity spaces.
        try:
            return sorted(entities)
        except TypeError:
            return sorted(entities, key=repr)

    def aggregate(self, from_ms: int, to_ms: int,
                  options: AggregationOptions | None = None, *,
                  use_dense: bool = True) -> MetricSampleAggregationResult:
        """Aggregate rolled-out windows overlapping [from_ms, to_ms]
        (ref aggregate MetricSampleAggregator.java:193).

        ``use_dense=True`` (the default) computes the whole entity pool as
        one ``[E, M, W]`` array program; ``use_dense=False`` runs the
        retained per-entity reference implementation (kept for the
        dense/legacy parity property tests and as executable
        documentation of the ladder). Both produce identical results —
        bit-identical values, codes, and completeness."""
        options = options or AggregationOptions()
        with self._tracer.span("aggregator.aggregate",
                               dense=use_dense), self._lock:
            window_indices = [w for w in range(self._oldest_window_index,
                                               self._current_window_index)
                              if w * self._window_ms <= to_ms
                              and (w + 1) * self._window_ms > from_ms]
            # Interested entities with no samples at all still count: they are
            # invalid and sit in the completeness denominator (ref
            # MetricSampleAggregator peeks every interested entity; an
            # unmonitored partition must drag the valid-entity ratio down,
            # not silently vanish from it).
            entities = (self._raw.entities()
                        if options.interested_entities is None
                        else set(options.interested_entities))
            num_win = len(window_indices)
            completeness = MetricSampleCompleteness(generation=self._generation,
                                                    num_total_entities=len(entities))
            entity_values: dict[Hashable, ValuesAndExtrapolations] = {}
            invalid_entities: set[Hashable] = set()
            if num_win == 0:
                if options.min_valid_windows > 0:
                    raise NotEnoughValidWindowsError(
                        f"0 valid windows, {options.min_valid_windows} required "
                        f"(in range [{from_ms}, {to_ms}])")
                return MetricSampleAggregationResult(self._generation, [], {},
                                                     completeness, entities)

            entity_list = self._sorted_entities(entities)
            if use_dense:
                return self._aggregate_dense(entity_list, window_indices,
                                             options, completeness,
                                             from_ms, to_ms)

            valid_matrix = np.zeros((len(entities), num_win), dtype=bool)
            for i, entity in enumerate(entity_list):
                vae, window_valid = self._aggregate_entity(entity, window_indices, options)
                entity_values[entity] = vae
                valid_matrix[i] = window_valid
                if window_valid.all():
                    completeness.valid_entities.add(entity)
                else:
                    invalid_entities.add(entity)

            if options.granularity is AggregationGranularity.ENTITY_GROUP:
                # One invalid entity invalidates its whole group (ref
                # AggregationOptions.Granularity.ENTITY_GROUP): demote every
                # entity sharing a group with an invalid one.
                invalid_groups = {self._entity_group_fn(e) for e in invalid_entities}
                demoted = {e for e in completeness.valid_entities
                           if self._entity_group_fn(e) in invalid_groups}
                completeness.valid_entities -= demoted
                invalid_entities |= demoted

            self._fill_completeness(completeness, entity_list, valid_matrix,
                                    window_indices, options)
            if len(completeness.valid_windows) < options.min_valid_windows:
                raise NotEnoughValidWindowsError(
                    f"{len(completeness.valid_windows)} valid windows, "
                    f"{options.min_valid_windows} required "
                    f"(in range [{from_ms}, {to_ms}])")
            return MetricSampleAggregationResult(self._generation,
                                                 completeness.valid_windows,
                                                 entity_values, completeness,
                                                 invalid_entities)

    def _aggregate_dense(self, entity_list: list[Hashable],
                         window_indices: list[int],
                         options: AggregationOptions,
                         completeness: MetricSampleCompleteness,
                         from_ms: int, to_ms: int
                         ) -> MetricSampleAggregationResult:
        """The dense whole-pool aggregation: one ``[E, M, W]`` program.

        Replaces E invocations of :meth:`_aggregate_entity` with masked
        array selects over the ``_RawStore`` pool — window validity is one
        boolean matrix, the extrapolation ladder is four masks, and the
        per-entity extrapolation budget is a cumulative count along the
        window axis. Bit-identical to the reference path: the same
        elementwise operations run in the same order, just batched."""
        E, W = len(entity_list), len(window_indices)
        M, S = self._num_metrics, self._num_slots
        raw = self._raw
        rows = raw.lookup_rows(entity_list)
        present = rows >= 0
        rs = np.where(present, rows, 0)

        win = np.asarray(window_indices, np.int64)   # contiguous span
        slots = win % S

        # --- window values for every (entity, slot): [E, S, M] ----------
        # AVG everywhere first (one fused gather+divide), then the MAX /
        # LATEST metric columns are overwritten via np.ix_ open-mesh
        # gathers so only the needed columns are materialized.
        base = raw.sums[rs] / np.maximum(raw.counts[rs], 1)
        max_ids = [info.id for info in self._metric_def.all_metrics()
                   if info.strategy is AggregationFunction.MAX]
        latest_ids = [info.id for info in self._metric_def.all_metrics()
                      if info.strategy is AggregationFunction.LATEST]
        slot_range = np.arange(S)
        if max_ids:
            gm = raw.maxes[np.ix_(rs, slot_range, np.asarray(max_ids))]
            base[:, :, max_ids] = np.where(np.isfinite(gm), gm, 0.0)
        if latest_ids:
            base[:, :, latest_ids] = raw.latest_values[
                np.ix_(rs, slot_range, np.asarray(latest_ids))]

        # --- validity + the extrapolation ladder as masks ----------------
        sc_all = np.where(present[:, None], raw.sample_counts[rs], 0)
        scnt = sc_all[:, slots]
        valid0 = scnt >= self._min_samples                        # NONE
        half_min = max(1, self._min_samples // 2)
        avail = ~valid0 & (scnt >= half_min)                      # AVG_AVAILABLE

        # Neighbor qualification over the extended range [w0-1, wN+1]:
        # a neighbor must be inside retention AND fully sampled.
        ext_win = np.arange(win[0] - 1, win[-1] + 2)
        in_ret = ((ext_win >= self._oldest_window_index)
                  & (ext_win < self._current_window_index))
        ext_slots = ext_win % S
        nfull = (sc_all[:, ext_slots] >= self._min_samples) & in_ret[None, :]
        left_ok, right_ok = nfull[:, :W], nfull[:, 2:]
        adj = ~valid0 & ~avail & (left_ok | right_ok)             # AVG_ADJACENT
        forced = ~valid0 & ~avail & ~adj & (scnt > 0)             # FORCED_INSUFFICIENT

        # Budget: only windows where an extrapolation actually applies
        # burn it (ref maxAllowedExtrapolationsPerEntity accounting —
        # hopeless windows never consume budget). The reference's running
        # counter is an exclusive cumulative count along the window axis.
        burn = avail | adj | forced
        prior_burns = np.cumsum(burn, axis=1, dtype=np.int64) - burn
        allowed = prior_burns < options.max_allowed_extrapolations_per_entity
        window_valid = valid0 | (burn & allowed)

        codes = np.full((E, W), Extrapolation.NO_VALID_EXTRAPOLATION.value,
                        np.int8)
        codes[valid0] = Extrapolation.NONE.value
        codes[avail & allowed] = Extrapolation.AVG_AVAILABLE.value
        codes[adj & allowed] = Extrapolation.AVG_ADJACENT.value
        codes[forced & allowed] = Extrapolation.FORCED_INSUFFICIENT.value

        # --- values: own slot for NONE/AVAILABLE/FORCED, neighbor mean
        # for ADJACENT, zero for invalid windows -------------------------
        own = base[:, slots, :]                                   # [E, W, M]
        nmean_den = np.maximum(
            left_ok.astype(np.float64) + right_ok, 1.0)[:, :, None]
        adj_val = (base[:, ext_slots[:W], :] * left_ok[:, :, None]
                   + base[:, ext_slots[2:], :] * right_ok[:, :, None]
                   ) / nmean_den
        vals = np.where((codes == Extrapolation.AVG_ADJACENT.value)[:, :, None],
                        adj_val, own)
        vals = np.where(window_valid[:, :, None], vals, 0.0)
        values = np.ascontiguousarray(vals.transpose(0, 2, 1))    # [E, M, W]

        # --- entity/group validity + demotion ----------------------------
        entity_valid = window_valid.all(axis=1)
        gid_map: dict[Hashable, int] = {}
        group_fn = self._entity_group_fn
        gids = np.fromiter(
            (gid_map.setdefault(group_fn(e), len(gid_map))
             for e in entity_list), np.int64, E)
        G = len(gid_map)
        group_has_invalid = (np.bincount(gids[~entity_valid], minlength=G)
                             > 0) if G else np.zeros(0, bool)
        post_valid = entity_valid
        if options.granularity is AggregationGranularity.ENTITY_GROUP and E:
            # One invalid entity invalidates its whole group (ref
            # AggregationOptions.Granularity.ENTITY_GROUP).
            post_valid = entity_valid & ~group_has_invalid[gids]
        valid_rows = np.nonzero(post_valid)[0]
        invalid_rows = np.nonzero(~post_valid)[0]
        completeness.valid_entities = {entity_list[i] for i in valid_rows}
        invalid_entities = {entity_list[i] for i in invalid_rows}

        # --- completeness (vectorized _fill_completeness) ----------------
        num_entities = max(1, E)
        valid_per_window = window_valid.sum(axis=0)
        any_valid = window_valid.any(axis=0)
        if G:
            inv_per_gw = np.zeros((G, W), np.int64)
            np.add.at(inv_per_gw, gids, (~window_valid).astype(np.int64))
            inv_groups_per_window = (inv_per_gw > 0).sum(axis=0)
        else:
            inv_groups_per_window = np.zeros(W, np.int64)
        for j, w in enumerate(window_indices):
            ratio = float(valid_per_window[j]) / num_entities
            completeness.valid_entity_ratio_by_window[w] = ratio
            group_ratio = (1.0 - int(inv_groups_per_window[j]) / G
                           if G else 0.0)
            completeness.valid_entity_group_ratio_by_window[w] = group_ratio
            # A window with zero valid entities is never valid, even when
            # the configured ratio floor is 0.0.
            meets = (ratio >= options.min_valid_entity_ratio
                     and bool(any_valid[j]))
            if options.granularity is AggregationGranularity.ENTITY_GROUP:
                meets = meets and (group_ratio
                                   >= options.min_valid_entity_group_ratio)
            if meets:
                completeness.valid_windows.append(w)
        if G:
            completeness.valid_entity_groups = {
                g for g, i in gid_map.items() if not group_has_invalid[i]}

        if len(completeness.valid_windows) < options.min_valid_windows:
            raise NotEnoughValidWindowsError(
                f"{len(completeness.valid_windows)} valid windows, "
                f"{options.min_valid_windows} required "
                f"(in range [{from_ms}, {to_ms}])")
        dense = DenseAggregate(
            entities=entity_list,
            row_index={e: i for i, e in enumerate(entity_list)},
            values=values, extrapolations=codes, window_valid=window_valid,
            window_indices=list(window_indices),
            window_times_ms=[w * self._window_ms for w in window_indices])
        return MetricSampleAggregationResult(
            self._generation, completeness.valid_windows,
            _LazyEntityValues(dense), completeness, invalid_entities,
            dense=dense)

    def _aggregate_entity(self, entity: Hashable, window_indices: list[int],
                          options: AggregationOptions
                          ) -> tuple[ValuesAndExtrapolations, np.ndarray]:
        num_win = len(window_indices)
        values = np.zeros((self._num_metrics, num_win), dtype=np.float64)
        extrapolations = [Extrapolation.NONE] * num_win
        window_valid = np.zeros(num_win, dtype=bool)
        num_extrapolations = 0

        row = self._raw.get_row(entity)
        if row is None:
            # Interested entity with no samples: every window invalid.
            extrapolations = [Extrapolation.NO_VALID_EXTRAPOLATION] * num_win
            window_times = [w * self._window_ms for w in window_indices]
            return (ValuesAndExtrapolations(values, extrapolations,
                                            window_times), window_valid)

        base = self._compute_window_values(row)
        counts = self._raw.sample_counts[row]

        for j, w in enumerate(window_indices):
            slot = w % self._num_slots
            count = int(counts[slot])
            if count >= self._min_samples:
                values[:, j] = base[:, slot]
                window_valid[j] = True
                continue
            # Extrapolate (ref RawMetricValues extrapolation ladder). The
            # budget is only consumed when an extrapolation actually applies —
            # windows that end NO_VALID_EXTRAPOLATION never burn budget.
            if num_extrapolations >= options.max_allowed_extrapolations_per_entity:
                extrapolations[j] = Extrapolation.NO_VALID_EXTRAPOLATION
                continue
            half_min = max(1, self._min_samples // 2)
            if count >= half_min:
                values[:, j] = base[:, slot]
                extrapolations[j] = Extrapolation.AVG_AVAILABLE
                window_valid[j] = True
                num_extrapolations += 1
                continue
            prev_w, next_w = w - 1, w + 1
            neighbor_slots = [x % self._num_slots for x in (prev_w, next_w)
                              if self._oldest_window_index <= x < self._current_window_index
                              and counts[x % self._num_slots] >= self._min_samples]
            if neighbor_slots:
                values[:, j] = base[:, neighbor_slots].mean(axis=1)
                extrapolations[j] = Extrapolation.AVG_ADJACENT
                window_valid[j] = True
                num_extrapolations += 1
            elif count > 0:
                values[:, j] = base[:, slot]
                extrapolations[j] = Extrapolation.FORCED_INSUFFICIENT
                window_valid[j] = True
                num_extrapolations += 1
            else:
                extrapolations[j] = Extrapolation.NO_VALID_EXTRAPOLATION
        window_times = [w * self._window_ms for w in window_indices]
        return ValuesAndExtrapolations(values, extrapolations, window_times), window_valid

    def _compute_window_values(self, row: int) -> np.ndarray:
        """Apply each metric's aggregation strategy over raw per-slot state.

        Returns ``[num_metrics, num_slots]``.
        """
        raw = self._raw
        out = np.zeros((self._num_metrics, self._num_slots), dtype=np.float64)
        safe_counts = np.maximum(raw.counts[row], 1)
        avg = (raw.sums[row] / safe_counts).T
        maxes = np.where(np.isfinite(raw.maxes[row]), raw.maxes[row], 0.0).T
        latest = raw.latest_values[row].T
        for info in self._metric_def.all_metrics():
            if info.strategy is AggregationFunction.AVG:
                out[info.id] = avg[info.id]
            elif info.strategy is AggregationFunction.MAX:
                out[info.id] = maxes[info.id]
            else:
                out[info.id] = latest[info.id]
        return out

    def _fill_completeness(self, completeness: MetricSampleCompleteness,
                           entity_list: list[Hashable], valid_matrix: np.ndarray,
                           window_indices: list[int], options: AggregationOptions) -> None:
        num_entities = max(1, len(entity_list))
        groups = [self._entity_group_fn(entity) for entity in entity_list]
        unique_groups = set(groups)
        for j, w in enumerate(window_indices):
            ratio = float(valid_matrix[:, j].sum()) / num_entities
            completeness.valid_entity_ratio_by_window[w] = ratio
            invalid_groups = {groups[i] for i in range(len(entity_list))
                              if not valid_matrix[i, j]}
            group_ratio = (1.0 - len(invalid_groups) / len(unique_groups)
                           if unique_groups else 0.0)
            completeness.valid_entity_group_ratio_by_window[w] = group_ratio
            # A window with zero valid entities is never valid, even when the
            # configured ratio floor is 0.0 (otherwise a time-jump reset would
            # hand downstream an all-zero "complete" model).
            meets = ratio >= options.min_valid_entity_ratio and bool(
                valid_matrix[:, j].any())
            if options.granularity is AggregationGranularity.ENTITY_GROUP:
                meets = meets and group_ratio >= options.min_valid_entity_group_ratio
            if meets:
                completeness.valid_windows.append(w)
        for i, entity in enumerate(entity_list):
            if valid_matrix[i].all():
                completeness.valid_entity_groups.add(groups[i])
        completeness.valid_entity_groups -= {self._entity_group_fn(entity)
                                             for i, entity in enumerate(entity_list)
                                             if not valid_matrix[i].all()}

    # ------------------------------------------------------------- internal
    def _roll_out_to(self, new_current: int) -> None:
        steps = new_current - self._current_window_index
        if steps >= self._num_slots:
            for slot in range(self._num_slots):
                self._raw.clear_slot_all(slot)
            self._current_window_index = new_current
            self._oldest_window_index = new_current - self._num_windows
            self._generation += 1
            return
        for w in range(self._current_window_index + 1, new_current + 1):
            self._raw.clear_slot_all(w % self._num_slots)
        self._current_window_index = new_current
        self._oldest_window_index = max(self._oldest_window_index,
                                        new_current - self._num_windows)
        self._generation += 1
