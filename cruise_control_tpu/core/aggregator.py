"""Windowed metric sample aggregation.

Rebuild of the reference's core aggregator (``monitor/sampling/aggregator/
MetricSampleAggregator.java:84`` with ``RawMetricValues.java``,
``MetricSampleCompleteness.java``, ``AggregationOptions.java``): raw samples
are rolled into fixed-width time windows per entity (partition or broker),
with extrapolation for windows that have too few samples, and completeness
accounting that gates model generation downstream.

Unlike the reference's per-entity object graph, each entity's raw window
state is a set of numpy ring buffers (``[num_windows+1, num_metrics]``), and
aggregation emits dense ``[num_entities, num_metrics, num_windows]`` arrays
ready to be flattened into the device-side cluster model.
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Callable, Hashable, Mapping

import numpy as np

from .metricdef import AggregationFunction, MetricDef


class Extrapolation(enum.Enum):
    """Per-window extrapolation applied when samples are insufficient.

    Mirrors ``Extrapolation.java``: NONE means the window had enough samples;
    the others record how the gap was filled (and count against
    ``max_allowed_extrapolations_per_entity``).
    """

    NONE = 0
    AVG_AVAILABLE = 1
    AVG_ADJACENT = 2
    FORCED_INSUFFICIENT = 3
    NO_VALID_EXTRAPOLATION = 4


class NotEnoughValidWindowsError(RuntimeError):
    """Raised when fewer valid windows exist than the caller requires
    (ref MetricSampleAggregator.aggregate -> NotEnoughValidWindowsException)."""


@dataclass(frozen=True)
class MetricSample:
    """One raw sample: an entity, a sample time, and a metric-id->value map."""

    entity: Hashable
    sample_time_ms: int
    values: Mapping[int, float]
    entity_group: Hashable | None = None


class AggregationGranularity(enum.Enum):
    """ref AggregationOptions.Granularity: whether one invalid entity
    invalidates just itself or its whole entity group (topic)."""

    ENTITY = "entity"
    ENTITY_GROUP = "entity_group"


@dataclass
class AggregationOptions:
    min_valid_entity_ratio: float = 0.0
    min_valid_entity_group_ratio: float = 0.0
    min_valid_windows: int = 1
    max_allowed_extrapolations_per_entity: int = 5
    granularity: AggregationGranularity = AggregationGranularity.ENTITY
    interested_entities: set[Hashable] | None = None


@dataclass
class MetricSampleCompleteness:
    """ref MetricSampleCompleteness.java: which windows are valid and how
    much of the entity space they cover."""

    generation: int
    valid_windows: list[int] = field(default_factory=list)
    valid_entity_ratio_by_window: dict[int, float] = field(default_factory=dict)
    valid_entity_group_ratio_by_window: dict[int, float] = field(default_factory=dict)
    valid_entities: set[Hashable] = field(default_factory=set)
    valid_entity_groups: set[Hashable] = field(default_factory=set)
    num_total_entities: int = 0

    @property
    def valid_entity_ratio(self) -> float:
        if not self.num_total_entities:
            return 0.0
        return len(self.valid_entities) / self.num_total_entities


@dataclass
class ValuesAndExtrapolations:
    """Aggregated values for one entity: ``[num_metrics, num_windows]`` plus
    the extrapolation applied per window (ref ValuesAndExtrapolations.java)."""

    values: np.ndarray
    extrapolations: list[Extrapolation]
    window_times_ms: list[int]


@dataclass
class MetricSampleAggregationResult:
    generation: int
    valid_windows: list[int]
    entity_values: dict[Hashable, ValuesAndExtrapolations]
    completeness: MetricSampleCompleteness
    invalid_entities: set[Hashable]


class _RawMetricValues:
    """Ring-buffered raw window state for one entity (ref RawMetricValues.java).

    Keeps per-window per-metric sum/count/max/latest so AVG/MAX/LATEST
    aggregation strategies can all be served.
    """

    __slots__ = ("sums", "counts", "maxes", "latest_values", "latest_times",
                 "sample_counts")

    def __init__(self, num_slots: int, num_metrics: int) -> None:
        self.sums = np.zeros((num_slots, num_metrics), dtype=np.float64)
        self.counts = np.zeros((num_slots, num_metrics), dtype=np.int32)
        self.maxes = np.full((num_slots, num_metrics), -np.inf, dtype=np.float64)
        self.latest_values = np.zeros((num_slots, num_metrics), dtype=np.float64)
        self.latest_times = np.full((num_slots, num_metrics), -1, dtype=np.int64)
        self.sample_counts = np.zeros(num_slots, dtype=np.int32)

    def clear_slot(self, slot: int) -> None:
        self.sums[slot] = 0.0
        self.counts[slot] = 0
        self.maxes[slot] = -np.inf
        self.latest_values[slot] = 0.0
        self.latest_times[slot] = -1
        self.sample_counts[slot] = 0

    def add(self, slot: int, time_ms: int, values: Mapping[int, float]) -> None:
        for metric_id, value in values.items():
            self.sums[slot, metric_id] += value
            self.counts[slot, metric_id] += 1
            if value > self.maxes[slot, metric_id]:
                self.maxes[slot, metric_id] = value
            if time_ms >= self.latest_times[slot, metric_id]:
                self.latest_times[slot, metric_id] = time_ms
                self.latest_values[slot, metric_id] = value
        self.sample_counts[slot] += 1


class MetricSampleAggregator:
    """The windowed aggregator (ref MetricSampleAggregator.java:84).

    Thread-safe for concurrent ``add_sample`` / ``aggregate``. Window layout:
    ``num_windows`` stable windows plus one *current* (in-flight) window; the
    current window is never included in aggregation results (ref ``:193``
    aggregates only rolled-out windows). Every window roll-out bumps
    ``generation`` which downstream proposal caches key on
    (ref LongGenerationed.java).
    """

    def __init__(self, num_windows: int, window_ms: int, min_samples_per_window: int,
                 metric_def: MetricDef,
                 entity_group_fn: Callable[[Hashable], Hashable] | None = None) -> None:
        if num_windows <= 0 or window_ms <= 0 or min_samples_per_window <= 0:
            raise ValueError("num_windows, window_ms, min_samples_per_window must be > 0")
        self._num_windows = num_windows
        self._window_ms = window_ms
        self._min_samples = min_samples_per_window
        self._metric_def = metric_def
        self._num_metrics = metric_def.size()
        self._num_slots = num_windows + 1
        self._entity_group_fn = entity_group_fn or (lambda entity: entity)
        self._raw: dict[Hashable, _RawMetricValues] = {}
        self._oldest_window_index = 0        # window index of slot window_index % slots
        self._current_window_index = 0
        self._initialized = False
        self._generation = 0
        self._lock = threading.RLock()

    # ------------------------------------------------------------------ api
    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    @property
    def window_ms(self) -> int:
        return self._window_ms

    @property
    def num_windows(self) -> int:
        return self._num_windows

    def window_index(self, time_ms: int) -> int:
        return time_ms // self._window_ms

    def add_sample(self, sample: MetricSample) -> bool:
        """Add one sample; returns False if it falls before the retained range
        (ref addSample MetricSampleAggregator.java:141)."""
        with self._lock:
            index = self.window_index(sample.sample_time_ms)
            if not self._initialized:
                self._initialized = True
                self._current_window_index = index
                self._oldest_window_index = index
            if index > self._current_window_index:
                self._roll_out_to(index)
            if index < self._oldest_window_index:
                return False
            raw = self._raw.get(sample.entity)
            if raw is None:
                raw = _RawMetricValues(self._num_slots, self._num_metrics)
                self._raw[sample.entity] = raw
            raw.add(index % self._num_slots, sample.sample_time_ms, sample.values)
            return True

    def retain_entities(self, entities: set[Hashable]) -> None:
        """Drop state for entities no longer in the cluster (ref retainEntities)."""
        with self._lock:
            removed = set(self._raw) - entities
            for entity in removed:
                del self._raw[entity]
            if removed:
                self._generation += 1

    def remove_entities(self, entities: set[Hashable]) -> None:
        with self._lock:
            for entity in entities:
                self._raw.pop(entity, None)
            if entities:
                self._generation += 1

    def all_entities(self) -> set[Hashable]:
        with self._lock:
            return set(self._raw)

    def num_available_windows(self) -> int:
        with self._lock:
            if not self._initialized:
                return 0
            return self._current_window_index - self._oldest_window_index

    def available_window_times(self) -> list[int]:
        with self._lock:
            return [w * self._window_ms
                    for w in range(self._oldest_window_index, self._current_window_index)]

    # ------------------------------------------------------------ aggregate
    def aggregate(self, from_ms: int, to_ms: int,
                  options: AggregationOptions | None = None) -> MetricSampleAggregationResult:
        """Aggregate rolled-out windows overlapping [from_ms, to_ms]
        (ref aggregate MetricSampleAggregator.java:193)."""
        options = options or AggregationOptions()
        with self._lock:
            window_indices = [w for w in range(self._oldest_window_index,
                                               self._current_window_index)
                              if w * self._window_ms <= to_ms
                              and (w + 1) * self._window_ms > from_ms]
            # Interested entities with no samples at all still count: they are
            # invalid and sit in the completeness denominator (ref
            # MetricSampleAggregator peeks every interested entity; an
            # unmonitored partition must drag the valid-entity ratio down,
            # not silently vanish from it).
            entities = (set(self._raw) if options.interested_entities is None
                        else set(options.interested_entities))
            num_win = len(window_indices)
            completeness = MetricSampleCompleteness(generation=self._generation,
                                                    num_total_entities=len(entities))
            entity_values: dict[Hashable, ValuesAndExtrapolations] = {}
            invalid_entities: set[Hashable] = set()
            if num_win == 0:
                if options.min_valid_windows > 0:
                    raise NotEnoughValidWindowsError(
                        f"0 valid windows, {options.min_valid_windows} required "
                        f"(in range [{from_ms}, {to_ms}])")
                return MetricSampleAggregationResult(self._generation, [], {},
                                                     completeness, entities)

            valid_matrix = np.zeros((len(entities), num_win), dtype=bool)
            entity_list = sorted(entities, key=repr)
            for i, entity in enumerate(entity_list):
                vae, window_valid = self._aggregate_entity(entity, window_indices, options)
                entity_values[entity] = vae
                valid_matrix[i] = window_valid
                if window_valid.all():
                    completeness.valid_entities.add(entity)
                else:
                    invalid_entities.add(entity)

            if options.granularity is AggregationGranularity.ENTITY_GROUP:
                # One invalid entity invalidates its whole group (ref
                # AggregationOptions.Granularity.ENTITY_GROUP): demote every
                # entity sharing a group with an invalid one.
                invalid_groups = {self._entity_group_fn(e) for e in invalid_entities}
                demoted = {e for e in completeness.valid_entities
                           if self._entity_group_fn(e) in invalid_groups}
                completeness.valid_entities -= demoted
                invalid_entities |= demoted

            self._fill_completeness(completeness, entity_list, valid_matrix,
                                    window_indices, options)
            if len(completeness.valid_windows) < options.min_valid_windows:
                raise NotEnoughValidWindowsError(
                    f"{len(completeness.valid_windows)} valid windows, "
                    f"{options.min_valid_windows} required "
                    f"(in range [{from_ms}, {to_ms}])")
            return MetricSampleAggregationResult(self._generation,
                                                 completeness.valid_windows,
                                                 entity_values, completeness,
                                                 invalid_entities)

    def _aggregate_entity(self, entity: Hashable, window_indices: list[int],
                          options: AggregationOptions
                          ) -> tuple[ValuesAndExtrapolations, np.ndarray]:
        num_win = len(window_indices)
        values = np.zeros((self._num_metrics, num_win), dtype=np.float64)
        extrapolations = [Extrapolation.NONE] * num_win
        window_valid = np.zeros(num_win, dtype=bool)
        num_extrapolations = 0

        raw = self._raw.get(entity)
        if raw is None:
            # Interested entity with no samples: every window invalid.
            extrapolations = [Extrapolation.NO_VALID_EXTRAPOLATION] * num_win
            window_times = [w * self._window_ms for w in window_indices]
            return (ValuesAndExtrapolations(values, extrapolations,
                                            window_times), window_valid)

        base = self._compute_window_values(raw)
        counts = raw.sample_counts

        for j, w in enumerate(window_indices):
            slot = w % self._num_slots
            count = int(counts[slot])
            if count >= self._min_samples:
                values[:, j] = base[:, slot]
                window_valid[j] = True
                continue
            # Extrapolate (ref RawMetricValues extrapolation ladder). The
            # budget is only consumed when an extrapolation actually applies —
            # windows that end NO_VALID_EXTRAPOLATION never burn budget.
            if num_extrapolations >= options.max_allowed_extrapolations_per_entity:
                extrapolations[j] = Extrapolation.NO_VALID_EXTRAPOLATION
                continue
            half_min = max(1, self._min_samples // 2)
            if count >= half_min:
                values[:, j] = base[:, slot]
                extrapolations[j] = Extrapolation.AVG_AVAILABLE
                window_valid[j] = True
                num_extrapolations += 1
                continue
            prev_w, next_w = w - 1, w + 1
            neighbor_slots = [x % self._num_slots for x in (prev_w, next_w)
                              if self._oldest_window_index <= x < self._current_window_index
                              and counts[x % self._num_slots] >= self._min_samples]
            if neighbor_slots:
                values[:, j] = base[:, neighbor_slots].mean(axis=1)
                extrapolations[j] = Extrapolation.AVG_ADJACENT
                window_valid[j] = True
                num_extrapolations += 1
            elif count > 0:
                values[:, j] = base[:, slot]
                extrapolations[j] = Extrapolation.FORCED_INSUFFICIENT
                window_valid[j] = True
                num_extrapolations += 1
            else:
                extrapolations[j] = Extrapolation.NO_VALID_EXTRAPOLATION
        window_times = [w * self._window_ms for w in window_indices]
        return ValuesAndExtrapolations(values, extrapolations, window_times), window_valid

    def _compute_window_values(self, raw: _RawMetricValues) -> np.ndarray:
        """Apply each metric's aggregation strategy over raw per-slot state.

        Returns ``[num_metrics, num_slots]``.
        """
        out = np.zeros((self._num_metrics, self._num_slots), dtype=np.float64)
        safe_counts = np.maximum(raw.counts, 1)
        avg = (raw.sums / safe_counts).T
        maxes = np.where(np.isfinite(raw.maxes), raw.maxes, 0.0).T
        latest = raw.latest_values.T
        for info in self._metric_def.all_metrics():
            if info.strategy is AggregationFunction.AVG:
                out[info.id] = avg[info.id]
            elif info.strategy is AggregationFunction.MAX:
                out[info.id] = maxes[info.id]
            else:
                out[info.id] = latest[info.id]
        return out

    def _fill_completeness(self, completeness: MetricSampleCompleteness,
                           entity_list: list[Hashable], valid_matrix: np.ndarray,
                           window_indices: list[int], options: AggregationOptions) -> None:
        num_entities = max(1, len(entity_list))
        groups = [self._entity_group_fn(entity) for entity in entity_list]
        unique_groups = set(groups)
        for j, w in enumerate(window_indices):
            ratio = float(valid_matrix[:, j].sum()) / num_entities
            completeness.valid_entity_ratio_by_window[w] = ratio
            invalid_groups = {groups[i] for i in range(len(entity_list))
                              if not valid_matrix[i, j]}
            group_ratio = (1.0 - len(invalid_groups) / len(unique_groups)
                           if unique_groups else 0.0)
            completeness.valid_entity_group_ratio_by_window[w] = group_ratio
            # A window with zero valid entities is never valid, even when the
            # configured ratio floor is 0.0 (otherwise a time-jump reset would
            # hand downstream an all-zero "complete" model).
            meets = ratio >= options.min_valid_entity_ratio and bool(
                valid_matrix[:, j].any())
            if options.granularity is AggregationGranularity.ENTITY_GROUP:
                meets = meets and group_ratio >= options.min_valid_entity_group_ratio
            if meets:
                completeness.valid_windows.append(w)
        for i, entity in enumerate(entity_list):
            if valid_matrix[i].all():
                completeness.valid_entity_groups.add(groups[i])
        completeness.valid_entity_groups -= {self._entity_group_fn(entity)
                                             for i, entity in enumerate(entity_list)
                                             if not valid_matrix[i].all()}

    # ------------------------------------------------------------- internal
    def _roll_out_to(self, new_current: int) -> None:
        steps = new_current - self._current_window_index
        if steps >= self._num_slots:
            for raw in self._raw.values():
                for slot in range(self._num_slots):
                    raw.clear_slot(slot)
            self._current_window_index = new_current
            self._oldest_window_index = new_current - self._num_windows
            self._generation += 1
            return
        for w in range(self._current_window_index + 1, new_current + 1):
            slot = w % self._num_slots
            for raw in self._raw.values():
                raw.clear_slot(slot)
        self._current_window_index = new_current
        self._oldest_window_index = max(self._oldest_window_index,
                                        new_current - self._num_windows)
        self._generation += 1
