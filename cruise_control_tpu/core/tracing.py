"""Span tracer: end-to-end pipeline tracing for the monitor → model →
optimize → execute loop.

The sensor registry (:mod:`core.sensors`) answers "how long do proposals
take on average"; it cannot answer "where did THIS proposal's latency go".
This module adds the missing axis: a thread-safe bounded ring buffer of
nested :class:`Span` records with a context-manager/decorator API, exported
as Chrome trace-event JSON (loadable in Perfetto / ``chrome://tracing``)
through the ``/trace`` endpoint and embedded in ``/state?substates=tracing``.

Design constraints:

- **Zero device syncs.** Spans only read the host clock
  (``time.perf_counter``); device-side search telemetry rides the
  optimizer's existing end-of-chain host fetch and is attached to spans as
  attributes after the fact (see ``analyzer/optimizer.py``).
- **Registry integration.** Every finished span also feeds a
  :class:`~cruise_control_tpu.core.sensors.Timer` named ``Span.<name>`` in
  the tracer's registry, so span populations surface on ``/metrics`` as
  Prometheus summary series without separate bookkeeping.
- **Reconstructed children.** Work that is unobservable from the host mid
  flight (the fused goal-chain walk: one device dispatch for G goals) is
  recorded after completion via :meth:`SpanTracer.record` with explicit
  start/parent — the per-goal child spans are rebuilt from the single-sync
  duration list.
- **Cross-thread wiring.** The active-span stack is thread-local; an async
  operation's worker thread starts its own root (the API layer wraps user
  tasks in a ``task.<endpoint>`` span), so every thread's spans nest
  correctly in its own Chrome-trace row.

One process-wide default tracer (:func:`default_tracer`) keeps wiring
optional: every subsystem accepts ``tracer=None`` and falls back to it, the
same way subsystems default to a private ``MetricRegistry``.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Callable

from .sensors import MetricRegistry

#: sensor group for span-fed timers (``Span.<span-name>``).
SPAN_SENSOR_GROUP = "Span"


class Span:
    """One finished span (immutable once recorded)."""

    __slots__ = ("span_id", "parent_id", "name", "start_s", "duration_s",
                 "thread_id", "thread_name", "attrs")

    def __init__(self, span_id: int, parent_id: int | None, name: str,
                 start_s: float, duration_s: float, thread_id: int,
                 thread_name: str, attrs: dict) -> None:
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start_s = start_s
        self.duration_s = duration_s
        self.thread_id = thread_id
        self.thread_name = thread_name
        self.attrs = attrs

    @property
    def end_s(self) -> float:
        return self.start_s + self.duration_s

    def to_json(self) -> dict:
        return {"spanId": self.span_id, "parentId": self.parent_id,
                "name": self.name,
                "startS": round(self.start_s, 6),
                "durationMs": round(self.duration_s * 1e3, 3),
                "thread": self.thread_name,
                "attributes": dict(self.attrs)}


class _ActiveSpan:
    """Context-manager handle for an in-flight span. ``set(**attrs)``
    attaches attributes before (or after) exit; exceptions are recorded as
    an ``error`` attribute and re-raised."""

    __slots__ = ("tracer", "name", "span_id", "parent_id", "start_s",
                 "attrs", "_finished")

    def __init__(self, tracer: "SpanTracer", name: str, attrs: dict) -> None:
        self.tracer = tracer
        self.name = name
        self.attrs = attrs
        self.span_id = next(tracer._ids)
        self.parent_id: int | None = None
        self.start_s = 0.0
        self._finished = False

    def set(self, **attrs) -> "_ActiveSpan":
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "_ActiveSpan":
        stack = self.tracer._stack()
        self.parent_id = stack[-1].span_id if stack else None
        stack.append(self)
        self.start_s = self.tracer._now()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        duration = self.tracer._now() - self.start_s
        stack = self.tracer._stack()
        # Pop self even if an inner span leaked (defensive unwinding).
        while stack and stack[-1] is not self:
            stack.pop()
        if stack:
            stack.pop()
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        if not self._finished:
            self._finished = True
            self.tracer._finish(self.name, self.start_s, duration,
                                self.parent_id, self.attrs,
                                span_id=self.span_id)
        return False


class _NoopSpan:
    """Shared do-nothing handle served while the tracer is disabled."""

    __slots__ = ()
    span_id = None
    parent_id = None
    start_s = 0.0

    def set(self, **attrs) -> "_NoopSpan":
        return self

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NOOP = _NoopSpan()


class SpanTracer:
    """Thread-safe bounded ring buffer of nested spans.

    ``capacity`` bounds memory: the buffer keeps the most recent spans and
    silently drops the oldest (``dropped_spans`` counts them). ``enabled``
    turns the whole tracer into a no-op — the bench's overhead A/B switch.
    """

    def __init__(self, capacity: int = 8192,
                 registry: MetricRegistry | None = None,
                 now: Callable[[], float] | None = None) -> None:
        from collections import deque
        self.capacity = int(capacity)
        self.registry = registry or MetricRegistry()
        self.enabled = True
        self._now = now or time.perf_counter
        self._epoch = self._now()
        self._spans: "deque[Span]" = deque(maxlen=self.capacity)
        self._dropped = 0
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._tls = threading.local()

    # ------------------------------------------------------------ recording
    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def span(self, name: str, **attrs) -> "_ActiveSpan | _NoopSpan":
        """``with tracer.span("optimizer.walk", mode="fused") as sp: ...``"""
        if not self.enabled:
            return _NOOP
        return _ActiveSpan(self, name, attrs)

    def traced(self, name: str | None = None):
        """Decorator form: ``@tracer.traced("monitor.train")``."""
        def deco(fn):
            import functools
            span_name = name or fn.__qualname__

            @functools.wraps(fn)
            def wrapper(*args, **kwargs):
                with self.span(span_name):
                    return fn(*args, **kwargs)
            return wrapper
        return deco

    def record(self, name: str, duration_s: float, *,
               start_s: float | None = None,
               parent_id: int | None | str = "current",
               attrs: dict | None = None) -> None:
        """Record an already-finished span — the reconstruction path for
        work with no observable host-side boundaries (per-goal slices of a
        fused device walk, executor task lifecycles stamped by the task
        tracker's clock). ``parent_id="current"`` (default) parents under
        this thread's active span; pass an explicit id (or None) to attach
        elsewhere."""
        if not self.enabled:
            return
        if parent_id == "current":
            stack = self._stack()
            parent_id = stack[-1].span_id if stack else None
        if start_s is None:
            start_s = self._now() - duration_s
        self._finish(name, start_s, duration_s, parent_id, attrs or {},
                     span_id=next(self._ids))

    def current_span_id(self) -> int | None:
        stack = self._stack()
        return stack[-1].span_id if stack else None

    def _finish(self, name, start_s, duration_s, parent_id, attrs,
                span_id=None) -> None:
        thread = threading.current_thread()
        span = Span(span_id if span_id is not None else next(self._ids),
                    parent_id, name, start_s, max(duration_s, 0.0),
                    thread.ident or 0, thread.name, attrs)
        with self._lock:
            if len(self._spans) >= self.capacity:
                self._dropped += 1
            self._spans.append(span)
        # Outside the buffer lock: the timer has its own.
        self.registry.timer(MetricRegistry.name(
            SPAN_SENSOR_GROUP, name)).update(span.duration_s)

    # -------------------------------------------------------------- reading
    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    @property
    def dropped_spans(self) -> int:
        return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._dropped = 0

    def to_json(self, limit: int = 256) -> dict:
        """Bounded recent-span snapshot for ``/state?substates=tracing``."""
        spans = self.spans()
        return {"numSpans": len(spans),
                "droppedSpans": self._dropped,
                "capacity": self.capacity,
                "spans": [s.to_json() for s in spans[-limit:]]}

    def to_chrome_trace(self) -> dict:
        """Chrome trace-event JSON (the ``/trace`` payload): complete
        ("X") events in microseconds relative to the tracer's epoch, plus
        thread-name metadata events — loadable as-is in Perfetto or
        ``chrome://tracing``."""
        pid = os.getpid()
        events: list[dict] = []
        seen_threads: dict[int, str] = {}
        for s in sorted(self.spans(), key=lambda s: s.start_s):
            seen_threads.setdefault(s.thread_id, s.thread_name)
            events.append({
                "name": s.name, "ph": "X", "cat": "cruise-control",
                "ts": round((s.start_s - self._epoch) * 1e6, 3),
                "dur": round(s.duration_s * 1e6, 3),
                "pid": pid, "tid": s.thread_id,
                "args": {**s.attrs, "spanId": s.span_id,
                         "parentId": s.parent_id}})
        meta = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                 "args": {"name": tname}}
                for tid, tname in sorted(seen_threads.items())]
        return {"displayTimeUnit": "ms", "traceEvents": meta + events}


#: process-wide default (the analog of the reference threading ONE
#: Dropwizard registry through every constructor): subsystems built with
#: ``tracer=None`` share it, so one /trace dump covers the whole loop.
_DEFAULT = SpanTracer()


def default_tracer() -> SpanTracer:
    return _DEFAULT
