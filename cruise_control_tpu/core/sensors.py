"""Self-metric sensors: the framework's own observability registry.

Rebuild of the reference's Dropwizard ``MetricRegistry`` usage — a registry
threaded through every subsystem constructor (ref
``KafkaCruiseControl.java:112``, ``GoalOptimizer.java:128``
``proposal-computation-timer``, ``LoadMonitor.java:101``
``cluster-model-creation-timer``, ``Executor.java:256-420`` execution
gauges/timers, ``AnomalyDetectorManager.java:183-216`` balancedness and
self-healing sensors, ``ExecutionTaskTracker.java:103-122`` per-state task
gauges) — exposed over HTTP instead of JMX: ``/metrics`` serves a
Prometheus-style text exposition and ``/state`` embeds the JSON snapshot.

Sensor types mirror the Dropwizard quartet:

- :class:`Counter` — monotonically increasing count.
- :class:`Meter` — count + event rate over a sliding window (ref Dropwizard
  ``Meter``'s one-minute rate; here an exact sliding-window rate, not an
  EWMA — simpler, and exact for the test clock).
- :class:`Timer` — durations with count/mean/max and streaming quantiles
  over a bounded reservoir.
- :class:`Gauge` — a callable read at scrape time (ref dropwizard
  ``Gauge<T>`` lambdas registered at constructor time).

All sensors are thread-safe; reads never block writers for long.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable


class Counter:
    __slots__ = ("_value", "_lock")

    def __init__(self) -> None:
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def count(self) -> int:
        return self._value

    def to_json(self) -> dict:
        return {"type": "counter", "count": self._value}


class StripedCounter(Counter):
    """Lock-free ``inc``: each thread owns a private cell (only the owner
    thread read-modify-writes it, so the CPython ``+=`` race vanishes
    without a lock); reads sum the stripes at scrape time. Renders as a
    plain counter family — striping changes the write path, never the
    scrape surface."""

    __slots__ = ("_stripes",)

    def __init__(self) -> None:
        super().__init__()
        self._stripes: dict[int, list[int]] = {}

    def inc(self, n: int = 1) -> None:
        ident = threading.get_ident()
        cell = self._stripes.get(ident)
        if cell is None:
            self._stripes[ident] = cell = [0]
        cell[0] += n

    @property
    def count(self) -> int:
        return self._value + sum(c[0] for c in list(self._stripes.values()))

    def to_json(self) -> dict:
        return {"type": "counter", "count": self.count}


class Meter:
    """Count + sliding-window rate (events/s over the last ``window_s``)."""

    __slots__ = ("_count", "_events", "_window_s", "_lock", "_now")

    def __init__(self, window_s: float = 60.0,
                 now: Callable[[], float] | None = None) -> None:
        self._count = 0
        self._events: list[tuple[float, int]] = []
        self._window_s = window_s
        self._lock = threading.Lock()
        self._now = now or time.monotonic

    def mark(self, n: int = 1) -> None:
        now = self._now()
        with self._lock:
            self._count += n
            self._events.append((now, n))
            cutoff = now - self._window_s
            while self._events and self._events[0][0] < cutoff:
                self._events.pop(0)

    @property
    def count(self) -> int:
        return self._count

    def rate(self) -> float:
        now = self._now()
        cutoff = now - self._window_s
        with self._lock:
            total = sum(n for t, n in self._events if t >= cutoff)
        return total / self._window_s

    def to_json(self) -> dict:
        return {"type": "meter", "count": self._count,
                "rate_per_s": round(self.rate(), 6)}


class StripedMeter(Meter):
    """Meter whose ``mark`` takes no lock: marks land on a per-thread
    deque (``deque.append`` is atomic; only the scrape side pops), and
    every read drains the stripes into the base meter under its lock.
    N request threads marking one request-rate meter stop serializing on
    the meter's ``Lock`` — contention moves to the scrape, which is rare.
    Renders identically to :class:`Meter` (same families)."""

    __slots__ = ("_stripes",)

    def __init__(self, window_s: float = 60.0,
                 now: Callable[[], float] | None = None) -> None:
        super().__init__(window_s, now)
        self._stripes: dict[int, deque] = {}

    def mark(self, n: int = 1) -> None:
        ident = threading.get_ident()
        d = self._stripes.get(ident)
        if d is None:
            self._stripes[ident] = d = deque()
        d.append((self._now(), n))

    def _drain_locked(self) -> None:
        for d in list(self._stripes.values()):
            while True:
                try:
                    t, n = d.popleft()
                except IndexError:
                    break
                self._count += n
                self._events.append((t, n))
        cutoff = self._now() - self._window_s
        if self._events and self._events[0][0] < cutoff:
            # Stripes drain slightly out of order; filter, don't pop-front.
            self._events = [(t, n) for t, n in self._events if t >= cutoff]

    @property
    def count(self) -> int:
        with self._lock:
            self._drain_locked()
            return self._count

    def rate(self) -> float:
        now = self._now()
        cutoff = now - self._window_s
        with self._lock:
            self._drain_locked()
            total = sum(n for t, n in self._events if t >= cutoff)
        return total / self._window_s

    def to_json(self) -> dict:
        rate = self.rate()                      # drains the stripes
        return {"type": "meter", "count": self._count,
                "rate_per_s": round(rate, 6)}


class Timer:
    """Duration sensor: count / mean / max / quantiles over a bounded
    reservoir (most recent ``reservoir`` observations)."""

    __slots__ = ("_count", "_sum", "_max", "_reservoir", "_cap", "_lock")

    def __init__(self, reservoir: int = 1024) -> None:
        self._count = 0
        self._sum = 0.0
        self._max = 0.0
        self._reservoir: list[float] = []
        self._cap = reservoir
        self._lock = threading.Lock()

    def update(self, seconds: float) -> None:
        with self._lock:
            self._count += 1
            self._sum += seconds
            self._max = max(self._max, seconds)
            if len(self._reservoir) >= self._cap:
                self._reservoir.pop(0)
            self._reservoir.append(seconds)

    def time(self):
        """Context manager: ``with timer.time(): ...``"""
        return _TimerContext(self)

    @property
    def count(self) -> int:
        return self._count

    @property
    def mean_s(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def quantile(self, q: float) -> float:
        with self._lock:
            if not self._reservoir:
                return 0.0
            data = sorted(self._reservoir)
        idx = min(int(q * len(data)), len(data) - 1)
        return data[idx]

    def to_json(self) -> dict:
        return {"type": "timer", "count": self._count,
                "mean_s": round(self.mean_s, 6),
                "max_s": round(self._max, 6),
                "p50_s": round(self.quantile(0.50), 6),
                "p95_s": round(self.quantile(0.95), 6),
                "p99_s": round(self.quantile(0.99), 6)}


class StripedTimer(Timer):
    """Timer whose ``update`` takes no lock (per-thread deques, drained
    into the base reservoir on any read — see :class:`StripedMeter`).
    Renders identically to :class:`Timer` (same summary family)."""

    __slots__ = ("_stripes",)

    def __init__(self, reservoir: int = 1024) -> None:
        super().__init__(reservoir)
        self._stripes: dict[int, deque] = {}

    def update(self, seconds: float) -> None:
        ident = threading.get_ident()
        d = self._stripes.get(ident)
        if d is None:
            self._stripes[ident] = d = deque()
        d.append(seconds)

    def _flush(self) -> None:
        with self._lock:
            for d in list(self._stripes.values()):
                while True:
                    try:
                        seconds = d.popleft()
                    except IndexError:
                        break
                    self._count += 1
                    self._sum += seconds
                    self._max = max(self._max, seconds)
                    if len(self._reservoir) >= self._cap:
                        self._reservoir.pop(0)
                    self._reservoir.append(seconds)

    @property
    def count(self) -> int:
        self._flush()
        return self._count

    @property
    def mean_s(self) -> float:
        self._flush()
        return self._sum / self._count if self._count else 0.0

    def quantile(self, q: float) -> float:
        self._flush()
        return super().quantile(q)

    def to_json(self) -> dict:
        self._flush()
        return super().to_json()


class _TimerContext:
    __slots__ = ("_timer", "_start")

    def __init__(self, timer: Timer) -> None:
        self._timer = timer

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._timer.update(time.perf_counter() - self._start)
        return False


class Gauge:
    """Callable read at scrape time (ref Dropwizard ``Gauge<T>``).
    Scrape errors surface as None rather than failing the whole report."""

    __slots__ = ("_fn",)

    def __init__(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    def value(self):
        try:
            return self._fn()
        except Exception:
            return None

    def to_json(self) -> dict:
        return {"type": "gauge", "value": self.value()}


def _family_names(base: str, s: object) -> tuple[str, ...]:
    """Rendered Prometheus family names for a sensor at a given base."""
    if isinstance(s, Counter):
        return (f"{base}_total",)
    if isinstance(s, Meter):
        return (f"{base}_total", f"{base}_rate")
    if isinstance(s, Timer):
        return (f"{base}_seconds",)
    return (base,)


def _flatten_names(items: list[tuple[str, object]]) -> list[str]:
    """Per-item unique ``cc_`` series base (positional — aligned with
    ``items``).

    Flattening maps every non-alphanumeric to ``_``, so distinct dotted
    names can collide (``A.b-c`` and ``A.b.c`` both flatten to
    ``cc_A_b_c``) — and a merged multi-registry scrape can even carry
    the SAME dotted name twice (two fleet members' monitors). Both used
    to emit duplicate ``# TYPE`` blocks, an exposition-format violation.
    Uniqueness is enforced positionally on the RENDERED family names
    (kind suffixes included: a Counter ``A.b`` and a Gauge ``A.b.total``
    both render family ``cc_A_b_total``), disambiguated deterministically
    (sorted input order) with a numeric suffix. Suffix-deduped families
    are format-legal but unattributable — fleet scrapes must namespace
    per-cluster registries instead (:class:`NamespacedRegistry`;
    tests/prom_lint.py's ``forbid_unlabeled_duplicates`` rejects the
    suffix form)."""
    assigned: set[str] = set()
    out: list[str] = []
    for name, s in items:
        base = "cc_" + "".join(ch if (ch.isalnum() or ch == "_") else "_"
                               for ch in name)
        candidate, i = base, 1
        while any(f in assigned for f in _family_names(candidate, s)):
            i += 1
            candidate = f"{base}_{i}"
        assigned.update(_family_names(candidate, s))
        out.append(candidate)
    return out


def _render_exposition(items: list[tuple[str, object]],
                       flat: list[str] | None = None) -> str:
    """Prometheus text exposition over sorted (dotted name, sensor) pairs —
    the ONE renderer behind both ``MetricRegistry.expose_text`` and the
    composite view (so merged registries cannot emit duplicate ``# TYPE``
    blocks either). Every series family carries a ``# HELP`` line naming
    the original dotted sensor. ``flat`` lets callers reuse a cached
    :func:`_flatten_names` result (the merge/sort/flatten structure is
    the expensive scrape half; values are always read live)."""
    if flat is None:
        flat = _flatten_names(items)
    lines: list[str] = []

    def family(series: str, dotted: str, kind: str) -> None:
        lines.append(f"# HELP {series} sensor {dotted}")
        lines.append(f"# TYPE {series} {kind}")

    for (name, s), base in zip(items, flat):
        if isinstance(s, Counter):
            family(f"{base}_total", name, "counter")
            lines.append(f"{base}_total {s.count}")
        elif isinstance(s, Meter):
            family(f"{base}_total", name, "counter")
            lines.append(f"{base}_total {s.count}")
            family(f"{base}_rate", name, "gauge")
            lines.append(f"{base}_rate {s.rate():.6f}")
        elif isinstance(s, Timer):
            family(f"{base}_seconds", name, "summary")
            for q in (0.5, 0.95, 0.99):
                lines.append(f"{base}_seconds{{quantile=\"{q}\"}} "
                             f"{s.quantile(q):.6f}")
            lines.append(f"{base}_seconds_count {s.count}")
            lines.append(f"{base}_seconds_sum {s._sum:.6f}")
        elif isinstance(s, Gauge):
            v = s.value()
            if v is None:
                continue
            try:
                rendered = f"{base} {float(v):.6f}"
            except (TypeError, ValueError):
                continue        # non-numeric gauges are dropped
            family(base, name, "gauge")
            lines.append(rendered)
    return "\n".join(lines) + "\n"


class MetricRegistry:
    """Named sensor registry (ref ``com.codahale.metrics.MetricRegistry``).

    Names follow the reference's dotted ``<group>.<sensor>`` convention,
    e.g. ``GoalOptimizer.proposal-computation-timer``. ``timer``/``meter``/
    ``counter`` are get-or-create (idempotent); ``gauge`` re-registration
    replaces the callable (matching ``register``'s last-wins usage for
    refreshed lambdas).
    """

    def __init__(self) -> None:
        self._sensors: dict[str, object] = {}
        self._lock = threading.Lock()
        #: bumps on every STRUCTURAL change (new sensor, replaced gauge).
        #: Values changing does not count — the exposition render cache
        #: keys on this to reuse the merge/flatten structure while still
        #: reading every value live at scrape time.
        self._mutations = 0
        self._render_cache: tuple | None = None

    @property
    def mutation_count(self) -> int:
        return self._mutations

    @staticmethod
    def name(group: str, sensor: str) -> str:
        return f"{group}.{sensor}"

    def _get_or_create(self, name: str, factory, kind) -> object:
        with self._lock:
            s = self._sensors.get(name)
            if s is None:
                s = factory()
                self._sensors[name] = s
                self._mutations += 1
            elif not isinstance(s, kind):
                raise TypeError(
                    f"sensor {name!r} already registered as "
                    f"{type(s).__name__}")
            return s

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter, Counter)

    def striped_counter(self, name: str) -> StripedCounter:
        return self._get_or_create(name, StripedCounter, StripedCounter)

    def meter(self, name: str, window_s: float = 60.0,
              now: Callable[[], float] | None = None) -> Meter:
        return self._get_or_create(
            name, lambda: Meter(window_s, now), Meter)

    def striped_meter(self, name: str, window_s: float = 60.0,
                      now: Callable[[], float] | None = None) -> StripedMeter:
        return self._get_or_create(
            name, lambda: StripedMeter(window_s, now), StripedMeter)

    def timer(self, name: str) -> Timer:
        return self._get_or_create(name, Timer, Timer)

    def striped_timer(self, name: str) -> StripedTimer:
        return self._get_or_create(name, StripedTimer, StripedTimer)

    def gauge(self, name: str, fn: Callable[[], float]) -> Gauge:
        with self._lock:
            g = Gauge(fn)
            self._sensors[name] = g
            self._mutations += 1
            return g

    def get(self, name: str):
        return self._sensors.get(name)

    def names(self) -> list[str]:
        return sorted(self._sensors)

    def snapshot(self) -> list[tuple[str, object]]:
        """Locked point-in-time (dotted name, sensor) list — the public
        merge surface the composite view renders from."""
        with self._lock:
            return sorted(self._sensors.items())

    # -------------------------------------------------------------- export
    def to_json(self) -> dict:
        """{name: sensor-json} snapshot for ``/state``."""
        with self._lock:
            items = list(self._sensors.items())
        return {name: s.to_json() for name, s in sorted(items)}

    def expose_text(self) -> str:
        """Prometheus-style text exposition for ``/metrics``.

        Sensor names are flattened to ``cc_<group>_<sensor>`` (collisions
        disambiguated — see :func:`_flatten_names`); timers emit
        ``_count``/``_sum`` and quantile series (a summary), meters
        ``_total`` and ``_rate``, counters ``_total``, gauges the bare
        name. Every family carries ``# HELP`` and exactly one ``# TYPE``.

        The merge/sort/flatten structure is cached and invalidated by the
        registry's mutation counter, so steady-state scrapes only format
        values — they stop re-sorting and re-deduplicating family names
        every time (the Prometheus-scrape hot path).
        """
        muts = self._mutations
        cache = self._render_cache
        if cache is not None and cache[0] == muts:
            items, flat = cache[1], cache[2]
        else:
            items = self.snapshot()
            flat = _flatten_names(items)
            self._render_cache = (muts, items, flat)
        return _render_exposition(items, flat)


class CompositeRegistry:
    """Read-only merged view over several registries, resolved at scrape
    time. The facade exposes one of these spanning its wired subsystems, so
    two independently constructed stacks in one process never share sensor
    state (each subsystem defaults to its own private registry) while
    ``/metrics`` and ``/state?substates=sensors`` still see everything.
    Subsystem sensor names are group-prefixed, so merges cannot collide."""

    def __init__(self, sources: Callable[[], list[MetricRegistry]]) -> None:
        self._raw_sources = sources
        self._render_cache: tuple | None = None

    def _sources(self) -> list[MetricRegistry]:
        # Dedupe by identity: subsystems wired with ONE shared registry
        # (the reference's single-registry pattern) must not emit every
        # series once per subsystem.
        out: list[MetricRegistry] = []
        for reg in self._raw_sources():
            if all(reg is not seen for seen in out):
                out.append(reg)
        return out

    def get(self, name: str):
        for reg in self._sources():
            s = reg.get(name)
            if s is not None:
                return s
        return None

    @property
    def mutation_count(self) -> int:
        """Structural-change key over every source (len guards source
        attach/detach; per-source counters only grow, so the sum plus the
        count detects any structural change)."""
        sources = self._sources()
        return len(sources) + sum(
            getattr(reg, "mutation_count", 0) for reg in sources)

    def names(self) -> list[str]:
        out: set[str] = set()
        for reg in self._sources():
            out.update(reg.names())
        return sorted(out)

    def to_json(self) -> dict:
        out: dict = {}
        for reg in self._sources():
            out.update(reg.to_json())
        return dict(sorted(out.items()))

    def expose_text(self) -> str:
        # Merge THEN render once: per-registry concatenation would emit a
        # second ``# TYPE`` block whenever two registries carry the same
        # sensor name (first writer wins, matching get()). Duck-typed
        # registries without the snapshot() merge surface (a nested
        # composite, a custom extra_registries entry) keep the old
        # concatenation behavior rather than breaking the scrape.
        #
        # The merged structure (sorted items + flattened family names) is
        # cached against the sources' mutation counters, so a /metrics
        # scrape of a quiet fleet re-renders values but never re-merges,
        # re-sorts, or re-deduplicates hundreds of families per request.
        sources = self._sources()
        snap_sources = [r for r in sources
                        if getattr(r, "snapshot", None) is not None]
        foreign = [r for r in sources
                   if getattr(r, "snapshot", None) is None]
        key = tuple(getattr(r, "mutation_count", -1) for r in snap_sources)
        cache = self._render_cache
        if (cache is not None and cache[0] == key and -1 not in key
                and len(cache[1]) == len(snap_sources)
                and all(a is b for a, b in zip(cache[1], snap_sources))):
            items, flat = cache[2], cache[3]
        else:
            merged: dict[str, object] = {}
            for reg in snap_sources:
                for name, s in reg.snapshot():
                    merged.setdefault(name, s)
            items = sorted(merged.items())
            flat = _flatten_names(items)
            self._render_cache = (key, list(snap_sources), items, flat)
        return _render_exposition(items, flat) + "".join(
            r.expose_text() for r in foreign)


class NamespacedRegistry:
    """Read-only prefix view over a registry: every dotted sensor name
    renders as ``<prefix>.<name>``.

    The fleet layer's scrape problem: registries from multiple
    ``LoadMonitor``/``ProposalCache`` instances (one per member cluster)
    carry IDENTICAL group-prefixed names, so a merged exposition used to
    fall back to ``_flatten_names``' numeric-suffix disambiguation
    (``cc_LoadMonitor_..._2``) — unlabeled duplicates nobody can
    attribute to a cluster. Wrapping each member's registries in a
    ``NamespacedRegistry(reg, cluster_id)`` renders
    ``cc_<cluster>_LoadMonitor_...`` instead; ``tests/prom_lint.py``'s
    ``forbid_unlabeled_duplicates`` rejects the un-namespaced form.

    ``get``/``names`` resolve PREFIXED names (the merge surface); the
    inner registry keeps answering its own un-prefixed names for the
    subsystem that owns it.
    """

    def __init__(self, inner, prefix: str) -> None:
        if not prefix:
            raise ValueError("NamespacedRegistry requires a prefix")
        self.inner = inner
        self.prefix = prefix

    @property
    def mutation_count(self) -> int:
        return getattr(self.inner, "mutation_count", 0)

    def _wrap(self, name: str) -> str:
        return f"{self.prefix}.{name}"

    def snapshot(self) -> list[tuple[str, object]]:
        return [(self._wrap(n), s) for n, s in self.inner.snapshot()]

    def get(self, name: str):
        pre = f"{self.prefix}."
        if not name.startswith(pre):
            return None
        return self.inner.get(name[len(pre):])

    def names(self) -> list[str]:
        return sorted(self._wrap(n) for n in self.inner.names())

    def to_json(self) -> dict:
        return {self._wrap(n): s.to_json()
                for n, s in self.inner.snapshot()}

    def expose_text(self) -> str:
        return _render_exposition(self.snapshot())


#: Sensor group names (ref CruiseControlMetrics sensor name constants).
GOAL_OPTIMIZER_SENSOR = "GoalOptimizer"
LOAD_MONITOR_SENSOR = "LoadMonitor"
EXECUTOR_SENSOR = "Executor"
ANOMALY_DETECTOR_SENSOR = "AnomalyDetector"
USER_TASKS_SENSOR = "UserTaskManager"
