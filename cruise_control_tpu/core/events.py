"""Control-plane flight recorder: the causal decision journal.

The span tracer (core/tracing.py) answers "where did the latency go" and
the sensor registry (core/sensors.py) answers "how often"; neither
answers the operator's first question after an incident: **what did the
control plane decide, and why**. This module adds that axis: a
thread-safe bounded ring of structured :class:`Event` records — one per
control-plane *decision* (a proposal served or refused, a heal
dispatched, a fence abort, a replica refusing a deposed leader's frame,
an SLO burn-rate breach) — with:

- **Causality chains.** Every event may name a ``cause`` seq, so the
  anomaly-detected → fix-dispatched → fix-outcome chain (and the
  plan-selected → served chain) reads as a linked list on ``/history``.
- **Trace linkage.** Events capture the recording thread's current
  SpanTracer span id, so a ``/history`` row jumps straight to the
  ``/trace`` span that produced it; the journal also exports Chrome
  instant ("i") events merged into the ``/trace`` payload.
- **Crash-safe JSONL segments.** ``persist()`` rewrites the active
  segment atomically (tmp + fsync + ``os.replace`` — the
  core/snapshot.py discipline) and rotates a full segment to
  ``<path>.prev`` with one more ``os.replace``; restore re-reads both
  with a *restricted decode* (strict per-line shape validation, refused
  lines metered) because the segment sits on the same trust boundary as
  the snapshot file.
- **Replication.** ``export_delta`` / ``apply_remote`` let the
  replication session ship the leader's journal to read replicas
  (fence-checked like every frame), so ``/history`` serves locally on a
  replica and post-failover forensics can splice both processes'
  journals by (node, seq).
- **Zero device syncs.** Appends read the host clock only; the warm
  propose path's overhead is gated <2% by bench scenario 12 (the same
  bar as the tracer).

``enabled = False`` turns the whole journal into a no-op — the bench's
A/B switch, mirroring :class:`~cruise_control_tpu.core.tracing.
SpanTracer`.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Callable, Iterable

from .sensors import MetricRegistry

LOG = logging.getLogger(__name__)

#: sensor group for the journal series (``EventJournal.*``).
EVENT_SENSOR = "EventJournal"

#: the closed category set — one striped counter per category is
#: pre-created at construction so the Prometheus family set is stable
#: (merged-scrape lint asserts HELP-completeness against it).
CATEGORIES = ("propose", "optimizer", "execute", "election", "replication",
              "admission", "detector", "snapshot", "slo", "fleet")

#: severity ladder, least to most severe (the /history ``severity``
#: filter is a minimum-severity cut).
SEVERITIES = ("info", "warn", "error")
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}


class Event:
    """One recorded decision (immutable once appended)."""

    __slots__ = ("seq", "ts_ms", "perf_s", "category", "action", "severity",
                 "epoch", "span_id", "cause", "node", "detail")

    def __init__(self, seq: int, ts_ms: int, perf_s: float, category: str,
                 action: str, severity: str, epoch: int | None,
                 span_id: int | None, cause: int | None, node: str | None,
                 detail: dict | None) -> None:
        self.seq = seq
        self.ts_ms = ts_ms
        self.perf_s = perf_s
        self.category = category
        self.action = action
        self.severity = severity
        self.epoch = epoch
        self.span_id = span_id
        self.cause = cause
        self.node = node
        self.detail = detail

    def to_json(self) -> dict:
        return {"seq": self.seq, "tsMs": self.ts_ms,
                "category": self.category, "action": self.action,
                "severity": self.severity, "epoch": self.epoch,
                "spanId": self.span_id, "cause": self.cause,
                "node": self.node, "detail": self.detail}


def _event_from_json(obj) -> Event | None:
    """Restricted decode for the trust boundary (segment restore and
    replicated journal frames): strict shape validation per record —
    wrong types, unknown categories/severities, or a non-dict detail all
    refuse the record rather than poisoning the ring. Returns None on
    refusal (the caller meters it)."""
    if not isinstance(obj, dict):
        return None
    try:
        seq = int(obj["seq"])
        ts_ms = int(obj["tsMs"])
        category = obj["category"]
        action = obj["action"]
        severity = obj.get("severity", "info")
    except (KeyError, TypeError, ValueError):
        return None
    if seq < 1 or category not in CATEGORIES or severity not in SEVERITIES:
        return None
    if not isinstance(action, str) or not action or len(action) > 128:
        return None
    epoch = obj.get("epoch")
    cause = obj.get("cause")
    span_id = obj.get("spanId")
    node = obj.get("node")
    detail = obj.get("detail")
    if epoch is not None and not isinstance(epoch, int):
        return None
    if cause is not None and not isinstance(cause, int):
        return None
    if span_id is not None and not isinstance(span_id, int):
        return None
    if node is not None and not isinstance(node, str):
        return None
    if detail is not None and not isinstance(detail, dict):
        return None
    return Event(seq, ts_ms, 0.0, category, action, severity, epoch,
                 span_id, cause, node, detail)


class EventJournal:
    """Thread-safe bounded decision ring + JSONL segment persistence.

    ``capacity`` bounds memory (oldest events drop, counted);
    ``enabled`` turns :meth:`record` into a no-op (the overhead A/B
    switch); ``categories`` restricts recording to a subset (the
    per-category enable — None records everything)."""

    def __init__(self, capacity: int = 4096, *,
                 registry: MetricRegistry | None = None,
                 tracer=None, node: str | None = None,
                 segment_path: str | None = None,
                 rotate_bytes: int = 262_144,
                 persist_interval_ms: int = 30_000,
                 categories: Iterable[str] | None = None,
                 now_ms: Callable[[], int] | None = None) -> None:
        self.capacity = int(capacity)
        self.enabled = True
        self.node = node
        self.segment_path = segment_path
        self.rotate_bytes = int(rotate_bytes)
        self.persist_interval_ms = int(persist_interval_ms)
        self.categories = (frozenset(categories)
                           if categories is not None else None)
        self.tracer = tracer
        self._now_ms = now_ms or (lambda: int(time.time() * 1000))
        self._perf = time.perf_counter
        self._ring: "deque[Event]" = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._dropped = 0
        #: per-node max seq applied via :meth:`apply_remote` — the
        #: replication dedup floor (cursor rejoins re-deliver frames).
        self._remote_floors: dict[str, int] = {}
        self._last_persist_ms: int | None = None
        #: seq floor of the active segment: events below it graduated to
        #: ``<path>.prev`` at the last rotation.
        self._persist_floor = 1
        self._last_persisted_seq = 0
        self.registry = registry or MetricRegistry()
        name = MetricRegistry.name
        g = EVENT_SENSOR
        # Pre-created per-category/per-severity striped counters: the
        # record hot path never creates sensors (registry mutations
        # invalidate the scrape render cache) and the family set is
        # scrape-stable from construction.
        self._cat_counters = {
            c: self.registry.striped_counter(name(g, f"events-{c}"))
            for c in CATEGORIES}
        self._sev_counters = {
            s: self.registry.striped_counter(name(g, f"severity-{s}"))
            for s in SEVERITIES}
        self._applied_remote = self.registry.counter(
            name(g, "applied-remote"))
        self._refused_records = self.registry.counter(
            name(g, "refused-records"))
        self._persist_writes = self.registry.counter(
            name(g, "persist-writes"))
        self._persist_failures = self.registry.meter(
            name(g, "persist-failure-rate"))
        self.registry.gauge(name(g, "last-seq"), lambda: self._seq)
        self.registry.gauge(name(g, "dropped"), lambda: self._dropped)

    def configure(self, *, enabled: bool | None = None,
                  capacity: int | None = None,
                  segment_path: str | None = None,
                  rotate_bytes: int | None = None,
                  persist_interval_ms: int | None = None,
                  categories: Iterable[str] | None = None,
                  node: str | None = None) -> None:
        """Apply the ``events.*`` config keys to a journal the facade
        already constructed (serve.py wiring). None leaves a field as-is;
        a capacity change re-bounds the ring in place."""
        if enabled is not None:
            self.enabled = bool(enabled)
        if capacity is not None and int(capacity) != self.capacity:
            with self._lock:
                self.capacity = int(capacity)
                self._ring = deque(self._ring, maxlen=self.capacity)
        if segment_path is not None:
            self.segment_path = segment_path or None
        if rotate_bytes is not None:
            self.rotate_bytes = int(rotate_bytes)
        if persist_interval_ms is not None:
            self.persist_interval_ms = int(persist_interval_ms)
        if categories is not None:
            unknown = sorted(set(categories) - set(CATEGORIES))
            if unknown:
                raise ValueError(f"unknown event categories {unknown} "
                                 f"(known: {CATEGORIES})")
            self.categories = frozenset(categories) or None
        if node is not None:
            self.node = node

    # ----------------------------------------------------------- recording
    def record(self, category: str, action: str, *,
               severity: str = "info", cause: int | None = None,
               epoch: int | None = None,
               detail: dict | None = None) -> int | None:
        """Append one decision. Returns the assigned seq (the handle a
        later event passes as ``cause``), or None when disabled or the
        category is filtered out. Host-clock only — zero device syncs."""
        if not self.enabled:
            return None
        if category not in CATEGORIES:
            raise ValueError(f"unknown event category {category!r} "
                             f"(known: {CATEGORIES})")
        if self.categories is not None and category not in self.categories:
            return None
        if severity not in SEVERITIES:
            severity = "info"
        span_id = (self.tracer.current_span_id()
                   if self.tracer is not None else None)
        ts_ms = self._now_ms()
        perf_s = self._perf()
        with self._lock:
            self._seq += 1
            seq = self._seq
            if len(self._ring) >= self.capacity:
                self._dropped += 1
            self._ring.append(Event(seq, ts_ms, perf_s, category, action,
                                    severity, epoch, span_id, cause,
                                    self.node, detail))
        # Striped counters: lock-free inc, outside the ring lock.
        self._cat_counters[category].inc()
        self._sev_counters[severity].inc()
        return seq

    # -------------------------------------------------------------- reads
    @property
    def last_seq(self) -> int:
        return self._seq

    @property
    def dropped(self) -> int:
        return self._dropped

    def events(self) -> list[Event]:
        with self._lock:
            return list(self._ring)

    def query(self, *, categories: Iterable[str] | None = None,
              min_severity: str | None = None, since_seq: int = 0,
              limit: int = 256) -> list[Event]:
        """Filtered read, newest-last. ``categories`` is an exact-match
        set; ``min_severity`` is a floor on the severity ladder;
        ``since_seq`` is exclusive; ``limit`` keeps the newest rows."""
        cats = frozenset(categories) if categories else None
        floor = _SEV_RANK.get(min_severity, 0) if min_severity else 0
        out = [e for e in self.events()
               if e.seq > since_seq
               and (cats is None or e.category in cats)
               and _SEV_RANK[e.severity] >= floor]
        return out[-max(int(limit), 0):]

    def history_json(self, *, categories: Iterable[str] | None = None,
                     min_severity: str | None = None, since_seq: int = 0,
                     limit: int = 256) -> dict:
        """The ``GET /history`` payload."""
        rows = self.query(categories=categories, min_severity=min_severity,
                          since_seq=since_seq, limit=limit)
        return {"node": self.node, "lastSeq": self._seq,
                "numEvents": len(self._ring), "dropped": self._dropped,
                "capacity": self.capacity,
                "events": [e.to_json() for e in rows]}

    def to_json(self, limit: int = 64) -> dict:
        """Bounded snapshot for ``/state`` embedding."""
        return self.history_json(limit=limit)

    def chrome_instant_events(self, epoch_s: float) -> list[dict]:
        """Chrome-trace instant ("i") events merged into the ``/trace``
        payload — ``epoch_s`` is the tracer's perf_counter epoch so the
        journal rides the same timeline as the spans. Remotely-applied
        events carry their *arrival* perf stamp (the leader's
        perf_counter is meaningless here)."""
        pid = os.getpid()
        return [{"name": f"{e.category}.{e.action}", "ph": "i",
                 "cat": "journal", "s": "p",
                 "ts": round((e.perf_s - epoch_s) * 1e6, 3),
                 "pid": pid, "tid": 0,
                 "args": {"seq": e.seq, "severity": e.severity,
                          "cause": e.cause, "epoch": e.epoch,
                          "spanId": e.span_id}}
                for e in self.events() if e.perf_s]

    # -------------------------------------------------------- replication
    def export_delta(self, since_seq: int, limit: int = 512) -> list[dict]:
        """Events with ``seq > since_seq`` as JSON dicts — the
        replication frame body. Bounded: a replica that missed more than
        ``limit`` events catches the rest on later frames (seqs are
        contiguous per node, so nothing is silently skipped as long as
        the publisher advances its cursor by what it shipped)."""
        out = [e.to_json() for e in self.events() if e.seq > since_seq]
        return out[:max(int(limit), 0)]

    def apply_remote(self, entries: list, *,
                     source_node: str | None = None) -> int:
        """Apply a leader's journal delta (replication follower side).
        Strictly validated per record; duplicates (cursor rejoins
        re-deliver frames) dedup on a per-node seq floor; the local seq
        counter jumps past every applied seq so local events stay
        monotonic above them. Returns the number applied."""
        if not isinstance(entries, (list, tuple)):
            return 0
        applied = 0
        now_perf = self._perf()
        with self._lock:
            for obj in entries:
                ev = _event_from_json(obj)
                if ev is None:
                    self._refused_records.inc()
                    continue
                node = ev.node or source_node or "remote"
                if ev.seq <= self._remote_floors.get(node, 0):
                    continue            # re-delivered duplicate
                self._remote_floors[node] = ev.seq
                ev.node = node          # remote rows always name a node
                ev.perf_s = now_perf
                if len(self._ring) >= self.capacity:
                    self._dropped += 1
                self._ring.append(ev)
                self._seq = max(self._seq, ev.seq)
                applied += 1
        if applied:
            self._applied_remote.inc(applied)
        return applied

    # ----------------------------------------------------------- snapshot
    def export_state(self) -> dict:
        """Snapshot-payload section (host-side JSON data only)."""
        return {"seq": self._seq,
                "events": [e.to_json() for e in self.events()]}

    def restore_state(self, state) -> int:
        """Merge a snapshot's journal section (restart warm-restore and
        the replica resync path). Reuses the remote-apply validation and
        dedup; local events already in the ring are preserved."""
        if not isinstance(state, dict):
            return 0
        n = self.apply_remote(state.get("events") or [])
        with self._lock:
            self._seq = max(self._seq, int(state.get("seq", 0) or 0))
        return n

    # -------------------------------------------------------- persistence
    def persist(self, now_ms: int | None = None) -> int | None:
        """Rewrite the active JSONL segment atomically (tmp + fsync +
        ``os.replace``); when the active segment would exceed
        ``rotate_bytes`` the previously-persisted content graduates to
        ``<path>.prev`` first (one more atomic ``os.replace``), so a
        crash at any point leaves both files complete. Best-effort on
        IO (metered + logged). Returns bytes written, or None."""
        if not self.segment_path:
            return None
        from .snapshot import atomic_write_bytes
        with self._lock:
            events = list(self._ring)
            floor = self._persist_floor
            last = self._last_persisted_seq
        # Only THIS process's events persist to its segment (remote rows
        # re-arrive over the stream or the snapshot); events recorded
        # before the node id was configured count as local.
        active = [e for e in events
                  if e.seq >= floor and e.node in (None, self.node)]
        data = self._encode(active)
        if len(data) > self.rotate_bytes and last >= floor:
            # Rotate: the old active file (events floor..last) becomes
            # .prev; the fresh active carries only the newer events.
            try:
                os.replace(self.segment_path, self.segment_path + ".prev")
            except FileNotFoundError:
                pass
            except OSError as exc:
                self._persist_failures.mark()
                LOG.warning("journal segment rotation failed (%s); "
                            "keeping one segment", exc)
            floor = last + 1
            active = [e for e in active if e.seq >= floor]
            data = self._encode(active)
        try:
            atomic_write_bytes(self.segment_path, data)
        except Exception as exc:   # noqa: BLE001 — serving must survive IO
            self._persist_failures.mark()
            LOG.warning("journal persist to %s failed (%s: %s)",
                        self.segment_path, type(exc).__name__, exc)
            return None
        with self._lock:
            self._persist_floor = floor
            self._last_persisted_seq = max(
                self._last_persisted_seq,
                max((e.seq for e in active), default=0))
            self._last_persist_ms = (now_ms if now_ms is not None
                                     else self._now_ms())
        self._persist_writes.inc()
        return len(data)

    def maybe_persist(self, now_ms: int) -> bool:
        """Cadenced persist (the ha_tick hook): write when
        ``persist_interval_ms`` elapsed since the last one."""
        if not self.segment_path:
            return False
        with self._lock:
            if (self._last_persist_ms is not None
                    and now_ms - self._last_persist_ms
                    < self.persist_interval_ms):
                return False
            if self._seq <= self._last_persisted_seq:
                self._last_persist_ms = now_ms
                return False
        return self.persist(now_ms) is not None

    @staticmethod
    def _encode(events: list[Event]) -> bytes:
        lines = [json.dumps(e.to_json(), sort_keys=True, default=str)
                 for e in events]
        return ("\n".join(lines) + "\n").encode("utf-8") if lines else b""

    def restore_from_disk(self) -> int:
        """Reload persisted segments (``.prev`` first, then the active
        one) through the restricted per-line decode; malformed lines are
        metered and skipped, never fatal. The local seq counter resumes
        past the highest restored seq. Returns events restored."""
        if not self.segment_path:
            return 0
        restored = 0
        max_seq = 0
        for path in (self.segment_path + ".prev", self.segment_path):
            try:
                with open(path, "rb") as f:
                    raw = f.read()
            except OSError:
                continue
            for line in raw.splitlines():
                if not line.strip():
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    self._refused_records.inc()
                    continue
                ev = _event_from_json(obj)
                if ev is None:
                    self._refused_records.inc()
                    continue
                with self._lock:
                    if len(self._ring) >= self.capacity:
                        self._dropped += 1
                    self._ring.append(ev)
                max_seq = max(max_seq, ev.seq)
                restored += 1
        if restored:
            with self._lock:
                self._seq = max(self._seq, max_seq)
                self._persist_floor = max_seq + 1
                self._last_persisted_seq = max(self._last_persisted_seq,
                                               max_seq)
            LOG.info("restored %d journal event(s) from %s (resuming at "
                     "seq %d)", restored, self.segment_path, self._seq + 1)
        return restored
