"""Kafka-style typed configuration framework.

Mirrors the reference's config core (``cruise-control-core/.../common/config/
ConfigDef.java`` and ``AbstractConfig.java``): every config key is *defined*
with a type, default, optional validator, importance and doc string; a config
instance parses a raw ``dict``/properties file against those definitions,
rejects unknown values of the wrong shape, applies defaults, and supports
reflective plugin loading (``getConfiguredInstance`` — here
:meth:`AbstractConfig.get_configured_instance` using ``importlib``).
"""

from __future__ import annotations

import enum
import importlib
from dataclasses import dataclass
from typing import Any, Callable, Mapping


class ConfigType(enum.Enum):
    BOOLEAN = "boolean"
    STRING = "string"
    INT = "int"
    LONG = "long"
    DOUBLE = "double"
    LIST = "list"
    CLASS = "class"
    PASSWORD = "password"


class Importance(enum.Enum):
    HIGH = "high"
    MEDIUM = "medium"
    LOW = "low"


NO_DEFAULT = object()


class ConfigException(ValueError):
    """Raised when a config value fails to parse or validate."""


class Password:
    """Opaque wrapper hiding secrets from str()/repr() (ref: Password.java)."""

    def __init__(self, value: str):
        self.value = value

    def __repr__(self) -> str:  # pragma: no cover - trivial
        return "[hidden]"

    __str__ = __repr__

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Password) and other.value == self.value

    def __hash__(self) -> int:
        return hash(self.value)


# ---------------------------------------------------------------------------
# Validators (ref: ConfigDef.Range / ConfigDef.ValidString / ValidList)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Range:
    min: float | None = None
    max: float | None = None

    @staticmethod
    def at_least(minimum: float) -> "Range":
        return Range(min=minimum)

    @staticmethod
    def between(minimum: float, maximum: float) -> "Range":
        return Range(min=minimum, max=maximum)

    def __call__(self, name: str, value: Any) -> None:
        if value is None:
            return
        if self.min is not None and value < self.min:
            raise ConfigException(f"{name}: value {value} must be at least {self.min}")
        if self.max is not None and value > self.max:
            raise ConfigException(f"{name}: value {value} must be no more than {self.max}")


@dataclass(frozen=True)
class ValidString:
    valid: tuple[str, ...]

    @staticmethod
    def in_(*valid: str) -> "ValidString":
        return ValidString(tuple(valid))

    def __call__(self, name: str, value: Any) -> None:
        if value is not None and value not in self.valid:
            raise ConfigException(f"{name}: {value!r} not one of {list(self.valid)}")


Validator = Callable[[str, Any], None]


@dataclass
class ConfigKey:
    name: str
    type: ConfigType
    default: Any = NO_DEFAULT
    validator: Validator | None = None
    importance: Importance = Importance.MEDIUM
    doc: str = ""

    @property
    def has_default(self) -> bool:
        return self.default is not NO_DEFAULT


class ConfigDef:
    """Registry of config key definitions (ref: ConfigDef.java)."""

    def __init__(self) -> None:
        self._keys: dict[str, ConfigKey] = {}

    def define(self, name: str, type: ConfigType, default: Any = NO_DEFAULT,
               validator: Validator | None = None,
               importance: Importance = Importance.MEDIUM, doc: str = "") -> "ConfigDef":
        if name in self._keys:
            raise ConfigException(f"Config {name!r} is defined twice")
        if default is not NO_DEFAULT and default is not None:
            default = _parse_type(name, default, type)
            if validator is not None:
                validator(name, default)
        self._keys[name] = ConfigKey(name, type, default, validator, importance, doc)
        return self

    def keys(self) -> Mapping[str, ConfigKey]:
        return dict(self._keys)

    def names(self) -> set[str]:
        return set(self._keys)

    def parse(self, props: Mapping[str, Any]) -> dict[str, Any]:
        values: dict[str, Any] = {}
        for name, key in self._keys.items():
            if name in props:
                value = _parse_type(name, props[name], key.type)
            elif key.has_default:
                value = key.default
            else:
                raise ConfigException(f"Missing required configuration {name!r} with no default")
            if key.validator is not None:
                key.validator(name, value)
            values[name] = value
        return values

    def merge(self, other: "ConfigDef") -> "ConfigDef":
        for key in other._keys.values():
            if key.name not in self._keys:
                self._keys[key.name] = key
        return self


def _parse_type(name: str, value: Any, ctype: ConfigType) -> Any:
    """Coerce a raw value (possibly a properties-file string) to its type.

    Mirrors ConfigDef.parseType (ConfigDef.java): trims strings, accepts
    native python values, and parses "true"/"false", numerics and
    comma-separated lists.
    """
    try:
        if value is None:
            return None
        if ctype is ConfigType.BOOLEAN:
            if isinstance(value, bool):
                return value
            if isinstance(value, str):
                lowered = value.strip().lower()
                if lowered == "true":
                    return True
                if lowered == "false":
                    return False
            raise ConfigException(f"{name}: expected boolean, got {value!r}")
        if ctype is ConfigType.STRING or ctype is ConfigType.CLASS:
            if isinstance(value, str):
                return value.strip()
            if ctype is ConfigType.CLASS and isinstance(value, type):
                return value
            raise ConfigException(f"{name}: expected string, got {value!r}")
        if ctype is ConfigType.INT or ctype is ConfigType.LONG:
            if isinstance(value, bool):
                raise ConfigException(f"{name}: expected int, got bool")
            if isinstance(value, int):
                return value
            if isinstance(value, str):
                return int(value.strip())
            raise ConfigException(f"{name}: expected int, got {value!r}")
        if ctype is ConfigType.DOUBLE:
            if isinstance(value, bool):
                raise ConfigException(f"{name}: expected double, got bool")
            if isinstance(value, (int, float)):
                return float(value)
            if isinstance(value, str):
                return float(value.strip())
            raise ConfigException(f"{name}: expected double, got {value!r}")
        if ctype is ConfigType.LIST:
            if isinstance(value, (list, tuple)):
                return list(value)
            if isinstance(value, str):
                stripped = value.strip()
                return [] if not stripped else [item.strip() for item in stripped.split(",")]
            raise ConfigException(f"{name}: expected list, got {value!r}")
        if ctype is ConfigType.PASSWORD:
            if isinstance(value, Password):
                return value
            if isinstance(value, str):
                return Password(value.strip())
            raise ConfigException(f"{name}: expected password/string, got {value!r}")
    except ConfigException:
        raise
    except (TypeError, ValueError) as exc:
        raise ConfigException(f"{name}: cannot parse {value!r} as {ctype.value}: {exc}") from exc
    raise ConfigException(f"{name}: unknown config type {ctype}")


class AbstractConfig:
    """A parsed config instance with typed getters (ref: AbstractConfig.java)."""

    def __init__(self, definition: ConfigDef, props: Mapping[str, Any],
                 allow_unknown: bool = True) -> None:
        self._definition = definition
        self._originals = dict(props)
        if not allow_unknown:
            unknown = set(props) - definition.names()
            if unknown:
                raise ConfigException(f"Unknown configuration(s): {sorted(unknown)}")
        self._values = definition.parse(props)
        self._used: set[str] = set()

    # -- typed getters ------------------------------------------------------
    def get(self, name: str) -> Any:
        if name not in self._values:
            raise ConfigException(f"Unknown configuration {name!r}")
        self._used.add(name)
        return self._values[name]

    def get_int(self, name: str) -> int:
        return self.get(name)

    get_long = get_int

    def get_double(self, name: str) -> float:
        return self.get(name)

    def get_boolean(self, name: str) -> bool:
        return self.get(name)

    def get_string(self, name: str) -> str:
        return self.get(name)

    def get_list(self, name: str) -> list[str]:
        return self.get(name)

    def get_password(self, name: str) -> Password:
        return self.get(name)

    def originals(self) -> dict[str, Any]:
        return dict(self._originals)

    def unused(self) -> set[str]:
        return set(self._values) - self._used

    def merged_values(self) -> dict[str, Any]:
        return dict(self._values)

    # -- plugin loading -----------------------------------------------------
    def get_configured_instance(self, name: str, expected_type: type | None = None,
                                **extra: Any) -> Any:
        """Instantiate the class named by config ``name`` and configure it.

        Mirrors AbstractConfig.getConfiguredInstance: the class is imported by
        dotted path, instantiated with no args, and — if it has a
        ``configure(config_dict)`` method (our ``CruiseControlConfigurable``
        contract) — passed the full merged config plus ``extra`` overrides.
        """
        value = self.get(name)
        return self._build_instance(name, value, expected_type, extra)

    def get_configured_instances(self, name: str, expected_type: type | None = None,
                                 **extra: Any) -> list[Any]:
        values = self.get(name)
        return [self._build_instance(name, v, expected_type, extra) for v in values]

    def _build_instance(self, name: str, value: Any, expected_type: type | None,
                        extra: Mapping[str, Any]) -> Any:
        cls = value if isinstance(value, type) else load_class(value)
        if expected_type is not None and not issubclass(cls, expected_type):
            raise ConfigException(
                f"{name}: {cls.__name__} is not a subclass of {expected_type.__name__}")
        instance = cls()
        configure = getattr(instance, "configure", None)
        if callable(configure):
            merged = self.merged_values()
            # Unknown keys (not in the ConfigDef) pass through raw so plugins
            # can read their own namespaced settings; known keys keep their
            # parsed/typed values.
            for key, value_ in self._originals.items():
                if key not in merged:
                    merged[key] = value_
            merged.update(extra)
            configure(merged)
        return instance


def load_class(dotted_path: str) -> type:
    """Import ``pkg.module.ClassName`` and return the class object."""
    module_name, _, class_name = dotted_path.rpartition(".")
    if not module_name:
        raise ConfigException(f"Not a dotted class path: {dotted_path!r}")
    try:
        module = importlib.import_module(module_name)
    except ImportError as exc:
        raise ConfigException(f"Cannot import module {module_name!r}: {exc}") from exc
    try:
        return getattr(module, class_name)
    except AttributeError as exc:
        raise ConfigException(f"Module {module_name!r} has no class {class_name!r}") from exc


def load_properties_file(path: str) -> dict[str, str]:
    """Parse a java-style ``.properties`` file into a dict.

    Handles ``#`` and ``!`` comments, ``=`` / ``:`` separators, preserves key
    case, and honors trailing-backslash line continuations.
    """
    props: dict[str, str] = {}
    with open(path) as handle:
        pending = ""
        for raw in handle:
            line = pending + raw.strip()
            pending = ""
            if not line or line[0] in "#!":
                continue
            if line.endswith("\\") and not line.endswith("\\\\"):
                pending = line[:-1]
                continue
            eq = min((i for i in (line.find("="), line.find(":")) if i >= 0),
                     default=-1)
            if eq < 0:
                props[line.strip()] = ""
            else:
                props[line[:eq].strip()] = line[eq + 1:].strip()
        if pending:
            props[pending.strip()] = ""
    return props
