"""Anomaly detector manager: the self-healing control loop (ref
``detector/AnomalyDetectorManager.java:52``).

Owns a priority queue of anomalies (``:74`` — priority by anomaly type,
then detection time), schedules each detector at its own interval
(``scheduleDetectorAtFixedRate`` ``:222``), and the handler step (ref
``AnomalyHandlerTask`` ``:343``) consults the notifier per anomaly:
FIX -> run the anomaly's fix through the facade (skipped while an
execution is ongoing), CHECK -> requeue for later, IGNORE -> drop.

Clock-driven: :meth:`run_once` performs one scheduling + handling round;
:meth:`start_detection` runs it on a daemon thread for live deployments.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import threading
import time as _time
from dataclasses import dataclass, field

from .anomalies import KafkaAnomaly, KafkaAnomalyType
from .notifier import (AnomalyNotificationResult, AnomalyNotifier,
                       SelfHealingNotifier)
from .provisioner import BasicProvisioner, Provisioner

LOG = logging.getLogger(__name__)


@dataclass
class DetectorSchedule:
    detector: object            # has .detect(now_ms)
    interval_ms: int
    next_run_ms: int = 0


class AnomalyDetectorManager:
    def __init__(self, facade, notifier: AnomalyNotifier | None = None,
                 provisioner: Provisioner | None = None,
                 now_ms=None, registry=None,
                 fixable_broker_count_threshold: int = 10,
                 fixable_broker_pct_threshold: float = 0.4,
                 num_cached_recent_anomalies: int = 10,
                 provisioner_enabled: bool = True, tracer=None) -> None:
        from ..core.sensors import (ANOMALY_DETECTOR_SENSOR, MetricRegistry)
        from ..core.tracing import default_tracer
        #: span tracer: detection rounds emit detector.detect spans, fixes
        #: detector.heal spans (nesting the facade/optimizer/executor work
        #: the fix runs)
        self.tracer = tracer or default_tracer()
        self.facade = facade
        #: self-healing refuses to act past these simultaneous-failure
        #: bounds (ref fixable.failed.broker.count/percentage.threshold —
        #: mass failures need a human, not an automatic drain)
        self.fixable_broker_count_threshold = fixable_broker_count_threshold
        self.fixable_broker_pct_threshold = fixable_broker_pct_threshold
        #: recent anomalies kept per type for /state (ref
        #: num.cached.recent.anomaly.states)
        self.num_cached_recent_anomalies = num_cached_recent_anomalies
        self.notifier = notifier or SelfHealingNotifier()
        #: ref provisioner.enable: False = no provisioning actions —
        #: /rightsize reports no provisioner and under/over-provision
        #: verdicts stay informational.
        self.provisioner = (None if not provisioner_enabled
                            else provisioner
                            or BasicProvisioner(facade.admin))
        self._now_ms = now_ms or (lambda: int(_time.time() * 1000))
        self._schedules: list[DetectorSchedule] = []
        self._queue: list[tuple[int, int, int, KafkaAnomaly]] = []
        self._counter = itertools.count()
        self._lock = threading.RLock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        # State for /state (ref AnomalyDetectorState.java).
        self.recent_anomalies: dict[KafkaAnomalyType, list[dict]] = {
            t: [] for t in KafkaAnomalyType}
        self.num_self_healing_started = 0
        self.num_self_healing_failed = 0
        self.ongoing_self_healing: str | None = None
        # Anomaly sensors (ref AnomalyDetectorManager.java:183-216
        # balancedness-score gauge + per-type anomaly-rate meters,
        # AnomalyDetectorState.java:116-118 self-healing counts and
        # mean-time-to-start-fix).
        self.registry = registry or MetricRegistry()
        _n = MetricRegistry.name
        self.registry.gauge(
            _n(ANOMALY_DETECTOR_SENSOR, "balancedness-score"),
            self._balancedness)
        self.registry.gauge(
            _n(ANOMALY_DETECTOR_SENSOR, "number-of-self-healing-started"),
            lambda: self.num_self_healing_started)
        self.registry.gauge(
            _n(ANOMALY_DETECTOR_SENSOR, "number-of-self-healing-failed"),
            lambda: self.num_self_healing_failed)
        self.registry.gauge(
            _n(ANOMALY_DETECTOR_SENSOR, "num-queued-anomalies"),
            lambda: len(self._queue))
        self._anomaly_meters = {
            t: self.registry.meter(_n(ANOMALY_DETECTOR_SENSOR,
                                      f"{t.name.lower()}-rate"))
            for t in KafkaAnomalyType}
        self._time_to_start_fix = self.registry.timer(
            _n(ANOMALY_DETECTOR_SENSOR, "time-to-start-fix"))
        #: every exception swallowed by the scheduling loop is logged AND
        #: marked here — a permanently-broken detector must be visible on
        #: /metrics, not silently absent from the anomaly stream
        self._detector_failures = self.registry.meter(
            _n(ANOMALY_DETECTOR_SENSOR, "detector-failure-rate"))
        # Per-type self-healing switches + provision verdict (remaining
        # rows of the documented AnomalyDetector sensor table:
        # <type>-self-healing-enabled, under/over-provisioned,
        # right-sized).
        for t in KafkaAnomalyType:
            self.registry.gauge(
                _n(ANOMALY_DETECTOR_SENSOR,
                   f"{t.name.lower()}-self-healing-enabled"),
                (lambda t=t: int(
                    self.notifier.self_healing_enabled().get(t, False))))
        for status in ("UNDER_PROVISIONED", "OVER_PROVISIONED",
                       "RIGHT_SIZED"):
            name = status.lower().replace("_provisioned", "-provisioned"
                                          ).replace("_sized", "-sized")
            self.registry.gauge(
                _n(ANOMALY_DETECTOR_SENSOR, name),
                (lambda s=status:
                 int(self._provision_status() == s)))

    def _provision_status(self) -> str | None:
        """Status of the latest cached optimization's provision verdict
        (ref the provision-state gauges fed by GoalViolationDetector)."""
        cache = getattr(self.facade, "proposal_cache", None)
        cached = cache.peek() if cache is not None else None
        resp = getattr(cached, "provision_response", None)
        return resp.status.value if resp is not None else None

    def _fixable(self, anomaly) -> bool:
        """Broker-failure anomalies stop being auto-fixable past the
        simultaneous-failure thresholds; all other anomaly types are
        unaffected (ref AnomalyDetectorUtils / SelfHealingNotifier
        hasFixableBrokerFailures)."""
        failed = getattr(anomaly, "failed_brokers", None)
        if not failed:
            return True
        if len(failed) > self.fixable_broker_count_threshold:
            return False
        total = max(len(self.facade.admin.describe_cluster()), 1)
        return len(failed) / total <= self.fixable_broker_pct_threshold

    def _balancedness(self):
        for sched in self._schedules:
            if hasattr(sched.detector, "last_balancedness"):
                return sched.detector.last_balancedness
        return None

    # ---------------------------------------------------------- wiring
    def register(self, detector, interval_ms: int,
                 initial_delay_ms: int = 0) -> None:
        """ref scheduleDetectorAtFixedRate :222."""
        self._schedules.append(DetectorSchedule(
            detector, interval_ms, next_run_ms=initial_delay_ms))

    def set_self_healing_enabled(self, anomaly_type_name: str,
                                 value: bool) -> None:
        atype = KafkaAnomalyType[anomaly_type_name.upper()]
        if isinstance(self.notifier, SelfHealingNotifier):
            self.notifier.set_self_healing_for(atype, value)

    # ------------------------------------------------------------- loop
    def run_once(self, now_ms: int | None = None) -> dict:
        """One detection + handling round; returns a summary for tests."""
        now = self._now_ms() if now_ms is None else now_ms
        detected = self._run_due_detectors(now)
        handled = self._handle_queue(now)
        return {"detected": detected, **handled}

    def _run_due_detectors(self, now: int) -> int:
        detected = 0
        for sched in self._schedules:
            if now < sched.next_run_ms:
                continue
            sched.next_run_ms = now + sched.interval_ms
            try:
                with self.tracer.span(
                        "detector.detect",
                        detector=type(sched.detector).__name__) as sp:
                    anomalies = sched.detector.detect(now)
                    sp.set(anomalies=len(anomalies))
            except Exception:
                # A broken detector must not kill the loop — but it must
                # be LOUD: logged with traceback and counted on the
                # detector-failure-rate meter (/metrics).
                self._detector_failures.mark()
                LOG.exception("detector %s failed in detect(); continuing",
                              type(sched.detector).__name__)
                continue
            for a in anomalies:
                self._enqueue(a, now)
                detected += 1
        return detected

    def _enqueue(self, anomaly: KafkaAnomaly, ready_ms: int) -> None:
        with self._lock:
            # De-dup: a pending anomaly of the same type and description is
            # the same ongoing condition re-detected — keep the earliest so
            # the notifier's time thresholds measure from first detection.
            for _, _, _, queued in self._queue:
                if (queued.anomaly_type is anomaly.anomaly_type
                        and queued.reason() == anomaly.reason()):
                    queued.merge_from(anomaly)   # absorb fresher data
                    return
            heapq.heappush(self._queue,
                           (int(anomaly.anomaly_type), ready_ms,
                            next(self._counter), anomaly))
            self._anomaly_meters[anomaly.anomaly_type].mark()
            history = self.recent_anomalies[anomaly.anomaly_type]
            history.append(anomaly.to_json())
            del history[:-self.num_cached_recent_anomalies]
        journal = getattr(self.facade, "journal", None)
        if journal is not None:
            # Head of the causal chain: detected → fix-dispatched →
            # fix-outcome. The seq rides the anomaly so the dispatch
            # event can name it as its cause.
            anomaly._journal_seq = journal.record(
                "detector", "anomaly-detected",
                detail={"anomalyId": anomaly.anomaly_id,
                        "anomalyType": anomaly.anomaly_type.name,
                        "reason": anomaly.reason()})

    def _handle_queue(self, now: int) -> dict:
        fixed, rechecks, ignored = 0, 0, 0
        deferred: list[tuple[int, int, int, KafkaAnomaly]] = []
        just_fixed: set[tuple[KafkaAnomalyType, str]] = set()
        while True:
            with self._lock:
                if not self._queue:
                    break
                prio, ready, cnt, anomaly = heapq.heappop(self._queue)
            if (anomaly.anomaly_type, anomaly.reason()) in just_fixed:
                ignored += 1   # stale duplicate of a condition just fixed
                continue
            if ready > now:
                deferred.append((prio, ready, cnt, anomaly))
                continue
            if not anomaly.still_valid(self.facade):
                ignored += 1   # condition recovered while deferred
                continue
            action = self.notifier.on_anomaly(anomaly, now)
            if (action.result is AnomalyNotificationResult.FIX
                    and not self._fixable(anomaly)):
                # Mass failure: refuse the automatic drain (ref
                # fixable.failed.broker.*.threshold — reassigning most of a
                # cluster away is worse than waiting for a human).
                ignored += 1
                continue
            if action.result is AnomalyNotificationResult.FIX:
                if self.facade.executor.has_ongoing_execution():
                    # ref maintenance.event.stop.ongoing.execution: an
                    # operator-announced maintenance plan PREEMPTS the
                    # running execution instead of queueing behind it.
                    from .anomalies import MaintenanceEvent
                    if (isinstance(anomaly, MaintenanceEvent)
                            and getattr(self.facade,
                                        "maintenance_stop_ongoing", False)):
                        self.facade.stop_ongoing_and_wait()
                    if self.facade.executor.has_ongoing_execution():
                        # ref :534 fixAnomalyInProgress: wait it out
                        deferred.append((prio, now + 10_000, cnt, anomaly))
                        continue
                fixed += 1
                just_fixed.add((anomaly.anomaly_type, anomaly.reason()))
                self.num_self_healing_started += 1
                # ref AnomalyDetectorState mean-time-to-start-fix-ms.
                self._time_to_start_fix.update(
                    max(now - anomaly.detected_ms, 0) / 1000.0)
                self.ongoing_self_healing = anomaly.anomaly_id
                journal = getattr(self.facade, "journal", None)
                dispatched_seq = None
                if journal is not None:
                    dispatched_seq = journal.record(
                        "detector", "fix-dispatched",
                        cause=getattr(anomaly, "_journal_seq", None),
                        detail={"anomalyId": anomaly.anomaly_id,
                                "anomalyType": anomaly.anomaly_type.name})
                try:
                    with self.tracer.span(
                            "detector.heal",
                            anomalyType=anomaly.anomaly_type.name,
                            anomalyId=anomaly.anomaly_id) as sp:
                        ok = anomaly.fix(self.facade)
                        sp.set(fixed=bool(ok))
                    if not ok:
                        self.num_self_healing_failed += 1
                    if journal is not None:
                        journal.record(
                            "detector", "fix-outcome",
                            severity="info" if ok else "warn",
                            cause=dispatched_seq,
                            detail={"anomalyId": anomaly.anomaly_id,
                                    "fixed": bool(ok)})
                except Exception:
                    self.num_self_healing_failed += 1
                    if journal is not None:
                        journal.record(
                            "detector", "fix-outcome", severity="error",
                            cause=dispatched_seq,
                            detail={"anomalyId": anomaly.anomaly_id,
                                    "fixed": False, "crashed": True})
                    LOG.exception("self-healing fix for %s (%s) failed",
                                  anomaly.anomaly_id,
                                  anomaly.anomaly_type.name)
                finally:
                    self.ongoing_self_healing = None
            elif action.result is AnomalyNotificationResult.CHECK:
                rechecks += 1
                deferred.append((prio, now + max(action.delay_ms, 1), cnt,
                                 anomaly))
            else:
                ignored += 1
        with self._lock:
            for item in deferred:
                heapq.heappush(self._queue, item)
        return {"fixed": fixed, "rechecked": rechecks, "ignored": ignored}

    # ------------------------------------------------- background thread
    def start_detection(self, tick_s: float = 5.0) -> None:
        """ref startDetection :235."""
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(tick_s):
                try:
                    self.run_once()
                except Exception:
                    # The background loop must survive any round failure,
                    # visibly: log + meter instead of a silent swallow.
                    self._detector_failures.mark()
                    LOG.exception(
                        "anomaly detection round failed; loop continues")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="anomaly-detector")
        self._thread.start()

    def stop_detection(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # ------------------------------------------------------------- state
    def state_json(self) -> dict:
        """ref AnomalyDetectorState.java:424."""
        balancedness = None
        resilience = None
        time_to_breach = None
        for sched in self._schedules:
            if hasattr(sched.detector, "last_balancedness"):
                balancedness = sched.detector.last_balancedness
            if hasattr(sched.detector, "last_resilience"):
                resilience = sched.detector.last_resilience
            if hasattr(sched.detector, "last_time_to_breach_ms"):
                time_to_breach = sched.detector.last_time_to_breach_ms
        return {
            # 100 = the last N-1 sweep found every single-broker loss
            # survivable (resilience detector; None = not registered/run)
            "resilienceScore": resilience,
            # estimated ms until the forecast trajectory's projected
            # capacity breach (capacity-forecast detector; None = not
            # registered/run or no breach projected)
            "forecastTimeToBreachMs": time_to_breach,
            "selfHealingEnabled": {
                t.name: v for t, v in
                self.notifier.self_healing_enabled().items()},
            "recentAnomalies": {t.name: v for t, v in
                                self.recent_anomalies.items() if v},
            "numSelfHealingStarted": self.num_self_healing_started,
            "numSelfHealingFailed": self.num_self_healing_failed,
            # Alerts fire on their own threshold even when self-healing is
            # disabled (ref SelfHealingNotifier alert-vs-fix thresholds);
            # surfacing the count lets operators (and tests) distinguish
            # "nothing detected" from "detected but healing is off".
            "numAlertsFired": len(getattr(self.notifier, "alerts", ())),
            "ongoingSelfHealing": self.ongoing_self_healing,
            "balancednessScore": balancedness,
            "numQueuedAnomalies": len(self._queue),
        }
