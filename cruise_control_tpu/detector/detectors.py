"""The six anomaly detectors (ref ``detector/GoalViolationDetector.java:56``,
``AbstractBrokerFailureDetector.java`` / ``KafkaBrokerFailureDetector.java``
(metadata-polling flavor), ``DiskFailureDetector.java``,
``MetricAnomalyDetector.java``, ``SlowBrokerFinder.java``,
``TopicAnomalyDetector.java`` + ``TopicReplicationFactorAnomalyFinder.java``,
``MaintenanceEventDetector.java`` + ``MaintenanceEventTopicReader.java``).

Each detector exposes ``detect(now_ms) -> list[KafkaAnomaly]``; the manager
schedules them at their own intervals and queues what they return.
"""

from __future__ import annotations

import fnmatch
import json
import logging
import os
from dataclasses import dataclass, field

import numpy as np

from ..core.anomaly import PercentileMetricAnomalyFinder
from ..core.snapshot import atomic_write_json
from ..core.metricdef import BrokerMetric
from .anomalies import (BrokerFailures, DiskFailures, GoalViolations,
                        KafkaMetricAnomaly, MaintenanceEvent, SlowBrokers,
                        TopicReplicationFactorAnomaly)

LOG = logging.getLogger(__name__)


class BrokerFailureDetector:
    """Metadata-polling broker failure detection (ref
    KafkaBrokerFailureDetector.java:23; the ZK-watcher flavor is an
    event-push variant of the same comparison).

    A broker is *failed* when it is expected (hosts replicas / was alive
    before) but the metadata reports it dead. First-seen failure times
    persist across restarts via a JSON file (ref the failed-broker file the
    reference keeps) so the 15/30-minute notifier thresholds survive a
    controller restart.
    """

    def __init__(self, admin, *, persist_path: str | None = None) -> None:
        self.admin = admin
        self.persist_path = persist_path
        self._failed_since: dict[int, int] = {}
        if persist_path and os.path.exists(persist_path):
            # A corrupt/torn/empty stamp file must not crash the detector
            # thread at 3am: warn + start fresh (the stamps only widen
            # the notifier thresholds — recoverable state, unlike the
            # failures it tracks). Writes are atomic now, but files
            # written by the pre-atomic code (or a full disk) survive.
            try:
                with open(persist_path, encoding="utf-8") as f:
                    self._failed_since = {int(k): int(v)
                                          for k, v in json.load(f).items()}
            except (OSError, ValueError) as exc:
                LOG.warning(
                    "failed-broker stamp file %s unreadable (%s: %s); "
                    "starting with empty failure history", persist_path,
                    type(exc).__name__, exc)

    def detect(self, now_ms: int) -> list[BrokerFailures]:
        alive = self.admin.describe_cluster()
        dead = {b for b, up in alive.items() if not up}
        for b in dead:
            self._failed_since.setdefault(b, now_ms)
        for b in list(self._failed_since):
            if b not in dead:
                del self._failed_since[b]
        self._persist()
        if not self._failed_since:
            return []
        return [BrokerFailures(detected_ms=now_ms,
                               failed_brokers=dict(self._failed_since))]

    def _persist(self) -> None:
        # Atomic (tmp + fsync + rename): a crash mid-dump used to leave a
        # torn JSON document on the LIVE file, poisoning the next start.
        if self.persist_path:
            try:
                atomic_write_json(self.persist_path, self._failed_since)
            except OSError as exc:
                LOG.warning("could not persist failed-broker stamps to "
                            "%s: %s", self.persist_path, exc)


class DiskFailureDetector:
    """Offline-logdir scan (ref DiskFailureDetector.java via
    AdminClient.describeLogDirs)."""

    def __init__(self, admin) -> None:
        self.admin = admin

    def detect(self, now_ms: int) -> list[DiskFailures]:
        offline_fn = getattr(self.admin, "offline_logdirs", None)
        if offline_fn is None:
            return []
        offline = {b: dirs for b, dirs in offline_fn().items() if dirs}
        if not offline:
            return []
        return [DiskFailures(detected_ms=now_ms, failed_disks=offline)]


@dataclass
class BalancednessWeights:
    """ref goal.balancedness.priority.weight / strictness.weight
    (GoalOptimizer.java:136-137)."""

    priority_weight: float = 1.1
    strictness_weight: float = 1.5


class GoalViolationDetector:
    """Dry-runs the detection goals on a fresh model and reports violations
    plus the balancedness score gauge [0, 100] (ref
    GoalViolationDetector.java:56, balancednessScore()).

    Score: 100 * (1 - sum(weight of violated goals) / sum(all weights)),
    where goal i (priority order) has weight priority_weight^(n-i), doubled
    by strictness_weight for hard goals — later(-priority) goals hurt less.
    """

    def __init__(self, monitor, optimizer,
                 weights: BalancednessWeights | None = None) -> None:
        self.monitor = monitor
        self.optimizer = optimizer
        self.weights = weights or BalancednessWeights()
        self.last_balancedness: float = 100.0

    def _goal_weight(self, index: int, hard: bool, total: int) -> float:
        w = self.weights.priority_weight ** (total - index)
        return w * (self.weights.strictness_weight if hard else 1.0)

    def detect(self, now_ms: int) -> list[GoalViolations]:
        from ..monitor import NotEnoughValidWindowsException
        # Dead brokers / offline replicas are broker- and disk-failure
        # territory; optimizing around them would report spurious unfixable
        # violations (ref GoalViolationDetector skipping detection when the
        # cluster has dead brokers or offline replicas).
        alive = self.monitor.admin.describe_cluster()
        if not all(alive.values()):
            return []
        offline_fn = getattr(self.monitor.admin, "offline_replicas", None)
        if offline_fn is not None and offline_fn():
            return []
        try:
            result = self.monitor.cluster_model(now_ms)
        except NotEnoughValidWindowsException:
            return []
        from ..analyzer import OptimizationOptions
        # Detection is a dry-run measurement: unfixable hard goals are a
        # *finding* here, not an error.
        res = self.optimizer.optimize(
            result.model, result.metadata,
            OptimizationOptions(skip_hard_goal_check=True))
        goals = self.optimizer.goals
        total_w = sum(self._goal_weight(i, g.hard, len(goals))
                      for i, g in enumerate(goals))
        violated_w = sum(
            self._goal_weight(i, g.hard, len(goals))
            for i, (g, gr) in enumerate(zip(goals, res.goal_results))
            if gr.violation_before > 1e-6)
        self.last_balancedness = round(
            100.0 * (1.0 - violated_w / total_w) if total_w else 100.0, 2)
        fixable = [gr.name for gr in res.goal_results
                   if gr.violation_before > 1e-6 and gr.satisfied]
        unfixable = [gr.name for gr in res.goal_results
                     if gr.violation_before > 1e-6 and not gr.satisfied]
        if not fixable and not unfixable:
            return []
        return [GoalViolations(detected_ms=now_ms,
                               fixable_violations=fixable,
                               unfixable_violations=unfixable)]


class MetricAnomalyDetector:
    """Percentile-based broker metric anomalies (ref
    MetricAnomalyDetector.java + KafkaMetricAnomalyFinder + the core
    percentile finder)."""

    def __init__(self, monitor,
                 finder: PercentileMetricAnomalyFinder | None = None) -> None:
        self.monitor = monitor
        self.finder = finder or PercentileMetricAnomalyFinder(
            interested_metrics=[int(BrokerMetric.BROKER_LOG_FLUSH_TIME_MS_MEAN),
                                int(BrokerMetric.CPU_USAGE)])

    def detect(self, now_ms: int) -> list[KafkaMetricAnomaly]:
        windows = self.monitor.broker_window_stats(now_ms)
        return [KafkaMetricAnomaly(detected_ms=now_ms,
                                   description=a.description,
                                   broker_id=a.entity)
                for a in self.finder.anomalies(windows)]


class SlowBrokerFinder:
    """Statistical slow-broker detection (ref SlowBrokerFinder.java:479):
    a broker is slow when its log-flush-time *per byte handled* is an
    outlier against the fleet (mean + ``num_std`` sigma) and its absolute
    flush time exceeds a floor — high flush time on an idle broker or a
    uniformly-loaded slow fleet should not page."""

    def __init__(self, monitor, *, num_std: float = 3.0,
                 flush_time_floor_ms: float = 100.0,
                 remove_slow_brokers: bool = False) -> None:
        self.monitor = monitor
        self.num_std = num_std
        self.flush_time_floor_ms = flush_time_floor_ms
        self.remove_slow_brokers = remove_slow_brokers

    def detect(self, now_ms: int) -> list[SlowBrokers]:
        windows = self.monitor.broker_window_stats(now_ms)
        if len(windows) < 2:
            return []
        ratios: dict[int, float] = {}
        flush: dict[int, float] = {}
        for broker, values in windows.items():
            ft = float(values[BrokerMetric.BROKER_LOG_FLUSH_TIME_MS_MEAN].mean())
            by = float(values[BrokerMetric.LEADER_BYTES_IN].mean()
                       + values[BrokerMetric.REPLICATION_BYTES_IN_RATE].mean())
            ratios[broker] = ft / (by + 1.0)
            flush[broker] = ft
        vals = np.asarray(list(ratios.values()))
        mean, std = vals.mean(), vals.std()
        slow = {b: flush[b] for b, r in ratios.items()
                if r > mean + self.num_std * std
                and flush[b] > self.flush_time_floor_ms}
        if not slow:
            return []
        return [SlowBrokers(detected_ms=now_ms, slow_brokers=slow,
                            remove_slow_brokers=self.remove_slow_brokers)]


class TopicAnomalyDetector:
    """Replication-factor anomalies for matching topics (ref
    TopicAnomalyDetector.java + TopicReplicationFactorAnomalyFinder.java)."""

    def __init__(self, admin, *, target_rf: int = 2,
                 topic_pattern: str = "*") -> None:
        self.admin = admin
        self.target_rf = target_rf
        self.topic_pattern = topic_pattern

    def detect(self, now_ms: int) -> list[TopicReplicationFactorAnomaly]:
        by_topic: dict[str, set[int]] = {}
        for (topic, _), info in self.admin.describe_partitions().items():
            if fnmatch.fnmatch(topic, self.topic_pattern):
                by_topic.setdefault(topic, set()).add(len(info.replicas))
        bad = {t: min(rfs) for t, rfs in by_topic.items()
               if rfs != {self.target_rf}}
        if not bad:
            return []
        return [TopicReplicationFactorAnomaly(
            detected_ms=now_ms, bad_topics=bad, target_rf=self.target_rf)]


class IdempotenceCache:
    """Durable de-dup of equivalent maintenance events (ref
    ``detector/IdempotenceCache.java:106``): an event key blocks duplicates
    for ``retention_ms``, the cache holds at most ``max_size`` keys
    (oldest evicted first), and the key->time map persists to a JSON file
    so a restart cannot re-execute a plan it already accepted."""

    def __init__(self, *, retention_ms: int = 180_000, max_size: int = 25,
                 persist_path: str | None = None, now_ms=None) -> None:
        import time as _t
        self.retention_ms = retention_ms
        self.max_size = max_size
        self.persist_path = persist_path
        self._now_ms = now_ms or (lambda: int(_t.time() * 1000))
        self._seen: dict[str, int] = {}
        if persist_path:
            # OSError included: any unreadable/torn cache degrades to an
            # empty one (duplicates within the retention window may then
            # re-execute — the documented trade for not crashing).
            try:
                with open(persist_path, encoding="utf-8") as f:
                    self._seen = {k: int(v)
                                  for k, v in json.load(f).items()}
            except (OSError, ValueError):
                pass

    def _persist(self) -> None:
        # Atomic like the failed-broker stamps: a torn idempotence cache
        # is worse than an empty one (it crashes the reader), and a LOST
        # one re-executes accepted plans.
        if self.persist_path:
            try:
                atomic_write_json(self.persist_path, self._seen)
            except OSError:
                pass   # best-effort, same contract as the tolerant load

    def _prune(self, now: int) -> None:
        cutoff = now - self.retention_ms
        for k in [k for k, t in self._seen.items() if t < cutoff]:
            del self._seen[k]
        while len(self._seen) > self.max_size:
            self._seen.pop(min(self._seen, key=self._seen.get))

    def check_and_add(self, key: str) -> bool:
        """True when the key is fresh (and is now recorded); False for a
        duplicate inside the retention window."""
        now = self._now_ms()
        self._prune(now)
        if key in self._seen:
            return False
        self._seen[key] = now
        self._prune(now)
        self._persist()
        return True


class MaintenanceEventReader:
    """In-memory maintenance-plan source with idempotence de-dup (ref
    MaintenanceEventTopicReader.java:350 + IdempotenceCache.java; the
    reference reads serialized plans from a Kafka topic).

    ``enable_idempotence`` / cache sizing mirror
    maintenance.event.enable.idempotence / .idempotence.retention.ms /
    .max.idempotence.cache.size; ``persist_path`` makes accepted plans
    survive a restart."""

    def __init__(self, *, enable_idempotence: bool = True,
                 idempotence_retention_ms: int = 180_000,
                 max_idempotence_cache_size: int = 25,
                 persist_path: str | None = None, now_ms=None) -> None:
        self._plans: list[MaintenanceEvent] = []
        self.enable_idempotence = enable_idempotence
        self._cache = IdempotenceCache(
            retention_ms=idempotence_retention_ms,
            max_size=max_idempotence_cache_size,
            persist_path=persist_path, now_ms=now_ms)

    def submit(self, event: MaintenanceEvent) -> bool:
        if self.enable_idempotence:
            key = "|".join(map(str, (event.event_type.value,
                                     sorted(event.broker_ids),
                                     event.topic_pattern,
                                     event.target_rf)))
            if not self._cache.check_and_add(key):
                return False
        self._plans.append(event)
        return True

    def drain(self) -> list[MaintenanceEvent]:
        plans, self._plans = self._plans, []
        return plans


class MaintenanceEventDetector:
    """ref MaintenanceEventDetector.java."""

    def __init__(self, reader: MaintenanceEventReader) -> None:
        self.reader = reader

    def detect(self, now_ms: int) -> list[MaintenanceEvent]:
        return self.reader.drain()
