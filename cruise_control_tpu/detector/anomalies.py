"""Kafka anomaly types (ref ``detector/KafkaAnomalyType.java:29`` and the
``KafkaAnomaly`` subclasses: ``BrokerFailures``, ``DiskFailures``,
``GoalViolations``, ``KafkaMetricAnomaly``, ``SlowBrokers``,
``TopicReplicationFactorAnomaly``, ``MaintenanceEvent``).

Each anomaly knows how to fix itself through the facade — the same
runnables the REST endpoints use (ref each anomaly's ``fix()`` invoking
Remove/Demote/Rebalance runnables with ``isTriggeredByAnomaly=true``).
"""

from __future__ import annotations

import enum
import itertools
from dataclasses import dataclass, field


class KafkaAnomalyType(enum.IntEnum):
    """Priority order: lower value = higher priority (ref
    KafkaAnomalyType.java:29)."""

    BROKER_FAILURE = 0
    MAINTENANCE_EVENT = 1
    DISK_FAILURE = 2
    METRIC_ANOMALY = 3
    TOPIC_ANOMALY = 4
    GOAL_VIOLATION = 5
    #: predicted (what-if) risk, not a live fault — low priority:
    #: every realized anomaly outranks a forecast
    BROKER_RISK = 6
    #: predicted capacity pressure from the load-trajectory forecast
    #: (forecast/detector.py) — like BROKER_RISK, a projection: lowest
    #: priority, provisioning evidence rather than a self-healing drain
    CAPACITY_FORECAST = 7
    #: a fleet member's endpoint walked DEGRADED → QUARANTINED
    #: (fleet/registry.py health state machine) — alert-only: the
    #: member's DATA plane may be perfectly healthy behind an
    #: unreachable endpoint, so the only safe "fix" is the registry's
    #: own half-open probe/readmission cycle, not a drain. Still a
    #: realized control-plane fault, so it outranks the SLO page below
    FLEET_MEMBER_QUARANTINED = 8
    #: SLO burn-rate breach from core/slo.py — an alerting signal about
    #: the control plane's own freshness, not a cluster fault: lowest
    #: priority of all so every real (or even projected) anomaly
    #: outranks it in the heal queue
    SLO_BREACH = 9


_ids = itertools.count()


@dataclass
class KafkaAnomaly:
    """ref KafkaAnomaly.java. ``fix`` returns True when a fix started."""

    detected_ms: int
    anomaly_id: str = field(default="", init=False)

    def __post_init__(self):
        self.anomaly_id = f"{type(self).__name__.lower()}-{next(_ids)}"

    anomaly_type: KafkaAnomalyType = KafkaAnomalyType.GOAL_VIOLATION

    def reason(self) -> str:
        return type(self).__name__

    def fix(self, facade) -> bool:
        raise NotImplementedError

    def still_valid(self, facade) -> bool:
        """Re-check against live cluster state before acting — a deferred
        anomaly may describe a condition that has since recovered."""
        return True

    def merge_from(self, other: "KafkaAnomaly") -> None:
        """Absorb a fresher detection of the same condition (the manager
        de-dups by reason but keeps the earliest queue entry so notifier
        time thresholds measure from first detection)."""

    def to_json(self) -> dict:
        return {"anomalyId": self.anomaly_id,
                "type": self.anomaly_type.name,
                "detectedMs": self.detected_ms,
                "description": self.reason()}


@dataclass
class BrokerFailures(KafkaAnomaly):
    """ref BrokerFailures.java."""

    failed_brokers: dict[int, int] = field(default_factory=dict)  # id -> since
    anomaly_type: KafkaAnomalyType = KafkaAnomalyType.BROKER_FAILURE

    def reason(self) -> str:
        return f"Brokers {sorted(self.failed_brokers)} failed"

    def still_valid(self, facade) -> bool:
        """Drop brokers that came back; a fully-recovered failure must not
        drain healthy brokers when its deferred fix finally fires."""
        alive = facade.admin.describe_cluster()
        self.failed_brokers = {b: t for b, t in self.failed_brokers.items()
                               if not alive.get(b, False)}
        return bool(self.failed_brokers)

    def merge_from(self, other: "KafkaAnomaly") -> None:
        if isinstance(other, BrokerFailures):
            # Keep the earliest failure time per broker; adopt new failures.
            for b, t in other.failed_brokers.items():
                self.failed_brokers[b] = min(
                    t, self.failed_brokers.get(b, t))

    def fix(self, facade) -> bool:
        res, exec_res = facade.remove_brokers(
            sorted(self.failed_brokers), dryrun=False,
            uuid=self.anomaly_id,
            goals=getattr(facade, "self_healing_goals", None))
        # No proposals == nothing left to move (already healed): success.
        return exec_res is None or exec_res.succeeded


@dataclass
class DiskFailures(KafkaAnomaly):
    """ref DiskFailures.java (offline logdirs)."""

    failed_disks: dict[int, list[str]] = field(default_factory=dict)
    anomaly_type: KafkaAnomalyType = KafkaAnomalyType.DISK_FAILURE

    def reason(self) -> str:
        return f"Disks failed: {self.failed_disks}"

    def fix(self, facade) -> bool:
        res, exec_res = facade.fix_offline_replicas(
            dryrun=False, uuid=self.anomaly_id,
            goals=getattr(facade, "self_healing_goals", None))
        return exec_res is None or exec_res.succeeded


@dataclass
class GoalViolations(KafkaAnomaly):
    """ref GoalViolations.java."""

    fixable_violations: list[str] = field(default_factory=list)
    unfixable_violations: list[str] = field(default_factory=list)
    anomaly_type: KafkaAnomalyType = KafkaAnomalyType.GOAL_VIOLATION

    def reason(self) -> str:
        return (f"Violated goals: fixable {self.fixable_violations}, "
                f"unfixable {self.unfixable_violations}")

    def fix(self, facade) -> bool:
        if not self.fixable_violations:
            return False
        # ref self.healing.goals: when configured, self-healing optimizes
        # with that chain instead of the default (serve.py validates it
        # covers the registered hard goals at startup).
        res, exec_res = facade.rebalance(
            dryrun=False, uuid=self.anomaly_id, ignore_proposal_cache=True,
            goals=getattr(facade, "self_healing_goals", None))
        return exec_res is None or exec_res.succeeded


@dataclass
class KafkaMetricAnomaly(KafkaAnomaly):
    """ref KafkaMetricAnomaly.java — alert-only by default."""

    description: str = ""
    broker_id: int | None = None
    anomaly_type: KafkaAnomalyType = KafkaAnomalyType.METRIC_ANOMALY

    def reason(self) -> str:
        return self.description

    def fix(self, facade) -> bool:
        return False   # ref: metric anomalies have no automatic fix


@dataclass
class SlowBrokers(KafkaAnomaly):
    """ref SlowBrokers.java: fix = demote (remove leadership), or remove
    when configured."""

    slow_brokers: dict[int, float] = field(default_factory=dict)
    remove_slow_brokers: bool = False
    anomaly_type: KafkaAnomalyType = KafkaAnomalyType.METRIC_ANOMALY

    def reason(self) -> str:
        return f"Slow brokers {sorted(self.slow_brokers)}"

    def fix(self, facade) -> bool:
        ids = sorted(self.slow_brokers)
        if self.remove_slow_brokers:
            _, exec_res = facade.remove_brokers(ids, dryrun=False,
                                                uuid=self.anomaly_id)
        else:
            _, exec_res = facade.demote_brokers(ids, dryrun=False,
                                                uuid=self.anomaly_id)
        return exec_res is None or exec_res.succeeded


def _rf_change_kwargs(facade) -> dict:
    """Shared goal-chain plumbing for self-healing RF changes (the
    RF-anomaly fix and the RF maintenance event take the same action).

    ref replication.factor.self.healing.skip.rack.awareness.check:
    clusters without reliable rack metadata skip rack-awareness for RF
    self-healing. An in-chain hard goal gates regardless of audit
    waivers, so the rack goals must leave the CHAIN (healing chain or
    default, minus the rack goals) AND be waived from the off-chain
    audit — the change_rf placement itself still prefers fresh racks
    when it can.

    Cost note: a rack-less chain is a DIFFERENT goal set, so the first
    fix pays its XLA compile (then the facade's goal-optimizer LRU keeps
    it warm). Deployments using this flag should set self.healing.goals
    explicitly — the deploy-time validation then covers the exact chain
    the 3am fix will run."""
    goals = getattr(facade, "self_healing_goals", None)
    kwargs: dict = {"goals": goals}
    if getattr(facade, "rf_self_healing_skip_rack_check", False):
        from ..analyzer import OptimizationOptions
        from ..analyzer.goals import default_goals
        rack = {"RackAwareGoal", "RackAwareDistributionGoal"}
        names = goals or [g.name for g in default_goals()]
        kwargs["goals"] = [n for n in names if n not in rack]
        kwargs["options"] = OptimizationOptions(
            waived_hard_goals=frozenset(rack))
    return kwargs


@dataclass
class TopicReplicationFactorAnomaly(KafkaAnomaly):
    """ref TopicReplicationFactorAnomaly.java: topics whose RF deviates from
    the target."""

    bad_topics: dict[str, int] = field(default_factory=dict)  # topic -> rf
    target_rf: int = 3
    anomaly_type: KafkaAnomalyType = KafkaAnomalyType.TOPIC_ANOMALY

    def reason(self) -> str:
        return (f"Topics with RF != {self.target_rf}: "
                f"{sorted(self.bad_topics)}")

    def fix(self, facade) -> bool:
        ok = True
        for topic in sorted(self.bad_topics):
            _, exec_res = facade.update_topic_configuration(
                topic, self.target_rf, dryrun=False, uuid=self.anomaly_id,
                **_rf_change_kwargs(facade))
            ok &= exec_res is None or exec_res.succeeded
        return ok


@dataclass
class BrokerRisk(KafkaAnomaly):
    """Predicted single-broker-loss risk from the resilience detector's
    N-1 what-if sweep: losing any broker in ``at_risk`` would violate the
    listed hard goals (no reference analog — the reference only reacts to
    realized failures).

    The 'fix' is provisioning evidence, not a rebalance: the anomaly
    carries an UNDER_PROVISIONED recommendation (with the headroom
    numbers that motivated it) and feeds it to the configured
    Provisioner — acting ahead of the failure is the platform layer's
    call, not an automatic drain of a healthy cluster.
    """

    #: broker id -> hard goals its loss would violate
    at_risk: dict[int, list[str]] = field(default_factory=dict)
    #: provisioner.ProvisionRecommendation (UNDER_PROVISIONED evidence)
    recommendation: object | None = None
    #: the sweep's max composite risk score [0, 1]
    max_risk: float = 0.0
    anomaly_type: KafkaAnomalyType = KafkaAnomalyType.BROKER_RISK

    def reason(self) -> str:
        detail = "; ".join(
            f"broker {b}: {', '.join(goals)}"
            for b, goals in sorted(self.at_risk.items()))
        return f"N-1 risk ({detail})"

    def fix(self, facade) -> bool:
        detector = getattr(facade, "detector", None)
        provisioner = getattr(detector, "provisioner", None)
        if provisioner is None or self.recommendation is None:
            return False
        provisioner.rightsize(recommendations=[self.recommendation])
        return True

    def to_json(self) -> dict:
        out = super().to_json()
        out["atRiskBrokers"] = {str(b): goals
                                for b, goals in sorted(self.at_risk.items())}
        out["maxRisk"] = round(self.max_risk, 4)
        if self.recommendation is not None:
            out["recommendation"] = self.recommendation.to_json()
        return out


@dataclass
class CapacityForecast(KafkaAnomaly):
    """Predicted capacity breach from the load-trajectory forecast
    (forecast/detector.py): at the scored horizon/quantile the projected
    load violates hard goals or exceeds usable capacity. Arrives BEFORE
    the pressure materializes — the whole point — so the urgency signal
    (``time_to_breach_ms``) rides the reason string every notifier
    alert renders, and the 'fix' is provisioning (broker adds and/or
    partition-count growth for hot topics), never a drain of a cluster
    that is still healthy today.
    """

    #: estimated ms until the projected breach (linear interpolation
    #: over the scored horizons' capacity pressure)
    time_to_breach_ms: int | None = None
    #: the (horizon, quantile) point the breach was scored at
    horizon_ms: int = 0
    quantile: float = 0.9
    #: ProvisionRecommendations (broker add + per-topic partition
    #: counts), each carrying time_to_breach_ms + forecast provenance
    recommendations: list = field(default_factory=list)
    max_risk: float = 0.0
    anomaly_type: KafkaAnomalyType = KafkaAnomalyType.CAPACITY_FORECAST

    def reason(self) -> str:
        when = ("unknown" if self.time_to_breach_ms is None
                else f"~{self.time_to_breach_ms / 60000.0:.0f} min")
        return (f"Forecast breach at +{self.horizon_ms}ms "
                f"p{int(round(self.quantile * 100))} "
                f"(time to breach {when}, risk {self.max_risk:.2f})")

    def fix(self, facade) -> bool:
        detector = getattr(facade, "detector", None)
        provisioner = getattr(detector, "provisioner", None)
        if provisioner is None or not self.recommendations:
            return False
        provisioner.rightsize(recommendations=list(self.recommendations))
        return True

    def to_json(self) -> dict:
        out = super().to_json()
        out["timeToBreachMs"] = self.time_to_breach_ms
        out["horizonMs"] = self.horizon_ms
        out["quantile"] = self.quantile
        out["maxRisk"] = round(self.max_risk, 4)
        out["recommendations"] = [r.to_json()
                                  for r in self.recommendations]
        return out


@dataclass
class SLOBreach(KafkaAnomaly):
    """Burn-rate breach of a control-plane SLO (core/slo.py): the fast
    AND slow windows of one objective (proposal freshness, replication
    stream lag, standby staleness) both exceeded their burn thresholds.
    Alert-only: like KafkaMetricAnomaly its ``fix()`` declines — the
    breach is about the control plane itself, so rebalancing the data
    plane cannot cure it. It rides the notifier path for paging and the
    journal chain for forensics (``journal_seq`` links back to the
    ``slo``/``breach`` event the evaluator recorded)."""

    objective: str = ""
    observed_ms: float | None = None
    target_ms: float = 0.0
    fast_burn: float = 0.0
    slow_burn: float = 0.0
    journal_seq: int | None = None
    anomaly_type: KafkaAnomalyType = KafkaAnomalyType.SLO_BREACH

    def reason(self) -> str:
        observed = ("n/a" if self.observed_ms is None
                    else f"{self.observed_ms:.0f}ms")
        return (f"SLO burn-rate breach: {self.objective} observed "
                f"{observed} vs target {self.target_ms:.0f}ms "
                f"(fast burn {self.fast_burn:.2f}, "
                f"slow burn {self.slow_burn:.2f})")

    def fix(self, facade) -> bool:
        return False   # alert-only: the breach is in the control plane

    def to_json(self) -> dict:
        out = super().to_json()
        out["objective"] = self.objective
        out["observedMs"] = self.observed_ms
        out["targetMs"] = self.target_ms
        out["fastBurn"] = self.fast_burn
        out["slowBurn"] = self.slow_burn
        out["journalSeq"] = self.journal_seq
        return out


@dataclass
class FleetMemberQuarantined(KafkaAnomaly):
    """A fleet member crossed the quarantine threshold: N consecutive
    degraded ticks (breaker open / fetch deadline missed / fetch error)
    and the registry excluded it from the fleet stack and dispatch
    (fleet/registry.py). Alert-only: ``fix()`` declines — readmission is
    the registry's own half-open probe → warm rebuild → rejoin cycle,
    and draining a cluster because its ENDPOINT is unreachable would
    punish a healthy data plane. ``journal_seq`` links the quarantine
    event in the flight recorder (``fleet`` category) for cause-chain
    forensics."""

    cluster_id: str = ""
    degraded_ticks: int = 0
    breaker_state: str = ""
    last_error: str | None = None
    journal_seq: int | None = None
    anomaly_type: KafkaAnomalyType = \
        KafkaAnomalyType.FLEET_MEMBER_QUARANTINED

    def reason(self) -> str:
        return (f"Fleet member {self.cluster_id!r} quarantined after "
                f"{self.degraded_ticks} degraded ticks (breaker "
                f"{self.breaker_state}; last error: {self.last_error})")

    def fix(self, facade) -> bool:
        return False   # alert-only: readmission is the registry's probe

    def to_json(self) -> dict:
        out = super().to_json()
        out["clusterId"] = self.cluster_id
        out["degradedTicks"] = self.degraded_ticks
        out["breakerState"] = self.breaker_state
        out["lastError"] = self.last_error
        out["journalSeq"] = self.journal_seq
        return out


class MaintenanceEventType(enum.Enum):
    """ref MaintenancePlan types."""

    ADD_BROKER = "ADD_BROKER"
    REMOVE_BROKER = "REMOVE_BROKER"
    FIX_OFFLINE_REPLICAS = "FIX_OFFLINE_REPLICAS"
    REBALANCE = "REBALANCE"
    DEMOTE_BROKER = "DEMOTE_BROKER"
    TOPIC_REPLICATION_FACTOR = "TOPIC_REPLICATION_FACTOR"


@dataclass
class MaintenanceEvent(KafkaAnomaly):
    """ref MaintenanceEvent.java: operator-announced plan consumed from the
    maintenance topic; 'fixing' = executing the plan."""

    event_type: MaintenanceEventType = MaintenanceEventType.REBALANCE
    broker_ids: list[int] = field(default_factory=list)
    topic_pattern: str | None = None
    target_rf: int | None = None
    anomaly_type: KafkaAnomalyType = KafkaAnomalyType.MAINTENANCE_EVENT

    def reason(self) -> str:
        return f"Maintenance: {self.event_type.value} {self.broker_ids}"

    def fix(self, facade) -> bool:
        # Preemption of an ongoing execution (ref
        # maintenance.event.stop.ongoing.execution) happens in the
        # manager's deferral gate — by the time fix() runs the executor
        # is idle.
        t = self.event_type
        if t is MaintenanceEventType.ADD_BROKER:
            _, ex = facade.add_brokers(self.broker_ids, dryrun=False,
                                       uuid=self.anomaly_id)
        elif t is MaintenanceEventType.REMOVE_BROKER:
            _, ex = facade.remove_brokers(self.broker_ids, dryrun=False,
                                          uuid=self.anomaly_id)
        elif t is MaintenanceEventType.DEMOTE_BROKER:
            _, ex = facade.demote_brokers(self.broker_ids, dryrun=False,
                                          uuid=self.anomaly_id)
        elif t is MaintenanceEventType.FIX_OFFLINE_REPLICAS:
            _, ex = facade.fix_offline_replicas(dryrun=False,
                                                uuid=self.anomaly_id)
        elif t is MaintenanceEventType.TOPIC_REPLICATION_FACTOR:
            _, ex = facade.update_topic_configuration(
                self.topic_pattern or "*", self.target_rf or 3,
                dryrun=False, uuid=self.anomaly_id,
                **_rf_change_kwargs(facade))
        else:
            _, ex = facade.rebalance(dryrun=False, uuid=self.anomaly_id,
                                     ignore_proposal_cache=True)
        return ex is None or ex.succeeded
