"""Provisioning verdicts + the rightsizing hook.

Ref ``analyzer/ProvisionStatus.java`` / ``ProvisionRecommendation.java`` /
``ProvisionResponse.java`` (the verdict objects goals attach to results)
and ``detector/BasicProvisioner.java`` + ``PartitionProvisioner.java`` /
``BasicBrokerProvisioner.java`` (the actuator: partition provisioning is
concrete — expand topics; broker provisioning is a platform hook).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class ProvisionStatus(enum.Enum):
    """ref ProvisionStatus.java."""

    RIGHT_SIZED = "RIGHT_SIZED"
    UNDER_PROVISIONED = "UNDER_PROVISIONED"
    OVER_PROVISIONED = "OVER_PROVISIONED"
    UNDECIDED = "UNDECIDED"


@dataclass(frozen=True)
class ProvisionRecommendation:
    """ref ProvisionRecommendation.java (399 LoC of builder — here a frozen
    record): a numeric recommendation attached to a verdict."""

    status: ProvisionStatus
    num_brokers: int | None = None
    num_partitions: int | None = None
    topic: str | None = None
    resource: str | None = None
    reason: str = ""
    #: the resource headroom numbers that motivated the verdict (e.g.
    #: ``{"demand": ..., "usableCapacity": ..., "headroomPct": ...}``
    #: from the optimizer's capacity math, or the post-N-1 remaining
    #: headroom from the resilience sweep). Excluded from hash/eq so the
    #: frozen record stays hashable despite the dict payload.
    headroom: dict | None = field(default=None, hash=False, compare=False)
    #: urgency signal (no reference analog): estimated ms until the
    #: predicted capacity breach materializes — None for reactive
    #: verdicts (the breach already happened). Rendered in ``/state``
    #: recent anomalies and every notifier alert message.
    time_to_breach_ms: int | None = None
    #: forecast provenance for predictive verdicts (fit timestamp,
    #: horizon/quantile scored, backtest error — ForecastSet.provenance
    #: plus the scoring point); None for reactive verdicts. Excluded
    #: from hash/eq like ``headroom``.
    forecast: dict | None = field(default=None, hash=False, compare=False)

    def to_json(self) -> dict:
        out: dict = {"status": self.status.value, "reason": self.reason}
        if self.num_brokers is not None:
            out["numBrokers"] = self.num_brokers
        if self.num_partitions is not None:
            out["numPartitions"] = self.num_partitions
        if self.topic is not None:
            out["topic"] = self.topic
        if self.resource is not None:
            out["resource"] = self.resource
        if self.headroom is not None:
            out["headroom"] = self.headroom
        if self.time_to_breach_ms is not None:
            out["timeToBreachMs"] = self.time_to_breach_ms
        if self.forecast is not None:
            out["forecast"] = self.forecast
        return out


@dataclass
class ProvisionResponse:
    """ref ProvisionResponse.java: aggregate of per-goal verdicts — any
    UNDER wins over OVER wins over RIGHT_SIZED."""

    status: ProvisionStatus = ProvisionStatus.UNDECIDED
    recommendations: list[ProvisionRecommendation] = field(default_factory=list)

    def aggregate(self, rec: ProvisionRecommendation) -> None:
        self.recommendations.append(rec)
        order = [ProvisionStatus.UNDECIDED, ProvisionStatus.RIGHT_SIZED,
                 ProvisionStatus.OVER_PROVISIONED,
                 ProvisionStatus.UNDER_PROVISIONED]
        if order.index(rec.status) > order.index(self.status):
            self.status = rec.status

    def to_json(self) -> dict:
        return {"status": self.status.value,
                "recommendations": [r.to_json() for r in self.recommendations]}


class Provisioner:
    """SPI (ref Provisioner.java): act on provision recommendations."""

    def rightsize(self, recommendations: list[ProvisionRecommendation],
                  **kwargs) -> dict:
        raise NotImplementedError


class BasicProvisioner(Provisioner):
    """ref BasicProvisioner.java: partition provisioning is concrete
    (creates the missing partitions via the admin client); broker
    provisioning returns the recommendation for the platform layer."""

    def __init__(self, admin) -> None:
        self.admin = admin

    def rightsize(self, recommendations: list[ProvisionRecommendation] | None = None,
                  **kwargs) -> dict:
        actions = []
        for rec in recommendations or []:
            if (rec.status is ProvisionStatus.UNDER_PROVISIONED
                    and rec.num_partitions and rec.topic):
                create = getattr(self.admin, "create_partitions", None)
                if create is not None:
                    # ref ProvisionerUtils.increasePartitionCount:
                    # num_partitions is the DESIRED TOTAL — partitions are
                    # added only if the topic currently has fewer; a topic
                    # already at/above the target is ignored, not doubled.
                    current = sum(1 for (t, _p)
                                  in self.admin.describe_partitions()
                                  if t == rec.topic)
                    missing = rec.num_partitions - current
                    if missing > 0:
                        create(rec.topic, missing)
                        actions.append({"action": "created-partitions",
                                        **rec.to_json()})
                    else:
                        actions.append({"action": "ignored-at-target",
                                        **rec.to_json()})
                    continue
            actions.append({"action": "recommended-only", **rec.to_json()})
        return {"provisionerState": ("COMPLETED" if actions
                                     else "COMPLETED_WITH_NO_ACTION"),
                "actions": actions}
