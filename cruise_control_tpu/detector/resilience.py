"""Resilience detector: proactive N-1 risk from the what-if engine.

No reference analog — the reference's detectors only react to *realized*
anomalies. This one runs the whole single-broker-loss sweep as one
batched device program (whatif/engine.py) on the live model and raises a
``BROKER_RISK`` anomaly when losing any single broker would violate a
hard goal, carrying the UNDER_PROVISIONED evidence (post-failure
headroom numbers) the Provisioner acts on. The forecast that "this
cluster does not survive its next broker failure" is exactly the
UNDER_PROVISIONED signal — it just arrives *before* the outage.
"""

from __future__ import annotations

import logging

from ..whatif import alive_broker_ids, n1_sweep
from ..whatif.spec import RESOURCE_KEYS
from .anomalies import BrokerRisk
from .provisioner import ProvisionRecommendation, ProvisionStatus

LOG = logging.getLogger(__name__)


class ResilienceDetector:
    """Scheduled N-1 what-if sweep over the live cluster model.

    Skips rounds while the cluster has realized failures (dead brokers /
    offline replicas are BrokerFailure/DiskFailure territory — a sweep on
    a degraded cluster would double-report the live anomaly as risk) and
    while the monitor has no valid model. Exposes the last sweep for
    /state consumers and a ``resilience-score`` gauge (100 = every
    single-broker loss keeps all hard goals satisfied).
    """

    def __init__(self, monitor, whatif, *, registry=None) -> None:
        self.monitor = monitor
        self.whatif = whatif
        #: last completed sweep's WhatIfReport (None until the first run)
        self.last_report = None
        #: 100 * (1 - max N-1 risk) of the last completed sweep. None
        #: until a sweep actually ran — a detector stuck behind an
        #: unready monitor or a degraded cluster must NOT report a
        #: fabricated all-clear (the gauge and /state surface None).
        self.last_resilience: float | None = None
        if registry is not None:
            from ..core.sensors import MetricRegistry
            registry.gauge(
                MetricRegistry.name("AnomalyDetector", "resilience-score"),
                lambda: self.last_resilience)

    def detect(self, now_ms: int) -> list[BrokerRisk]:
        from ..monitor import NotEnoughValidWindowsException
        alive = self.monitor.admin.describe_cluster()
        if not all(alive.values()):
            # A realized failure makes the last healthy-cluster forecast
            # meaningless — surface "unknown", not a stale all-clear.
            self.last_resilience = None
            return []
        offline_fn = getattr(self.monitor.admin, "offline_replicas", None)
        if offline_fn is not None and offline_fn():
            self.last_resilience = None
            return []
        try:
            result = self.monitor.cluster_model(now_ms)
        except NotEnoughValidWindowsException:
            self.last_resilience = None
            return []
        ids = alive_broker_ids(result.model, result.metadata)
        if len(ids) < 2:
            return []     # losing the only broker is not a plannable event
        report = self.whatif.sweep(result.model, result.metadata,
                                   n1_sweep(ids),
                                   stale_model=result.stale)
        self.last_report = report
        worst = report.riskiest()
        self.last_resilience = round(100.0 * (1.0 - worst.risk), 2)
        at_risk = {o.scenario.brokers[0]: o.violated_hard_goals
                   for o in report.outcomes if o.violated_hard_goals}
        if not at_risk:
            return []
        # UNDER_PROVISIONED evidence from the riskiest loss: the resource
        # with the least post-failure headroom motivates the verdict.
        risky = max((o for o in report.outcomes if o.violated_hard_goals),
                    key=lambda o: o.risk)
        tightest = min(
            (k for k in RESOURCE_KEYS
             if risky.headroom.get(k, {}).get("minBrokerFrac") is not None),
            key=lambda k: risky.headroom[k]["minBrokerFrac"],
            default=None)
        rec = ProvisionRecommendation(
            ProvisionStatus.UNDER_PROVISIONED,
            num_brokers=1,
            resource=tightest,
            reason=(f"N-1 sweep: losing broker "
                    f"{risky.scenario.brokers[0]} violates "
                    f"{risky.violated_hard_goals} "
                    f"(risk {risky.risk:.2f})"),
            headroom={
                "scenario": risky.scenario.name,
                "capacityPressure": round(risky.capacity_pressure, 4),
                "perResource": risky.headroom,
            })
        LOG.warning("resilience sweep: %d/%d single-broker losses violate "
                    "hard goals (worst: %s, risk %.2f)",
                    len(at_risk), len(ids), risky.scenario.name, risky.risk)
        return [BrokerRisk(detected_ms=now_ms, at_risk=at_risk,
                           recommendation=rec, max_risk=worst.risk)]
