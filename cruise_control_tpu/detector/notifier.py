"""Anomaly notifiers — the self-healing policy layer (ref
``detector/notifier/AnomalyNotifier.java`` SPI and
``SelfHealingNotifier.java:59``).

For each anomaly the notifier decides FIX (self-heal now), CHECK (re-queue
and look again later), or IGNORE. The stock policy for broker failures:
alert after ``broker_failure_alert_threshold_ms`` (default 15 min,
``:69``), auto-fix after ``self_healing_threshold_ms`` (default 30 min,
``:70``) — grace for transient bounces. Webhook-style notifiers mirror the
Slack/MS Teams/Alerta integrations as a pluggable sink callable.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable

from .anomalies import (BrokerFailures, GoalViolations, KafkaAnomaly,
                        KafkaAnomalyType)


class AnomalyNotificationResult(enum.Enum):
    """ref AnomalyNotificationResult."""

    FIX = "FIX"
    CHECK = "CHECK"
    IGNORE = "IGNORE"


@dataclass
class NotificationAction:
    result: AnomalyNotificationResult
    delay_ms: int = 0


class AnomalyNotifier:
    """SPI (ref AnomalyNotifier.java:107)."""

    def on_anomaly(self, anomaly: KafkaAnomaly,
                   now_ms: int) -> NotificationAction:
        raise NotImplementedError

    def self_healing_enabled(self) -> dict[KafkaAnomalyType, bool]:
        raise NotImplementedError


class SelfHealingNotifier(AnomalyNotifier):
    """ref SelfHealingNotifier.java:59."""

    BROKER_FAILURE_ALERT_THRESHOLD_MS = 15 * 60 * 1000   # ref :69
    BROKER_FAILURE_SELF_HEALING_THRESHOLD_MS = 30 * 60 * 1000   # ref :70

    def __init__(self, *, alert_threshold_ms: int | None = None,
                 self_healing_threshold_ms: int | None = None,
                 enabled: dict[KafkaAnomalyType, bool] | None = None,
                 alert_sink: Callable[[str, bool], None] | None = None):
        self.alert_threshold_ms = (
            self.BROKER_FAILURE_ALERT_THRESHOLD_MS
            if alert_threshold_ms is None else alert_threshold_ms)
        self.self_healing_threshold_ms = (
            self.BROKER_FAILURE_SELF_HEALING_THRESHOLD_MS
            if self_healing_threshold_ms is None else self_healing_threshold_ms)
        self._enabled = {t: True for t in KafkaAnomalyType}
        if enabled:
            self._enabled.update(enabled)
        #: called with (message, is_autofix) — the Slack/Teams webhook slot
        self.alert_sink = alert_sink or (lambda msg, autofix: None)
        self.alerts: list[str] = []

    def self_healing_enabled(self) -> dict[KafkaAnomalyType, bool]:
        return dict(self._enabled)

    def set_self_healing_for(self, anomaly_type: KafkaAnomalyType,
                             value: bool) -> None:
        self._enabled[anomaly_type] = value

    def _alert(self, message: str, autofix: bool) -> None:
        self.alerts.append(message)
        self.alert_sink(message, autofix)

    def on_anomaly(self, anomaly: KafkaAnomaly,
                   now_ms: int) -> NotificationAction:
        atype = anomaly.anomaly_type
        if isinstance(anomaly, BrokerFailures):
            return self._on_broker_failure(anomaly, now_ms)
        if atype is KafkaAnomalyType.FLEET_MEMBER_QUARANTINED:
            # Alert-only regardless of the enabled map: the member's data
            # plane may be perfectly healthy behind an unreachable
            # endpoint — there is nothing a local fix could move, and the
            # registry's own readmission probes are the recovery path.
            self._alert(f"{atype.name}: {anomaly.reason()}", False)
            return NotificationAction(AnomalyNotificationResult.IGNORE)
        if not self._enabled.get(atype, False):
            self._alert(f"{atype.name}: {anomaly.reason()} "
                        "(self-healing disabled)", False)
            return NotificationAction(AnomalyNotificationResult.IGNORE)
        if atype is KafkaAnomalyType.METRIC_ANOMALY and not hasattr(
                anomaly, "slow_brokers"):
            # Plain metric anomalies alert only (ref onMetricAnomaly).
            self._alert(f"METRIC_ANOMALY: {anomaly.reason()}", False)
            return NotificationAction(AnomalyNotificationResult.IGNORE)
        if (isinstance(anomaly, GoalViolations)
                and not anomaly.fixable_violations):
            # Nothing self-healing can do; alert + gauge territory (ref
            # onGoalViolation only fixes when there are fixable goals).
            self._alert(f"GOAL_VIOLATION (unfixable): {anomaly.reason()}",
                        False)
            return NotificationAction(AnomalyNotificationResult.IGNORE)
        self._alert(f"{atype.name}: {anomaly.reason()} (self-healing)", True)
        return NotificationAction(AnomalyNotificationResult.FIX)

    def _on_broker_failure(self, anomaly: BrokerFailures,
                           now_ms: int) -> NotificationAction:
        """Graduated response (ref onBrokerFailure): wait, then alert, then
        auto-fix once the oldest failure crosses the threshold."""
        if not anomaly.failed_brokers:
            return NotificationAction(AnomalyNotificationResult.IGNORE)
        earliest = min(anomaly.failed_brokers.values())
        alert_at = earliest + self.alert_threshold_ms
        fix_at = earliest + self.self_healing_threshold_ms
        if now_ms < alert_at:
            return NotificationAction(AnomalyNotificationResult.CHECK,
                                      delay_ms=alert_at - now_ms)
        if now_ms < fix_at:
            self._alert(f"BROKER_FAILURE: {anomaly.reason()}", False)
            if not self._enabled.get(KafkaAnomalyType.BROKER_FAILURE, False):
                return NotificationAction(AnomalyNotificationResult.IGNORE)
            return NotificationAction(AnomalyNotificationResult.CHECK,
                                      delay_ms=fix_at - now_ms)
        if not self._enabled.get(KafkaAnomalyType.BROKER_FAILURE, False):
            self._alert(f"BROKER_FAILURE: {anomaly.reason()} "
                        "(self-healing disabled)", False)
            return NotificationAction(AnomalyNotificationResult.IGNORE)
        self._alert(f"BROKER_FAILURE: {anomaly.reason()} (auto-fix)", True)
        return NotificationAction(AnomalyNotificationResult.FIX)


class WebhookSelfHealingNotifier(SelfHealingNotifier):
    """SelfHealingNotifier that also posts every alert to an HTTP webhook.

    Base for the Slack / MS Teams / Alerta integrations (ref
    ``SlackSelfHealingNotifier.java``, ``MSTeamsSelfHealingNotifier.java``,
    ``AlertaSelfHealingNotifier.java`` — all of which are exactly
    SelfHealingNotifier plus a JSON POST per alert). ``http_post(url,
    payload_dict)`` is injectable for tests; delivery failures are recorded,
    never raised (an unreachable webhook must not stall the anomaly loop).
    """

    def __init__(self, webhook_url: str, *,
                 http_post: Callable[[str, dict], None] | None = None,
                 extra_headers: dict[str, str] | None = None,
                 **kwargs):
        super().__init__(**kwargs)
        self.webhook_url = webhook_url
        self._extra_headers = extra_headers or {}
        self._http_post = http_post or self._default_post
        self.delivery_errors: list[str] = []

    def _alert(self, message: str, autofix: bool) -> None:
        # Overrides (not wraps) the base hook so reassigning the public
        # alert_sink slot can't silently detach webhook delivery.
        super()._alert(message, autofix)
        try:
            self._http_post(self.webhook_url, self.payload(message, autofix))
        except Exception as e:   # noqa: BLE001 — alerting must not stall
            self.delivery_errors.append(f"{type(e).__name__}: {e}")

    def _default_post(self, url: str, payload: dict) -> None:
        import json
        import urllib.request
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json",
                     **self._extra_headers})
        with urllib.request.urlopen(req, timeout=10.0) as resp:
            resp.read()

    def payload(self, message: str, autofix: bool) -> dict:
        raise NotImplementedError


class SlackSelfHealingNotifier(WebhookSelfHealingNotifier):
    """ref SlackSelfHealingNotifier.java — incoming-webhook message."""

    def __init__(self, webhook_url: str, *, channel: str | None = None,
                 icon: str = ":information_source:",
                 user: str = "cruise-control", **kwargs):
        super().__init__(webhook_url, **kwargs)
        self.channel = channel
        self.icon = icon
        self.user = user

    def payload(self, message: str, autofix: bool) -> dict:
        p = {"text": message, "icon_emoji": self.icon, "username": self.user}
        if self.channel:
            p["channel"] = self.channel
        return p


class MSTeamsSelfHealingNotifier(WebhookSelfHealingNotifier):
    """ref MSTeamsSelfHealingNotifier.java — MessageCard payload."""

    def payload(self, message: str, autofix: bool) -> dict:
        return {"@type": "MessageCard", "@context": "https://schema.org/extensions",
                "themeColor": "D00000" if autofix else "E8A33D",
                "summary": "Cruise Control anomaly",
                "text": message}


class AlertaSelfHealingNotifier(WebhookSelfHealingNotifier):
    """ref AlertaSelfHealingNotifier.java + AlertaMessage.java — alerta.io
    alert API; ``api_key`` goes into the Authorization header via a custom
    poster when set."""

    def __init__(self, api_url: str, *, environment: str = "production",
                 origin: str = "cruise-control", api_key: str | None = None,
                 **kwargs):
        if api_key:
            kwargs.setdefault("extra_headers",
                              {"Authorization": f"Key {api_key}"})
        super().__init__(api_url.rstrip("/") + "/alert", **kwargs)
        self.environment = environment
        self.origin = origin

    def payload(self, message: str, autofix: bool) -> dict:
        return {"resource": "kafka-cluster", "event": message.split(":")[0],
                "severity": "critical" if autofix else "warning",
                "environment": self.environment, "origin": self.origin,
                "service": ["cruise-control"], "text": message}
