"""Detector layer (L5): anomaly detection + self-healing (ref
``cruise-control/.../detector/``)."""

from .anomalies import (BrokerFailures, DiskFailures, GoalViolations,
                        KafkaAnomaly, KafkaAnomalyType, KafkaMetricAnomaly,
                        MaintenanceEvent, MaintenanceEventType, SlowBrokers,
                        TopicReplicationFactorAnomaly)
from .detectors import (BalancednessWeights, BrokerFailureDetector,
                        DiskFailureDetector, GoalViolationDetector,
                        MaintenanceEventDetector, MaintenanceEventReader,
                        MetricAnomalyDetector, SlowBrokerFinder,
                        TopicAnomalyDetector)
from .manager import AnomalyDetectorManager, DetectorSchedule
from .notifier import (AnomalyNotificationResult, AnomalyNotifier,
                       NotificationAction, SelfHealingNotifier)
from .provisioner import (BasicProvisioner, Provisioner,
                          ProvisionRecommendation, ProvisionResponse,
                          ProvisionStatus)

__all__ = [
    "BrokerFailures", "DiskFailures", "GoalViolations", "KafkaAnomaly",
    "KafkaAnomalyType", "KafkaMetricAnomaly", "MaintenanceEvent",
    "MaintenanceEventType", "SlowBrokers", "TopicReplicationFactorAnomaly",
    "BalancednessWeights", "BrokerFailureDetector", "DiskFailureDetector",
    "GoalViolationDetector", "MaintenanceEventDetector",
    "MaintenanceEventReader", "MetricAnomalyDetector", "SlowBrokerFinder",
    "TopicAnomalyDetector", "AnomalyDetectorManager", "DetectorSchedule",
    "AnomalyNotificationResult", "AnomalyNotifier", "NotificationAction",
    "SelfHealingNotifier", "BasicProvisioner", "Provisioner",
    "ProvisionRecommendation", "ProvisionResponse", "ProvisionStatus",
]
