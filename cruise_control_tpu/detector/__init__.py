"""Detector layer (L5): anomaly detection + self-healing (ref
``cruise-control/.../detector/``)."""

from .anomalies import (BrokerFailures, BrokerRisk, CapacityForecast,
                        DiskFailures, GoalViolations, KafkaAnomaly,
                        KafkaAnomalyType, KafkaMetricAnomaly,
                        MaintenanceEvent, MaintenanceEventType,
                        SlowBrokers, TopicReplicationFactorAnomaly)
from .detectors import (BalancednessWeights, BrokerFailureDetector,
                        DiskFailureDetector, GoalViolationDetector,
                        MaintenanceEventDetector, MaintenanceEventReader,
                        MetricAnomalyDetector, SlowBrokerFinder,
                        TopicAnomalyDetector)
from .manager import AnomalyDetectorManager, DetectorSchedule
from .resilience import ResilienceDetector
from .notifier import (AlertaSelfHealingNotifier, AnomalyNotificationResult,
                       AnomalyNotifier, MSTeamsSelfHealingNotifier,
                       NotificationAction, SelfHealingNotifier,
                       SlackSelfHealingNotifier, WebhookSelfHealingNotifier)
from .provisioner import (BasicProvisioner, Provisioner,
                          ProvisionRecommendation, ProvisionResponse,
                          ProvisionStatus)

__all__ = [
    "BrokerFailures", "BrokerRisk", "CapacityForecast",
    "ResilienceDetector",
    "DiskFailures", "GoalViolations", "KafkaAnomaly",
    "KafkaAnomalyType", "KafkaMetricAnomaly", "MaintenanceEvent",
    "MaintenanceEventType", "SlowBrokers", "TopicReplicationFactorAnomaly",
    "BalancednessWeights", "BrokerFailureDetector", "DiskFailureDetector",
    "GoalViolationDetector", "MaintenanceEventDetector",
    "MaintenanceEventReader", "MetricAnomalyDetector", "SlowBrokerFinder",
    "TopicAnomalyDetector", "AnomalyDetectorManager", "DetectorSchedule",
    "AnomalyNotificationResult", "AnomalyNotifier", "NotificationAction",
    "SelfHealingNotifier", "WebhookSelfHealingNotifier",
    "SlackSelfHealingNotifier", "MSTeamsSelfHealingNotifier",
    "AlertaSelfHealingNotifier", "BasicProvisioner", "Provisioner",
    "ProvisionRecommendation", "ProvisionResponse", "ProvisionStatus",
]
