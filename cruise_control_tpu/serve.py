"""Server entrypoint: build the whole stack from a properties file and run
(ref ``KafkaCruiseControlMain.java`` + ``KafkaCruiseControlApp``).

``python -m cruise_control_tpu.serve --config cruisecontrol.properties``

With no real Kafka in reach, the default admin backend is a demo
:class:`SimulatedKafkaCluster` (size via ``--demo-brokers/partitions``);
pointing at a real cluster means providing an object implementing
:class:`~cruise_control_tpu.executor.admin.ClusterAdminClient` via
``admin.client.class`` (plugin-loaded, reference-style).
"""

from __future__ import annotations

import argparse
import signal
import sys
import time

from .analyzer import TpuGoalOptimizer, goals_by_name
from .api import CruiseControlApp, KafkaCruiseControl
from .api.security import BasicSecurityProvider, Role
from .config.brokersets import FileBrokerSetResolver
from .config.capacity import FileCapacityResolver, FixedCapacityResolver
from .config.constants import CruiseControlConfig
from .core.config import load_class, load_properties_file
from .model.cpu_regression import LinearRegressionModelParameters
from .detector import (AnomalyDetectorManager, BalancednessWeights,
                       BrokerFailureDetector, DiskFailureDetector,
                       GoalViolationDetector, KafkaAnomalyType,
                       MaintenanceEventDetector, MetricAnomalyDetector,
                       ResilienceDetector,
                       SelfHealingNotifier, SlowBrokerFinder,
                       TopicAnomalyDetector)
from .executor import Executor, SimulatedKafkaCluster
from .monitor import (FileSampleStore, LoadMonitor, LoadMonitorTaskRunner,
                      MetricFetcherManager, NoopSampleStore,
                      SyntheticWorkloadSampler)


def build_app(config: CruiseControlConfig, admin=None) -> CruiseControlApp:
    """Constructor wiring, ref KafkaCruiseControl.java:112-129."""
    if admin is None:
        admin = _make_admin(config)
    cap_file = config.get_string("capacity.config.file")
    resolver = (FileCapacityResolver(cap_file) if cap_file
                else FixedCapacityResolver())
    bset_file = config.get_string("broker.set.config.file")
    broker_set_resolver = (FileBrokerSetResolver(bset_file) if bset_file
                           else None)
    # The mesh is resolved before the monitor so model BUILDS upload
    # partition-axis shards from the start (resident state + optimizer +
    # what-if all consume the same layout). -1 = all visible devices.
    mesh = None
    mesh_devices = config.get_int("search.mesh.devices")
    if mesh_devices:
        from .parallel import make_mesh, resolve_mesh_devices
        mesh = make_mesh(resolve_mesh_devices(mesh_devices))
        # Re-check even sharding with the RESOLVED device count (the
        # parse-time check covers explicit N; -1 resolves only here).
        from .core.config import ConfigException
        from .model.spec import check_even_sharding
        check_even_sharding(
            config.get_int("model.partition.pad.multiple"),
            int(mesh.devices.size),
            what="model.partition.pad.multiple", exc=ConfigException)
    # Padding/HBM budgets land on the process-default device-stats
    # collector (0 = unenforced): breaches warn + flag /devicestats.
    from .core.runtime_obs import default_collector
    default_collector().set_budgets(
        padding_waste_pct=config.get_double(
            "device.padding.waste.budget.pct"),
        hbm_bytes=config.get_int("device.hbm.budget.bytes"))
    monitor = LoadMonitor(admin, config.monitor_config(),
                          capacity_resolver=resolver,
                          broker_set_resolver=broker_set_resolver,
                          admin_retry=config.executor_config().admin_retry,
                          mesh=mesh)
    store_dir = config.get_string("sample.store.dir")
    store = FileSampleStore(store_dir) if store_dir else NoopSampleStore()
    cpu_model = LinearRegressionModelParameters()
    sampler = _make_sampler(config, admin, cpu_model)
    on_exec_store = None
    if config.get_string("sample.partition.metric.store.on.execution.class"):
        # ref KafkaPartitionMetricSampleOnExecutionStore: keep execution-
        # window samples separately (file-backed beside the main store).
        import os as _os
        on_exec_dir = _os.path.join(store_dir or ".", "on_execution")
    fetcher = MetricFetcherManager(
        sampler, config.get_int("num.metric.fetchers"), store=store,
        assignor=load_class(config.get_string(
            "metric.sampler.partition.assignor.class"))(),
        max_retries=config.get_int("fetch.metric.samples.max.retry.count"))
    runner = LoadMonitorTaskRunner(
        monitor, fetcher,
        sampling_interval_ms=config.get_int("metric.sampling.interval.ms"))
    constraint = config.balancing_constraint()
    goal_names = config.get_list("default.goals")
    branches = config.get_int("search.branches")
    if branches > 1:
        import jax
        branches = min(branches, len(jax.devices()))
    # Tuned search schedules (search.tuning.*): per-shape-bucket
    # SearchConfig overrides persisted by offline tuning runs (bench.py
    # --scenario 7), loaded ONCE at construction so warm serving picks
    # up tuned schedules with zero recompiles within a bucket.
    tuned_store = None
    if config.get_boolean("search.tuning.enabled"):
        from .analyzer import TunedConfigStore
        tuned_store = TunedConfigStore(
            config.get_string("search.tuning.store.path") or None)
    optimizer = TpuGoalOptimizer(
        goals=goals_by_name(goal_names, constraint) if goal_names else None,
        constraint=constraint, config=config.search_config(), mesh=mesh,
        branches=branches,
        # Multi-objective population search (search.population.*):
        # parse-time exclusivity vs branches/mesh/fleet already held.
        population=config.population_config(),
        tuned_store=tuned_store,
        # ref hard.goals: the registered hard-goal set every optimization
        # is audited against post-run regardless of chain membership.
        hard_goal_names=config.get_list("hard.goals") or None)
    executor = Executor(admin, config.executor_config())
    from .analyzer import DefaultOptimizationOptionsGenerator
    gen_cls = load_class(config.get_string(
        "optimization.options.generator.class"))
    excl = config.get_string("topics.excluded.from.partition.movement")
    if issubclass(gen_cls, DefaultOptimizationOptionsGenerator):
        # The default (and subclasses inheriting its __init__) take the
        # always-excluded pattern — never the config object, which its
        # pattern parameter would silently swallow.
        options_generator = gen_cls(excl or None)
    else:
        # Signature-based dispatch: a try/except TypeError would mask
        # genuine TypeErrors raised inside a plugin's constructor body.
        import inspect
        params = inspect.signature(gen_cls).parameters
        options_generator = gen_cls(config) if params else gen_cls()
    if config.get_string("sample.partition.metric.store.on.execution.class"):
        from .monitor.store import OnExecutionSampleStore
        fetcher.on_execution_store = OnExecutionSampleStore(
            FileSampleStore(on_exec_dir), executor.has_ongoing_execution)
    fleet_enabled = config.get_boolean("fleet.enabled")
    facade = KafkaCruiseControl(admin, monitor, task_runner=runner,
                                optimizer=optimizer, executor=executor,
                                options_generator=options_generator,
                                cpu_model=cpu_model,
                                admin_retry=executor.config.admin_retry,
                                cluster_id=(config.get_string(
                                    "fleet.cluster.id")
                                    if fleet_enabled else None))
    if fleet_enabled:
        # Fleet control plane: the local stack is the first member (its
        # monitor + cluster-scoped proposal cache); every
        # fleet.member.<id>.endpoint key adds a remote member whose
        # admin rides a RemoteBackend failure domain (per-call deadline
        # + retry + circuit breaker — docs/fleet.md §Failure domains).
        # One batched [C] dispatch per tick refreshes every stale member
        # cache; the tick loop starts in main() alongside the facade's
        # own refresher.
        from .core.retry import NO_RETRY
        from .fleet import (FleetRegistry, MoveBudgetCoordinator,
                            RemoteBackend)
        budget = None
        if config.get_int("fleet.move.budget.per.tick") > 0:
            budget = MoveBudgetCoordinator(
                budget_per_tick=config.get_int("fleet.move.budget.per.tick"),
                carry_max_ticks=config.get_int("fleet.budget.carry.max.ticks"),
                journal=facade.journal)
        facade.fleet = FleetRegistry(
            optimizer,
            max_clusters=config.get_int("fleet.max.clusters"),
            quarantine_after=config.get_int("fleet.quarantine.after.ticks"),
            fetch_workers=config.get_int("fleet.fetch.workers"),
            fetch_deadline_ms=config.get_long("fleet.fetch.deadline.ms"),
            breaker_window_ms=config.get_long("fleet.breaker.window.ms"),
            breaker_failures=config.get_int("fleet.breaker.failures"),
            breaker_open_ms=config.get_long("fleet.breaker.open.ms"),
            journal=facade.journal, budget=budget)
        facade.fleet.register(
            config.get_string("fleet.cluster.id"), monitor,
            proposal_cache=facade.proposal_cache)
        call_deadline = config.get_long("fleet.call.deadline.ms")
        for mid, ep in FleetRegistry.member_endpoints(config).items():
            # Each remote member gets its own admin client (the
            # admin.client.class plugin in real deployments, a demo sim
            # otherwise) behind a RemoteBackend carrying the member's
            # endpoint — its breaker doubles as the health-machine
            # breaker, so backend call failures and fleet-tick fetch
            # failures share one rolling window.
            backend = RemoteBackend(
                mid, _make_admin(config), endpoint=ep,
                retry=executor.config.admin_retry or NO_RETRY,
                call_deadline_ms=call_deadline)
            facade.fleet.register(
                mid, LoadMonitor(backend, config.monitor_config(),
                                 capacity_resolver=resolver,
                                 admin_retry=None),
                backend=backend)

    # Control-plane flight recorder (core/events.py; docs/observability.md
    # §Flight recorder): reconfigure the facade-built journal from the
    # events.* keys and reload any persisted segment BEFORE the decision
    # points start firing, so post-restart /history still shows the
    # pre-crash tail.
    facade.journal.configure(
        enabled=config.get_boolean("events.enabled"),
        capacity=config.get_int("events.ring.capacity"),
        segment_path=config.get_string("events.segment.path"),
        rotate_bytes=config.get_long("events.segment.rotate.bytes"),
        persist_interval_ms=config.get_long("events.persist.interval.ms"),
        categories=config.get_list("events.categories") or None)
    if facade.journal.segment_path:
        facade.journal.restore_from_disk()

    # Crash-safe snapshots + warm-standby HA (docs/operations.md
    # §Snapshot/restore & HA): the manager restores in start_up (before
    # prewarm) and writes on the ha_tick cadence in main(); the elector
    # fences the executor under its epoch.
    # Heavy-traffic read tier (docs/operations.md §Serving-tier
    # tuning): a positive TTL opts the live-value endpoints into the
    # render-cache micro-cache window; pure-function endpoints
    # (/proposals, the explorer) are cached regardless.
    rc_ttl = config.get_long("webserver.rendercache.ttl.ms")
    if rc_ttl > 0:
        facade.rendercache.enable(ttl_ms=rc_ttl)

    snap_path = config.get_string("snapshot.path")
    if snap_path:
        from .core.snapshot import SnapshotManager
        facade.attach_snapshotter(SnapshotManager(
            snap_path,
            interval_ms=config.get_long("snapshot.interval.ms"),
            max_age_ms=config.get_long("snapshot.max.age.ms")))
    if config.get_boolean("ha.enabled"):
        import os as _os
        import socket as _socket

        from .core.leader import LeaderElector
        identity = config.get_string("ha.identity") or (
            f"{_socket.gethostname()}:"
            f"{config.get_int('webserver.http.port')}-{_os.getpid()}")
        facade.attach_elector(LeaderElector(
            admin, identity, lease_ms=config.get_long("ha.lease.ms"),
            # replication.replica.promotable=false pins a pure read
            # replica: its elector observes but never takes the lease.
            eligible=config.get_boolean("replication.replica.promotable")))
        # Snapshot-delta streaming to read replicas (core/replication.py;
        # docs/operations.md §Replication): the leader publishes resident
        # deltas into the local ring (served at /replication_stream);
        # with a peer endpoint configured this node follows it while
        # standing by. Full snapshots stay the bootstrap/RESYNC path, so
        # snapshot.path is required.
        if config.get_boolean("replication.enabled"):
            if not snap_path:
                raise ValueError(
                    "replication.enabled requires snapshot.path (full "
                    "snapshots are the bootstrap/resync path)")
            from .core.replication import (DualChannel,
                                           HttpReplicationClient,
                                           ReplicationChannel)
            ring = ReplicationChannel(
                capacity=config.get_int("replication.buffer.frames"),
                compress_min_bytes=config.get_int(
                    "replication.compress.min.bytes"))
            channel = ring
            peer = config.get_string("replication.leader.endpoint")
            if peer:
                peer_host, _, peer_port = peer.rpartition(":")
                channel = DualChannel(ring, HttpReplicationClient(
                    peer_host or "127.0.0.1", int(peer_port)))
            facade.attach_replication_channel(
                channel, node_id=identity,
                max_staleness_ms=config.get_long(
                    "replication.max.staleness.ms"),
                poll_wait_ms=config.get_long("replication.poll.wait.ms"),
                coalesce_ms=config.get_long("replication.coalesce.ms"))
    elif config.get_boolean("replication.enabled"):
        raise ValueError("replication.enabled requires ha.enabled (the "
                         "stream's roles come from the leader elector)")

    # ref self.healing.goals + the reference's startup sanity check
    # (KafkaCruiseControlConfig sanityCheckGoalNames): a configured
    # self-healing chain must cover every registered hard goal, or fixes
    # would fail the hard-goal gate at 3am instead of failing the config
    # at deploy time.
    from .analyzer.goals import short_goal_name
    healing_goals = [short_goal_name(n)
                     for n in config.get_list("self.healing.goals")]
    if healing_goals:
        # Resolve the names NOW: an unknown/misspelled healing goal must
        # fail the deploy, not the first 3am fix() call.
        goals_by_name(healing_goals, constraint)
        from .analyzer.goals import HARD_GOAL_ALTERNATIVES
        from .analyzer.goals import default_goals as _default_goals
        hard_names = {short_goal_name(n)
                      for n in (optimizer.hard_goal_names
                                or [g.name for g in _default_goals()
                                    if g.hard])}
        present = set(healing_goals)
        missing = {n for n in hard_names - present
                   # A documented relaxation in the chain satisfies the
                   # strict form (same rule the hard-goal audit applies).
                   if not any(a in present
                              for a in HARD_GOAL_ALTERNATIVES.get(n, ()))}
        if missing:
            raise ValueError(
                f"self.healing.goals must include every registered hard "
                f"goal (hard.goals); missing: {sorted(missing)}")
        facade.self_healing_goals = healing_goals
    facade.rf_self_healing_skip_rack_check = config.get_boolean(
        "replication.factor.self.healing.skip.rack.awareness.check")

    healing_on = config.get_boolean("self.healing.enabled")

    def healing_for(t: KafkaAnomalyType) -> bool:
        # An explicitly-set per-type key overrides the master switch (ref
        # SelfHealingNotifier per-type config resolution); otherwise the
        # master value applies.
        key = f"self.healing.{t.name.lower().replace('_', '.')}.enabled"
        if key in config.originals():
            return config.get_boolean(key)
        return healing_on

    notifier = _make_notifier(
        config,
        alert_threshold_ms=config.get_int("broker.failure.alert.threshold.ms"),
        self_healing_threshold_ms=config.get_int(
            "broker.failure.self.healing.threshold.ms"),
        enabled={t: healing_for(t) for t in KafkaAnomalyType})
    if facade.fleet is not None:
        # Built before the notifier existed: quarantine anomalies
        # (FLEET_MEMBER_QUARANTINED, alert-only) route through it.
        facade.fleet.notifier = notifier
    detector = AnomalyDetectorManager(
        facade, notifier,
        fixable_broker_count_threshold=config.get_int(
            "fixable.failed.broker.count.threshold"),
        fixable_broker_pct_threshold=config.get_double(
            "fixable.failed.broker.percentage.threshold"),
        num_cached_recent_anomalies=config.get_int(
            "num.cached.recent.anomaly.states"),
        provisioner_enabled=config.get_boolean("provisioner.enable"))
    interval = config.get_int("anomaly.detection.interval.ms")
    detector.register(
        BrokerFailureDetector(
            admin, persist_path=config.get_string("failed.brokers.file.path")),
        config.get_int("broker.failure.detection.interval.ms"))
    detector.register(DiskFailureDetector(admin),
                      config.get_int("disk.failure.detection.interval.ms"))
    # ref anomaly.detection.goals (default: the 4 leading hard goals,
    # AnomalyDetectorConfig.java:101): the violation detector dry-runs
    # THIS chain. With a distribution-threshold multiplier != 1 (ref
    # goal.violation.distribution.threshold.multiplier) the detection
    # optimizer gets its own RELAXED constraint so detection only fires
    # beyond the relaxed band (anti-flap); otherwise the goal-scoped
    # optimizer is memoized on the facade so compiled passes are shared
    # with same-goal user requests.
    det_goals = config.get_list("anomaly.detection.goals")
    det_mult = config.get_double(
        "goal.violation.distribution.threshold.multiplier")
    if det_mult != 1.0:
        # Routed through the facade's memoized builder so the detection
        # optimizer inherits the options generator (topic exclusions must
        # bind detection too), mesh, branches, and registered hard goals;
        # an empty detection-goal list falls back to the SERVING chain
        # (relaxed), exactly like the multiplier-free branch below.
        det_optimizer = facade._optimizer_for(
            det_goals or goal_names or None,
            constraint=constraint.for_goal_violation_detection(det_mult))
    elif det_goals:
        det_optimizer = facade._optimizer_for(det_goals)
    else:
        det_optimizer = optimizer
    detector.register(
        GoalViolationDetector(monitor, det_optimizer,
                              weights=BalancednessWeights(
            priority_weight=config.get_double(
                "goal.balancedness.priority.weight"),
            strictness_weight=config.get_double(
                "goal.balancedness.strictness.weight"))),
        config.get_int("goal.violation.detection.interval.ms"))
    detector.register(MetricAnomalyDetector(monitor),
                      config.get_int("metric.anomaly.detection.interval.ms"))
    detector.register(SlowBrokerFinder(
        monitor, remove_slow_brokers=config.get_boolean(
            "slow.broker.removal.enabled")), interval)
    detector.register(TopicAnomalyDetector(
        admin, target_rf=config.get_int(
            "topic.anomaly.target.replication.factor")),
        config.get_int("topic.anomaly.detection.interval.ms"))
    # Proactive N-1 resilience sweep (whatif engine, shared with
    # /simulate so the compiled sweep program is paid for once). 0
    # disables it.
    # The scenario cap guards /simulate too, so it applies regardless of
    # whether the resilience detector is enabled.
    facade.whatif.max_scenarios = config.get_int("whatif.max.scenarios")
    resilience_interval = config.get_int("resilience.detection.interval.ms")
    if resilience_interval > 0:
        detector.register(
            ResilienceDetector(monitor, facade.whatif,
                               registry=detector.registry),
            resilience_interval)
    # Forecast engine + proactive capacity provisioning (forecast/;
    # docs/forecasting.md): reconfigure the facade's engine from the
    # forecast.* keys, wire the persistence store (fitted models restart
    # warm, next to the tuned-config store), and schedule the
    # capacity-forecast detector on its interval.
    forecast_cfg = config.forecast_config()
    facade.forecast.config = forecast_cfg
    if forecast_cfg.enabled:
        from .forecast import CapacityForecastDetector, ForecastStore
        facade.forecast.store = ForecastStore(
            config.get_string("forecast.store.path") or None)
        persisted = facade.forecast.store.load()
        if persisted is not None and facade.forecast.last_fit is None:
            facade.forecast.last_fit = persisted
        if forecast_cfg.interval_ms > 0:
            detector.register(
                CapacityForecastDetector(monitor, facade.forecast,
                                         registry=detector.registry),
                forecast_cfg.interval_ms)
    # Regime-aware continuous tuning (workload/regime.py;
    # docs/workloads.md §Regime loop): classify the traffic regime off
    # the aggregated window series each detector round and re-resolve
    # the optimizer's tuned schedule per (shape bucket, regime) on
    # shift. Serving-path default is incumbent-pinning (trials=0 — no
    # per-candidate compiles); offline runs (bench --scenario 14) fill
    # the store with genuinely tuned per-regime schedules.
    if config.get_boolean("tuning.regime.enabled"):
        from .workload import RegimeShiftDetector, RegimeTuningLoop
        if optimizer.tuned_store is None:
            from .analyzer import TunedConfigStore
            optimizer.tuned_store = TunedConfigStore(
                config.get_string("search.tuning.store.path") or None)
        detector.register(
            RegimeShiftDetector(
                monitor,
                RegimeTuningLoop(optimizer, optimizer.tuned_store,
                                 config.regime_detector()),
                registry=detector.registry),
            interval)
    # ref maintenance.event.reader.class (empty = maintenance events
    # disabled, the reference default): the reader drains operator-
    # announced plans with idempotence de-dup; MaintenanceEvent.fix reads
    # facade.maintenance_stop_ongoing for the stop-then-execute option.
    reader_cls_name = config.get_string("maintenance.event.reader.class")
    if reader_cls_name:
        reader_cls = load_class(reader_cls_name)
        # Signature-based dispatch, like the options-generator plugin
        # above: a try/except TypeError would mask genuine TypeErrors
        # raised inside a plugin's constructor body.
        import inspect
        sig = inspect.signature(reader_cls)
        if "enable_idempotence" in sig.parameters:
            reader = reader_cls(
                enable_idempotence=config.get_boolean(
                    "maintenance.event.enable.idempotence"),
                idempotence_retention_ms=config.get_int(
                    "maintenance.event.idempotence.retention.ms"),
                max_idempotence_cache_size=config.get_int(
                    "maintenance.event.max.idempotence.cache.size"))
        elif sig.parameters:
            reader = reader_cls(config)
        else:
            reader = reader_cls()
        facade.maintenance_event_reader = reader
        detector.register(MaintenanceEventDetector(reader), interval)
    facade.maintenance_stop_ongoing = config.get_boolean(
        "maintenance.event.stop.ongoing.execution")
    # Burn-rate SLO evaluator (core/slo.py; docs/observability.md §SLO
    # burn rates): samples the freshness signals on both the detector
    # loop (leader) and ha_tick (standbys — they run no detector loop
    # but still need standby-staleness alerts); breaches journal slo
    # events and raise the alert-only SLO_BREACH anomaly.
    if config.get_boolean("slo.enabled"):
        from .core.slo import SLOEvaluator
        slo = SLOEvaluator(
            journal=facade.journal,
            fast_window_ms=config.get_long("slo.fast.window.ms"),
            slow_window_ms=config.get_long("slo.slow.window.ms"),
            fast_burn_threshold=config.get_double("slo.fast.burn.threshold"),
            slow_burn_threshold=config.get_double("slo.slow.burn.threshold"),
            interval_ms=config.get_long("slo.evaluation.interval.ms"))
        slo.add_objective(
            "proposal-freshness",
            lambda: facade.proposal_cache.freshness_age_ms(facade._now_ms()),
            config.get_long("slo.proposal.freshness.target.ms"))
        slo.add_objective(
            "replication-stream-lag",
            lambda: (facade.replication.stream_lag_ms
                     if facade.replication is not None else None),
            config.get_long("slo.replication.lag.target.ms"))
        slo.add_objective(
            "standby-staleness",
            lambda: (facade.snapshotter._last_staleness_ms
                     if facade.snapshotter is not None
                     and facade.ha_role() != "leader" else None),
            config.get_long("slo.standby.staleness.target.ms"))
        facade.slo = slo
        facade.extra_registries.append(slo.registry)
        detector.register(slo, config.get_long("slo.evaluation.interval.ms"))
    facade.detector = detector

    security = None
    if config.get_boolean("webserver.security.enable"):
        security = _make_security(config)
    cors = None
    if config.get_boolean("webserver.http.cors.enabled"):
        cors = {
            "Access-Control-Allow-Origin":
                config.get_string("webserver.http.cors.origin"),
            "Access-Control-Allow-Methods":
                config.get_string("webserver.http.cors.allowmethods"),
            "Access-Control-Expose-Headers":
                config.get_string("webserver.http.cors.exposeheaders"),
            # Request headers the async protocol needs on preflight —
            # without this a browser POST carrying User-Task-ID fails
            # CORS even with cors.enabled (exposeheaders only covers
            # response headers).
            "Access-Control-Allow-Headers":
                "User-Task-ID, Content-Type, Authorization"}
    ssl_context = None
    if config.get_boolean("webserver.ssl.enable"):
        import ssl
        ssl_context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ssl_context.load_cert_chain(
            config.get_string("webserver.ssl.keystore.location"),
            password=config.get_string("webserver.ssl.key.password") or None)
    # ref CruiseControlParametersConfig: a non-default
    # <endpoint>.parameters.class plugin replaces the built-in parameter
    # class for that endpoint.
    parameter_overrides = {}
    from .config.constants import _PLUGGABLE_ENDPOINTS
    from .api.parameters import ENDPOINT_PARAMETERS
    for key_ep in _PLUGGABLE_ENDPOINTS:
        raw = config.get_string(f"{key_ep}.parameters.class")
        # config keys use dots; "stop.proposal" maps onto the
        # stop_proposal_execution endpoint (reference naming).
        endpoint = {"stop.proposal": "stop_proposal_execution"}.get(
            key_ep, key_ep.replace(".", "_"))
        # The built-in default is the "module:endpoint" sentinel; anything
        # else is a dotted plugin class path.
        if raw and ":" not in raw:
            if endpoint not in ENDPOINT_PARAMETERS:
                raise ValueError(
                    f"{key_ep}.parameters.class set for unknown endpoint "
                    f"{endpoint}")
            parameter_overrides[endpoint] = load_class(raw)
    return CruiseControlApp(
        facade,
        host=config.get_string("webserver.http.address"),
        port=config.get_int("webserver.http.port"),
        security=security,
        two_step_verification=config.get_boolean(
            "two.step.verification.enabled"),
        max_active_tasks=config.get_int("max.active.user.tasks"),
        completed_task_retention_ms=config.get_int(
            "completed.user.task.retention.time.ms"),
        max_cached_completed_tasks=config.get_int(
            "max.cached.completed.user.tasks"),
        purgatory_retention_ms=config.get_int(
            "two.step.purgatory.retention.time.ms"),
        purgatory_max_requests=config.get_int(
            "two.step.purgatory.max.requests"),
        reason_required=config.get_boolean("request.reason.required"),
        cors=cors,
        accesslog=config.get_boolean("webserver.accesslog.enabled"),
        ssl_context=ssl_context,
        parameter_overrides=parameter_overrides,
        engine=config.get_string("webserver.engine"),
        max_block_time_ms=config.get_long(
            "webserver.request.maxBlockTimeMs"),
        admission_rate_per_s=(
            config.get_double("admission.principal.rate.per.sec")
            if config.get_boolean("admission.rate.limit.enabled")
            else None),
        admission_burst=config.get_int("admission.principal.burst"))


class _AgentPipelineSampler:
    """Drive the L0 reporter agents then consume their records — the demo
    wiring of the full reporter -> metrics-topic -> sampler -> processor
    path (a real deployment's agents run inside the brokers; here the
    sampling tick doubles as the reporting tick)."""

    #: forwards the inner AgentTopicSampler's two-phase protocol so the
    #: fetcher manager's shard fan-out applies to the served path too.
    parallel_safe = True

    def __init__(self, agents, inner):
        self.agents = agents
        self.inner = inner
        self._prepared_window: tuple[int, int] | None = None

    def prepare_round(self, start_ms: int, end_ms: int) -> None:
        for a in self.agents:
            # end_ms is exclusive in the processor's window filter; stamp
            # the records just inside it. Reporting happens once per ROUND
            # (here), never per shard — per-shard reporting would duplicate
            # every record under fan-out.
            a.maybe_report(end_ms - 1)
        self.inner.prepare_round(start_ms, end_ms)
        self._prepared_window = (start_ms, end_ms)

    def get_samples(self, assignment):
        if self._prepared_window != (assignment.start_ms,
                                     assignment.end_ms):
            # Direct (manager-less) call: reporting still has to happen
            # before the inner sampler's serial fallback polls.
            for a in self.agents:
                a.maybe_report(assignment.end_ms - 1)
        return self.inner.get_samples(assignment)


def _make_sampler(config: CruiseControlConfig, admin, cpu_model=None):
    """Sampler selection, in precedence order: an explicit
    ``metric.sampler.class`` plugin, a Prometheus scrape when
    ``prometheus.server.endpoint`` is set, the agent metrics pipeline when
    enabled, else the default synthetic sampler."""
    raw_cls = config.get_string("metric.sampler.class")
    default_cls = "cruise_control_tpu.monitor.sampler.SyntheticWorkloadSampler"
    if raw_cls and raw_cls != default_cls:
        # CLASS-typed configs may carry an actual type, not just a path.
        cls = raw_cls if isinstance(raw_cls, type) else load_class(raw_cls)
        from .monitor import PrometheusMetricSampler
        if cls is PrometheusMetricSampler:
            # The canonical plugin spelling routes to the full Prometheus
            # wiring (adapter + host map) below.
            endpoint = config.get_string("prometheus.server.endpoint")
            if not endpoint:
                raise ValueError(
                    "PrometheusMetricSampler requires "
                    "prometheus.server.endpoint")
        else:
            import inspect
            params = list(inspect.signature(cls).parameters)
            if params[:1] in (["cluster"], ["admin"]):
                return cls(admin)
            if params[:1] == ["config"]:
                return cls(config)
            if not params:
                return cls()
            raise ValueError(
                f"metric.sampler.class {cls.__name__}: unsupported "
                f"constructor signature {params} — expected (cluster|admin),"
                " (config), or ()")
    endpoint = config.get_string("prometheus.server.endpoint")
    if not endpoint and config.get_boolean("use.agent.metrics.pipeline"):
        import zlib

        from .monitor import AgentTopicSampler, CruiseControlMetricsProcessor
        from .reporter import (MetricsReporterAgent, MetricsTransport,
                               SimClusterMetricsSource)
        rates = {tp: (25.0 + 75.0 * (zlib.crc32(repr(tp).encode()) % 1000)
                      / 1000.0, 40.0)
                 for tp in admin.describe_partitions()}
        transport = MetricsTransport()
        source = SimClusterMetricsSource(admin, rates)
        interval = config.get_int("metric.sampling.interval.ms")
        agents = [MetricsReporterAgent(b, source, transport,
                                       reporting_interval_ms=interval)
                  for b in sorted(admin.describe_cluster())]
        processor = CruiseControlMetricsProcessor(admin,
                                                  cpu_model=cpu_model)
        return _AgentPipelineSampler(agents,
                                     AgentTopicSampler(transport, processor))
    if not endpoint:
        return SyntheticWorkloadSampler(admin)
    import json as _json

    from .monitor import PrometheusAdapter, PrometheusMetricSampler
    map_file = config.get_string("prometheus.broker.host.map.file")
    if map_file:
        with open(map_file, encoding="utf-8") as f:
            host_map = {h: int(b) for h, b in _json.load(f).items()}
    else:
        # Default host naming b<id>, the reference's fallback of resolving
        # instance hosts against the cluster's broker host list.
        host_map = {f"b{b}": b for b in admin.describe_cluster()}
    return PrometheusMetricSampler(
        PrometheusAdapter(endpoint), host_map,
        step_ms=config.get_int("prometheus.query.resolution.step.ms"))


def _make_notifier(config: CruiseControlConfig, **kwargs):
    """Notifier selection (ref anomaly.notifier.class +
    Slack/MSTeams/Alerta notifier configs)."""
    kind = config.get_string("webhook.notifier.type")
    url = config.get_string("webhook.notifier.url")
    if not kind or not url:
        return SelfHealingNotifier(**kwargs)
    from .detector import (AlertaSelfHealingNotifier,
                           MSTeamsSelfHealingNotifier,
                           SlackSelfHealingNotifier)
    if kind == "slack":
        channel = config.get_string("webhook.notifier.channel")
        return SlackSelfHealingNotifier(url, channel=channel or None,
                                        **kwargs)
    if kind == "msteams":
        return MSTeamsSelfHealingNotifier(url, **kwargs)
    return AlertaSelfHealingNotifier(
        url, environment=config.get_string("alerta.environment"),
        api_key=config.get_string("alerta.api.key") or None, **kwargs)


def _make_security(config: CruiseControlConfig):
    """Provider selection (ref webserver.security.provider set)."""
    kind = config.get_string("webserver.security.provider")
    if kind == "jwt":
        from .api.security import JwtSecurityProvider
        secret = config.get_string("jwt.secret")
        if not secret:
            raise ValueError("jwt security requires jwt.secret")
        return JwtSecurityProvider(
            secret, role_claim=config.get_string("jwt.role.claim"),
            expected_audiences=config.get_list("jwt.expected.audiences"),
            cookie_name=config.get_string("jwt.cookie.name") or None)
    if kind == "trustedproxy":
        from .api.security import TrustedProxySecurityProvider
        return TrustedProxySecurityProvider(
            set(config.get_list("trusted.proxy.services")),
            principal_header=config.get_string(
                "trusted.proxy.principal.header"),
            ip_regex=config.get_string(
                "trusted.proxy.services.ip.regex") or None)
    if kind == "spnego":
        from .api.security import SpnegoSecurityProvider
        principal = config.get_string("spnego.principal")
        if not principal:
            raise ValueError("spnego security requires spnego.principal")
        return SpnegoSecurityProvider(principal)
    return BasicSecurityProvider(_load_credentials(
        config.get_string("webserver.auth.credentials.file")))


def _load_credentials(path: str) -> dict[str, tuple[str, Role]]:
    """Jetty-style auth file: ``name: password,ROLE`` per line (ref
    BasicSecurityProvider's credentials file)."""
    users: dict[str, tuple[str, Role]] = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            name, _, rest = line.partition(":")
            password, _, role = rest.strip().partition(",")
            users[name.strip()] = (password.strip(),
                                   Role[role.strip().upper() or "VIEWER"])
    return users


def _make_admin(config: CruiseControlConfig,
                demo_brokers: int = 64, demo_partitions: int = 2048):
    """Admin backend: a plugin implementing ClusterAdminClient when
    ``admin.client.class`` is set, else the demo simulated cluster."""
    cls_name = config.get_string("admin.client.class")
    if cls_name:
        cls = load_class(cls_name)
        try:
            return cls(config)
        except TypeError:
            return cls()
    return _demo_cluster(demo_brokers, demo_partitions)


def _demo_cluster(num_brokers: int, num_partitions: int) -> SimulatedKafkaCluster:
    sim = SimulatedKafkaCluster(now_ms=int(time.time() * 1000))
    for b in range(num_brokers):
        sim.add_broker(b, logdirs=("logdir0", "logdir1"))
    for p in range(num_partitions):
        sim.add_partition(f"topic-{p % max(num_partitions // 32, 1)}", p,
                          [p % num_brokers, (p + 1) % num_brokers],
                          size_mb=50.0 + (p % 100))
    return sim


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="cruise-control-tpu server")
    ap.add_argument("--config", help="cruisecontrol.properties path")
    ap.add_argument("--port", type=int, help="override webserver.http.port")
    ap.add_argument("--demo-brokers", type=int, default=64)
    ap.add_argument("--demo-partitions", type=int, default=2048)
    args = ap.parse_args(argv)
    # Server logging (ref config/log4j.properties): INFO to stdout so the
    # OPERATION_LOG audit trail and component logs actually appear.
    import logging
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    # Fall back to CPU when the default accelerator backend is unreachable
    # (same probe bench.py uses) — a control plane must come up regardless.
    from .utils.platform import ensure_live_backend
    platform = ensure_live_backend()
    print(f"jax platform: {platform}", flush=True)
    props = load_properties_file(args.config) if args.config else {}
    if args.port is not None:
        props["webserver.http.port"] = str(args.port)
    config = CruiseControlConfig(props)
    admin = _make_admin(config, args.demo_brokers, args.demo_partitions)
    app = build_app(config, admin)
    app.facade.start_up(
        precompute_interval_s=config.get_int("proposal.expiration.ms") / 1000,
        skip_loading=config.get_boolean("skip.loading.samples"),
        freshness_target_ms=config.get_long("proposals.freshness.target.ms"),
        start_prewarm=config.get_boolean("prewarm.on.start"),
        # With the fleet plane on, its shared tick refills the local
        # member's cache (batched dispatch) — the refresher drops to
        # watch-only: full freshness-SLO breach accounting, no second
        # per-cluster compute racing the fleet tick. Blocking reads
        # still compute on miss either way.
        precompute_watch_only=app.facade.fleet is not None)
    if app.facade.fleet is not None:
        app.facade.fleet.start(config.get_long("fleet.tick.ms") / 1000.0)
    app.facade.detector.start_detection()
    app.start()
    print(f"cruise-control-tpu listening on "
          f"http://{config.get_string('webserver.http.address')}:{app.port}"
          f"/kafkacruisecontrol/state", flush=True)
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    runner = app.facade.task_runner
    try:
        # The serving loop drives wall-clock-paced work: the demo cluster's
        # virtual time follows real time (so executions progress), and the
        # sampling loop fires at its configured interval (ref the reference's
        # scheduled LoadMonitorTaskRunner).
        while not stop:
            time.sleep(0.5)
            now = int(time.time() * 1000)
            if isinstance(admin, SimulatedKafkaCluster):
                admin.advance_to(now)
            try:
                runner.maybe_run_sampling(now)
            except Exception:
                pass   # transient sampler failure: retry next tick
            try:
                # Election + cadenced snapshot write (leader) / newer-
                # snapshot refresh (standby); no-op when neither is on.
                app.facade.ha_tick(now)
            except Exception:
                logging.getLogger(__name__).warning(
                    "ha/snapshot tick failed; retrying next tick",
                    exc_info=True)
    finally:
        app.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
