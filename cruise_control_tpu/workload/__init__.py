"""Trace-driven workload plane (ROADMAP item 5): pattern-class load
generation, consumer adapters, and regime-aware online tuning.

See docs/workloads.md for the pattern classes, the trace schema, the
three consumer adapters (bench / chaos / forecast backtests), and the
regime -> tuner flow.
"""

from .adapters import (TraceSampler, backtest_by_class,
                       schedule_burst_faults)
from .generator import (TRACE_RESOURCES, TopicTrace, WorkloadTrace,
                        diurnal_growth_series, generate_trace)
from .patterns import (DOW_OFFSETS, PATTERN_CLASSES, SPEC_REGISTRY,
                       CorrelatedBurstSpec, DiurnalGrowthSpec,
                       FlashCrowdSpec, PatternSpec, SkewDriftSpec,
                       StepMigrationSpec, WeeklySpec, base_level,
                       stack_resources)
from .regime import (REGIMES, RegimeDetector, RegimeShiftDetector,
                     RegimeTuningLoop, aggregate_series)

__all__ = [
    "TRACE_RESOURCES", "TopicTrace", "WorkloadTrace",
    "diurnal_growth_series", "generate_trace",
    "DOW_OFFSETS", "PATTERN_CLASSES", "SPEC_REGISTRY",
    "CorrelatedBurstSpec", "DiurnalGrowthSpec", "FlashCrowdSpec",
    "PatternSpec", "SkewDriftSpec", "StepMigrationSpec", "WeeklySpec",
    "base_level", "stack_resources",
    "TraceSampler", "backtest_by_class", "schedule_burst_faults",
    "REGIMES", "RegimeDetector", "RegimeShiftDetector",
    "RegimeTuningLoop", "aggregate_series",
]
