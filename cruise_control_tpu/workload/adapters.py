"""Narrow adapters feeding the generated traces to existing consumers.

Three consumers, three adapters, zero changes to the consumers' own
contracts (docs/workloads.md):

- :class:`TraceSampler` — a ``MetricSampler`` replaying a
  :class:`~.generator.WorkloadTrace` against a simulated cluster, the
  drop-in replacement for ``SyntheticWorkloadSampler`` in the chaos
  harnesses (``ChaosHarness(sampler=...)``);
- :func:`schedule_burst_faults` — the trace-clocked chaos hook: maps
  the trace's burst windows onto ``ChaosEngine`` steps so faults land
  DURING bursts, deterministically;
- :func:`backtest_by_class` — per-pattern-class worst holdout MAPE
  through the forecast ladder (the scenario-14 gate rows).
"""

from __future__ import annotations

import numpy as np

from ..core.metricdef import BrokerMetric, KafkaMetric
from ..monitor.samples import BrokerMetricSample, PartitionMetricSample
from ..monitor.sampler import Samples, SamplerAssignment
from .generator import WorkloadTrace


class TraceSampler:
    """Replay a workload trace as metric samples over a simulated
    cluster.

    Window selection: sample time ``end_ms`` maps to trace window
    ``(end_ms // window_ms) % num_windows`` (``window_ms`` defaults to
    the trace's own width; chaos harnesses pass their monitor window so
    one trace window advances per sampling round; the modulo loops the
    trace for soaks longer than the trace). A topic's window load
    spreads across its live partitions by the trace's share matrix when
    the class has one (skew drift), uniformly otherwise; topics the
    trace does not know get ``default_bytes_in`` flat. Broker samples
    sum the leader/follower shares exactly like
    ``SyntheticWorkloadSampler``, so processor CPU attribution
    round-trips the same way."""

    parallel_safe = False

    def __init__(self, cluster, trace: WorkloadTrace, *,
                 window_ms: int | None = None, loop: bool = True,
                 cpu_per_byte: float = 0.001,
                 default_bytes_in: float = 50.0):
        self.cluster = cluster
        self.trace = trace
        self.window_ms = window_ms or trace.window_ms
        self.loop = loop
        self.cpu_per_byte = cpu_per_byte
        self.default_bytes_in = default_bytes_in

    def window_at(self, end_ms: int) -> int:
        w = int(end_ms // max(self.window_ms, 1))
        if self.loop:
            return w % self.trace.num_windows
        return min(w, self.trace.num_windows - 1)

    def _partition_rates(self, tp: tuple[str, int], w: int,
                         topic_parts: dict[str, list[int]]
                         ) -> tuple[float, float]:
        tt = self.trace.topics.get(tp[0])
        if tt is None:
            bytes_in = self.default_bytes_in
            return bytes_in, bytes_in * 1.5
        live = topic_parts.get(tp[0]) or [tp[1]]
        if tt.shares is not None:
            P = tt.shares.shape[1]
            share = float(tt.shares[w, tp[1] % P])
            # Renormalize over the partition ids actually live in the
            # sim (the trace's P and the sim's ids/count need not
            # match — a sim topic's partitions are not necessarily
            # numbered 0..count-1).
            norm = float(tt.shares[w, np.asarray(live) % P].sum())
            share = share / max(norm, 1e-12)
        else:
            share = 1.0 / len(live)
        return float(tt.values[1, w]) * share, float(tt.values[2, w]) * share

    def get_samples(self, assignment: SamplerAssignment) -> Samples:
        infos = self.cluster.describe_partitions()
        t = assignment.end_ms
        w = self.window_at(t)
        topic_parts: dict[str, list[int]] = {}
        for topic, p in infos:
            topic_parts.setdefault(topic, []).append(p)
        psamples: list[PartitionMetricSample] = []
        by_broker_in: dict[int, float] = {}
        by_broker_out: dict[int, float] = {}
        by_broker_disk: dict[int, float] = {}
        for tp in assignment.partitions:
            info = infos.get(tp)
            if info is None:
                continue
            bytes_in, bytes_out = self._partition_rates(tp, w,
                                                        topic_parts)
            s = PartitionMetricSample(tp[0], tp[1], t)
            s.record(KafkaMetric.LEADER_BYTES_IN, bytes_in)
            s.record(KafkaMetric.LEADER_BYTES_OUT, bytes_out)
            s.record(KafkaMetric.DISK_USAGE, info.size_mb)
            s.record(KafkaMetric.PRODUCE_RATE, bytes_in / 10.0)
            s.record(KafkaMetric.FETCH_RATE, bytes_out / 10.0)
            s.record(KafkaMetric.MESSAGE_IN_RATE, bytes_in / 100.0)
            s.record(KafkaMetric.REPLICATION_BYTES_IN_RATE,
                     bytes_in * max(len(info.replicas) - 1, 0))
            s.record(KafkaMetric.CPU_USAGE,
                     self.cpu_per_byte * (bytes_in + bytes_out))
            psamples.append(s)
            by_broker_in[info.leader] = (by_broker_in.get(info.leader, 0.0)
                                         + bytes_in)
            by_broker_out[info.leader] = (by_broker_out.get(info.leader,
                                                            0.0)
                                          + bytes_out)
            for b in info.replicas:
                by_broker_disk[b] = (by_broker_disk.get(b, 0.0)
                                     + info.size_mb)
                if b != info.leader:
                    by_broker_in[b] = by_broker_in.get(b, 0.0) + bytes_in
        bsamples: list[BrokerMetricSample] = []
        alive = self.cluster.describe_cluster()
        for b in assignment.brokers:
            if not alive.get(b, False):
                continue
            s = BrokerMetricSample(b, t)
            tot_in = by_broker_in.get(b, 0.0)
            tot_out = by_broker_out.get(b, 0.0)
            s.record(BrokerMetric.CPU_USAGE,
                     self.cpu_per_byte * (tot_in + tot_out))
            s.record(BrokerMetric.LEADER_BYTES_IN, tot_in)
            s.record(BrokerMetric.LEADER_BYTES_OUT, tot_out)
            s.record(BrokerMetric.DISK_USAGE, by_broker_disk.get(b, 0.0))
            metrics = self.cluster.broker_metrics(b)
            s.record(BrokerMetric.BROKER_LOG_FLUSH_TIME_MS_MEAN,
                     metrics.get("log_flush_time_ms", 0.0))
            bsamples.append(s)
        return Samples(psamples, bsamples)


def schedule_burst_faults(engine, trace: WorkloadTrace, *,
                          window_ms: int | None = None,
                          action: str = "kill_broker",
                          recover: str | None = "restart_broker",
                          at_frac: float = 0.25,
                          recover_after_windows: int = 4,
                          **kwargs) -> list[int]:
    """Schedule one ``action`` INSIDE each of the trace's burst ranges
    (at ``at_frac`` through the range — mid-ramp by default, so the
    fault lands while load is still climbing), plus the paired
    ``recover`` action ``recover_after_windows`` later. ``window_ms``
    maps trace windows to engine steps and must match the replaying
    :class:`TraceSampler`'s. Returns the scheduled fault steps (the
    soak's assertion anchors). ``kwargs`` go to both actions (e.g.
    ``broker=2``)."""
    window_ms = window_ms or trace.window_ms
    steps: list[int] = []
    for s, e in trace.burst_windows():
        w = s + int((e - s) * at_frac)
        step = w * window_ms // engine.step_ms
        engine.schedule(step, action, **kwargs)
        if recover is not None:
            back = ((w + recover_after_windows) * window_ms
                    // engine.step_ms)
            engine.schedule(back, recover, **kwargs)
        steps.append(step)
    return steps


def backtest_by_class(trace: WorkloadTrace, *,
                      seasonal_period_ms: int | None = None,
                      week_period_ms: int = 0,
                      changepoint_min_shift: float = 0.0,
                      min_history_windows: int = 3
                      ) -> dict[str, float]:
    """Worst 1-window-holdout MAPE per pattern class, fitted through
    the forecast degrade ladder (weekly + changepoint rungs included
    when enabled) — the ``forecast_mape_<class>`` bench rows. Classes
    whose fits carry no backtest (degenerate histories) are omitted."""
    from ..forecast import fit_topic_forecasts
    if seasonal_period_ms is None:
        seasonal_period_ms = trace.day_windows * trace.window_ms
    fits = fit_topic_forecasts(
        trace.topic_series(), trace.window_ms,
        seasonal_period_ms=seasonal_period_ms,
        week_period_ms=week_period_ms,
        changepoint_min_shift=changepoint_min_shift,
        min_history_windows=min_history_windows, fitted_at_ms=0)
    out: dict[str, float] = {}
    for cls, topics in trace.classes().items():
        errs = [fits.forecasts[t].backtest_mape for t in topics
                if fits.forecasts[t].backtest_mape is not None]
        if errs:
            out[cls] = max(errs)
    return out
