"""First-class workload pattern classes for the trace-driven generator.

No reference analog — the reference control plane replays recorded
metrics; here the *shapes* of production traffic are the model
(ROADMAP item 5): flash crowds, weekly seasonality, step migrations,
correlated multi-topic bursts, and partition-skew drift (the
key-distribution constraint of arxiv 2205.09415 makes skew traces
mandatory for any credible partition-load model). Each
:class:`PatternSpec` is a small, composable recipe that turns a topic
index + shared abscissa into one ``[4, W]`` per-resource window trace
(cpu / nwIn / nwOut / disk — the forecast fit's resource order) and,
for skewed classes, a ``[W, P]`` per-partition share matrix.

Determinism contract: a spec consumes the generator's single seeded rng
a FIXED number of draws per topic (independent of which other specs run
or of the partition count), so the same ``(specs, topics, seed)`` always
produces byte-identical traces — the property tests and the bench's
seed-stable scenario-8 dedupe both rely on it. ``prepare`` runs once per
spec (in spec order) before any topic is generated; correlated classes
draw their shared latents there.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import ClassVar

import numpy as np

#: canonical pattern-class labels, the vocabulary bench rows
#: (``forecast_mape_<class>``) and the regime detector share
PATTERN_CLASSES = ("steady", "diurnal_growth", "flash_crowd", "weekly",
                   "step_migration", "correlated_burst", "skew_drift")


def base_level(i: int) -> float:
    """The per-topic base load level — the same deterministic lattice
    bench.py's scenario-8 inline builder used (``200 + 10 * (i % 17)``),
    kept as THE level convention so every pattern class produces
    comparable magnitudes."""
    return 200.0 + 10.0 * (i % 17)


def stack_resources(y: np.ndarray, level: float,
                    disk: np.ndarray | None = None) -> np.ndarray:
    """The ``[4, W]`` resource stack from one nwIn series: cpu tracks
    bytes at 1%, nwOut at half fan-out, disk flat at 5x level unless the
    class supplies its own (the scenario-8 conventions)."""
    if disk is None:
        disk = np.full_like(y, 5.0 * level)
    return np.stack([0.01 * y, y, 0.5 * y, disk])


@dataclass(frozen=True)
class PatternSpec:
    """Base spec: steady load with mild relative noise.

    Subclasses override :meth:`topic_values` (and optionally
    :meth:`prepare` / :meth:`topic_shares` / :meth:`burst_windows`).
    ``noise`` is the relative sigma of the per-window jitter every class
    applies (0 disables — shares are always noise-free)."""

    pattern: ClassVar[str] = "steady"
    noise: float = 0.01

    def prepare(self, rng: np.random.Generator, num_windows: int,
                day_windows: int) -> dict:
        """Shared latent state drawn ONCE per spec before any topic
        (correlated classes pick their common burst here)."""
        return {}

    def _noise(self, rng: np.random.Generator, level: float,
               num_windows: int) -> np.ndarray:
        # Always consume the same number of draws, even at noise=0, so
        # toggling noise never re-phases the stream for later topics.
        eps = rng.normal(0.0, 0.01 * level, num_windows)
        return eps * (self.noise / 0.01) if self.noise != 0.01 else eps

    def topic_values(self, rng: np.random.Generator, i: int,
                     x: np.ndarray, day_windows: int,
                     state: dict) -> np.ndarray:
        level = base_level(i)
        y = level + self._noise(rng, level, len(x))
        return stack_resources(y, level)

    def topic_shares(self, i: int, num_windows: int,
                     partitions: int, state: dict) -> np.ndarray | None:
        """Per-partition share matrix ``[W, P]`` (rows sum to 1), or
        None for classes whose load spreads uniformly."""
        return None

    def burst_windows(self, num_windows: int,
                      state: dict) -> list[tuple[int, int]]:
        """Half-open ``[start, end)`` window ranges where this class is
        bursting — the trace-clocked chaos hook injects faults here."""
        return []


@dataclass(frozen=True)
class DiurnalGrowthSpec(PatternSpec):
    """Level + linear growth + diurnal sinusoid — byte-identical to the
    inline trace builder bench.py scenario 8 shipped with (the dedupe
    satellite's seed-stability contract): same level lattice, same
    slope/amplitude rules, same single ``rng.normal`` draw per topic."""

    pattern: ClassVar[str] = "diurnal_growth"

    def topic_values(self, rng, i, x, day_windows, state):
        W = len(x)
        level = base_level(i)
        slope = 0.05 * (i % 5) * level / W
        amp = 0.2 * level
        y = (level + slope * x + amp * np.sin(2 * np.pi * x / day_windows)
             + rng.normal(0.0, 0.01 * level, W))
        return np.stack([0.01 * y, y, 0.5 * y,
                         5.0 * level + slope * x])   # cpu/nwIn/nwOut/disk


@dataclass(frozen=True)
class FlashCrowdSpec(PatternSpec):
    """A flash crowd: steady baseline, then a ramp to ``peak_ratio`` x
    level, a hold, and a linear decay back — the canonical viral-event
    shape. The burst is a LEVEL excursion, not a trend, so a fit without
    changepoint handling smears it into the level; the changepoint rung
    truncates to the post-burst suffix and recovers the clean baseline."""

    pattern: ClassVar[str] = "flash_crowd"
    peak_ratio: float = 8.0
    ramp_windows: int = 4
    hold_windows: int = 6
    decay_windows: int = 12
    at_frac: float = 0.5

    def _profile(self, num_windows: int) -> np.ndarray:
        at = int(num_windows * self.at_frac)
        b = np.zeros(num_windows)
        r, h, d = self.ramp_windows, self.hold_windows, self.decay_windows
        up = np.arange(1, r + 1) / r
        down = 1.0 - np.arange(1, d + 1) / d
        prof = np.concatenate([up, np.ones(h), down])
        end = min(at + len(prof), num_windows)
        b[at:end] = prof[:end - at]
        return b

    def topic_values(self, rng, i, x, day_windows, state):
        level = base_level(i)
        b = self._profile(len(x))
        y = (level * (1.0 + (self.peak_ratio - 1.0) * b)
             + self._noise(rng, level, len(x)))
        return stack_resources(y, level)

    def burst_windows(self, num_windows, state):
        at = int(num_windows * self.at_frac)
        end = min(at + self.ramp_windows + self.hold_windows
                  + self.decay_windows, num_windows)
        return [(at, end)]


#: additive day-of-week load offsets (fraction of level), Mon..Sun —
#: midweek ramps up, Friday peaks, the weekend craters (the e-commerce
#: shape the paper's deployment balances around)
DOW_OFFSETS = (0.0, 0.05, 0.12, 0.04, 0.25, -0.28, -0.38)


@dataclass(frozen=True)
class WeeklySpec(PatternSpec):
    """Weekly seasonality: a daily sinusoid plus additive day-of-week
    offsets (``DOW_OFFSETS``). A day is ``day_windows`` windows and a
    week is exactly 7 days, matching the forecast ladder's weekly-bucket
    rule — the weekly rung fits this class to noise level; without it
    the weekend offset alone is a ~38% level error."""

    pattern: ClassVar[str] = "weekly"
    daily_amp: float = 0.2

    def topic_values(self, rng, i, x, day_windows, state):
        level = base_level(i)
        dow = np.asarray(DOW_OFFSETS)[
            (x.astype(int) // day_windows) % 7]
        y = (level * (1.0 + self.daily_amp
                      * np.sin(2 * np.pi * x / day_windows) + dow)
             + self._noise(rng, level, len(x)))
        return stack_resources(y, level)


@dataclass(frozen=True)
class StepMigrationSpec(PatternSpec):
    """A step migration: load jumps to ``step_ratio`` x level at window
    ``at_frac * W`` and STAYS there (a workload migrating onto the
    cluster). The changepoint rung must locate the step and fit the
    post-step suffix; the regime detector classifies the sustained
    elevation as ``step_migration``."""

    pattern: ClassVar[str] = "step_migration"
    step_ratio: float = 2.5
    at_frac: float = 2.0 / 3.0

    def step_window(self, num_windows: int) -> int:
        return int(num_windows * self.at_frac)

    def topic_values(self, rng, i, x, day_windows, state):
        level = base_level(i)
        at = self.step_window(len(x))
        y = (level * (1.0 + (self.step_ratio - 1.0) * (x >= at))
             + self._noise(rng, level, len(x)))
        return stack_resources(y, level)


@dataclass(frozen=True)
class CorrelatedBurstSpec(PatternSpec):
    """A correlated multi-topic burst: EVERY topic assigned this spec
    bursts over the same windows (the shared latent drawn in
    :meth:`prepare`), with a per-topic amplitude scale — the
    cross-topic correlation that makes aggregate headroom, not
    per-topic headroom, the binding constraint."""

    pattern: ClassVar[str] = "correlated_burst"
    peak_ratio: float = 5.0
    ramp_windows: int = 2
    hold_windows: int = 4
    decay_windows: int = 6
    #: fixed burst-start fraction; None draws it from the shared rng
    at_frac: float | None = None

    def prepare(self, rng, num_windows, day_windows):
        if self.at_frac is not None:
            at = int(num_windows * self.at_frac)
        else:
            at = int(rng.integers(num_windows // 4,
                                  max(num_windows // 2, num_windows // 4 + 1)))
        return {"at": at}

    def topic_values(self, rng, i, x, day_windows, state):
        level = base_level(i)
        amp = 0.75 + 0.5 * rng.random()     # per-topic burst severity
        b = np.zeros(len(x))
        r, h, d = self.ramp_windows, self.hold_windows, self.decay_windows
        prof = np.concatenate([np.arange(1, r + 1) / r, np.ones(h),
                               1.0 - np.arange(1, d + 1) / d])
        at = state["at"]
        end = min(at + len(prof), len(x))
        b[at:end] = prof[:end - at]
        y = (level * (1.0 + (self.peak_ratio - 1.0) * amp * b)
             + self._noise(rng, level, len(x)))
        return stack_resources(y, level)

    def burst_windows(self, num_windows, state):
        at = state["at"]
        end = min(at + self.ramp_windows + self.hold_windows
                  + self.decay_windows, num_windows)
        return [(at, end)]


@dataclass(frozen=True)
class SkewDriftSpec(PatternSpec):
    """Partition-skew drift: topic-level load stays steady but the
    per-partition key distribution is Zipf with an exponent drifting
    ``zipf_a0 -> zipf_a1`` across the trace — a hot key emerging. The
    share matrix is noise-free and analytic, so the property test can
    recover the exponent trajectory exactly (arxiv 2205.09415's
    constraint: partition counts cannot relieve a skewed key)."""

    pattern: ClassVar[str] = "skew_drift"
    zipf_a0: float = 1.01
    zipf_a1: float = 2.0

    def exponent(self, w: int, num_windows: int) -> float:
        frac = w / max(num_windows - 1, 1)
        return self.zipf_a0 + (self.zipf_a1 - self.zipf_a0) * frac

    def topic_shares(self, i, num_windows, partitions, state):
        ranks = np.arange(1, partitions + 1, dtype=float)
        a = np.asarray([self.exponent(w, num_windows)
                        for w in range(num_windows)])
        raw = ranks[None, :] ** (-a[:, None])          # [W, P]
        return raw / raw.sum(axis=1, keepdims=True)


#: pattern label -> default spec instance (the bench / docs registry)
SPEC_REGISTRY = {
    "steady": PatternSpec(),
    "diurnal_growth": DiurnalGrowthSpec(),
    "flash_crowd": FlashCrowdSpec(),
    "weekly": WeeklySpec(),
    "step_migration": StepMigrationSpec(),
    "correlated_burst": CorrelatedBurstSpec(),
    "skew_drift": SkewDriftSpec(),
}
