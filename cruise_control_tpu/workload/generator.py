"""Seeded trace generation: pattern specs -> per-topic window traces.

One :func:`generate_trace` call materializes a :class:`WorkloadTrace`:
per-topic ``[4, W]`` resource series (cpu / nwIn / nwOut / disk — the
forecast fit's order) plus optional ``[W, P]`` per-partition shares,
each labeled with its pattern class. The trace is the single source the
three consumers adapt from (docs/workloads.md):

- forecast backtests read :meth:`WorkloadTrace.topic_series` (exactly
  the ``fit_topic_forecasts`` input schema);
- chaos soaks replay it through ``workload.adapters.TraceSampler`` and
  clock fault injection off :meth:`WorkloadTrace.burst_windows`;
- bench scenario 14 groups MAPE gates by :meth:`WorkloadTrace.classes`.

Determinism: ONE ``np.random.default_rng(seed)`` stream, consumed spec
``prepare`` hooks first (in spec order) then topics in topic order —
:meth:`WorkloadTrace.digest` is the byte-level witness the determinism
test pins.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

import numpy as np

from .patterns import DiurnalGrowthSpec, PatternSpec

#: resource row order of every trace (shared with forecast/model.py)
TRACE_RESOURCES = ("cpu", "nwIn", "nwOut", "disk")


@dataclass
class TopicTrace:
    """One topic's generated trace: resource values, pattern label,
    optional per-partition shares, and the class's burst ranges."""

    topic: str
    pattern: str
    values: np.ndarray                     # f64[4, W]
    shares: np.ndarray | None = None       # f64[W, P], rows sum to 1
    bursts: list = field(default_factory=list)   # [(start_w, end_w)]


@dataclass
class WorkloadTrace:
    """The generated workload: topic -> :class:`TopicTrace` plus the
    provenance (seed, window width) every consumer carries along."""

    window_ms: int
    num_windows: int
    seed: int
    day_windows: int
    topics: dict[str, TopicTrace]

    def __len__(self) -> int:
        return len(self.topics)

    def topic_series(self) -> dict[str, tuple[np.ndarray, np.ndarray]]:
        """The forecast-fit adapter: topic -> (values[4, W], valid[W])
        with every window valid (the generator never produces holes —
        dropouts are the chaos engine's job)."""
        ones = np.ones(self.num_windows, bool)
        return {t: (tt.values, ones) for t, tt in self.topics.items()}

    def classes(self) -> dict[str, list[str]]:
        """pattern label -> sorted topic list (the per-class gate axis)."""
        out: dict[str, list[str]] = {}
        for t, tt in self.topics.items():
            out.setdefault(tt.pattern, []).append(t)
        return {k: sorted(v) for k, v in sorted(out.items())}

    def burst_windows(self) -> list[tuple[int, int]]:
        """Merged union of every topic's burst ranges, sorted — the
        trace-clocked chaos hook's fault anchors."""
        ranges = sorted(r for tt in self.topics.values()
                        for r in tt.bursts)
        merged: list[tuple[int, int]] = []
        for s, e in ranges:
            if merged and s <= merged[-1][1]:
                merged[-1] = (merged[-1][0], max(merged[-1][1], e))
            else:
                merged.append((s, e))
        return merged

    def aggregate(self, resource: int = 1) -> np.ndarray:
        """Cluster-aggregate series of one resource row (default nwIn)
        summed over topics — the regime detector's input shape."""
        return np.sum([tt.values[resource]
                       for tt in self.topics.values()], axis=0)

    def digest(self) -> str:
        """sha256 over every topic's values (+ shares) in topic order —
        the byte-identical determinism witness."""
        h = hashlib.sha256()
        for t in sorted(self.topics):
            tt = self.topics[t]
            h.update(t.encode())
            h.update(np.ascontiguousarray(tt.values).tobytes())
            if tt.shares is not None:
                h.update(np.ascontiguousarray(tt.shares).tobytes())
        return h.hexdigest()


def generate_trace(specs: list[PatternSpec], topics: list[str], *,
                   num_windows: int, window_ms: int = 60_000,
                   seed: int = 0, day_windows: int = 24,
                   partitions: int = 8) -> WorkloadTrace:
    """Generate one trace: topic ``i`` is assigned ``specs[i % len]``
    (round-robin, so a multi-class trace interleaves classes across the
    topic list). One seeded rng, consumed ``prepare`` first then topics
    in order — see the module docstring's determinism contract."""
    if not specs:
        raise ValueError("generate_trace needs at least one PatternSpec")
    if num_windows < 2:
        raise ValueError(f"num_windows must be >= 2, got {num_windows}")
    rng = np.random.default_rng(seed)
    states = [spec.prepare(rng, num_windows, day_windows)
              for spec in specs]
    x = np.arange(num_windows, dtype=float)
    out: dict[str, TopicTrace] = {}
    for i, t in enumerate(topics):
        spec = specs[i % len(specs)]
        state = states[i % len(specs)]
        values = spec.topic_values(rng, i, x, day_windows, state)
        shares = spec.topic_shares(i, num_windows, partitions, state)
        out[t] = TopicTrace(topic=t, pattern=spec.pattern, values=values,
                            shares=shares,
                            bursts=spec.burst_windows(num_windows, state))
    return WorkloadTrace(window_ms=window_ms, num_windows=num_windows,
                         seed=seed, day_windows=day_windows, topics=out)


def diurnal_growth_series(topics: list[str], num_windows: int, *,
                          day_windows: int = 24, seed: int = 13
                          ) -> dict[str, tuple[np.ndarray, np.ndarray]]:
    """The scenario-8 fit traces, generated through the pattern class:
    byte-identical to the inline builder bench.py shipped before the
    workload package existed (level lattice ``200 + 10*(i%17)``, growth
    ``0.05*(i%5)*level/W``, 20% diurnal amplitude, 1% noise from
    ``default_rng(seed)`` consumed in topic order) — the dedupe
    satellite's seed-stability contract, pinned by
    tests/test_workload.py against a frozen copy of the old code."""
    trace = generate_trace([DiurnalGrowthSpec()], list(topics),
                           num_windows=num_windows, seed=seed,
                           day_windows=day_windows)
    return trace.topic_series()
