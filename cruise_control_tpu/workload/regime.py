"""Traffic-regime detection and the continuous online-tuning loop.

Closes ROADMAP item 5's tuning loop: PR 11's successive-halving tuner
ran offline per shape bucket; here a :class:`RegimeDetector` classifies
the ACTIVE traffic regime from the cluster-aggregate window series (the
same aggregator cube the forecast fits read), and a
:class:`RegimeTuningLoop` reacts to shifts — ensuring a tuned config
exists per ``(shape bucket, regime)`` in the ``TunedConfigStore`` and
flipping the optimizer's ``active_regime`` so lookups resolve to the
regime's schedule. The tuned config joins the compiled-chain /
dispatch-group key exactly as buckets do today, so once each regime's
chain is warm a shift changes WHICH cached chain runs, never compiles a
new one (the zero-warm-recompile gate of bench scenario 14).

Classification is pure host numpy over the recent window tail:

- ``steady`` — no recent window exceeds ``burst_ratio`` x the robust
  (median) baseline;
- ``flash_crowd`` — an excursion that is already decaying (the latest
  windows sit well below the recent peak);
- ``step_migration`` — a sustained elevation (the latest windows hold
  near the recent peak).

Hysteresis (``min_dwell`` consecutive classifications before a switch)
keeps a noisy boundary from thrashing the tuner.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

import numpy as np

LOG = logging.getLogger(__name__)

#: the regime vocabulary (store keys qualify buckets with these labels)
REGIMES = ("steady", "flash_crowd", "step_migration")

_EPS = 1e-9


@dataclass
class RegimeDetector:
    """Stateful classifier over cluster-aggregate window series.

    :meth:`classify` is stateless (the property tests drive it
    directly); :meth:`observe` adds the dwell hysteresis and the shift
    journal."""

    #: a recent window must exceed this multiple of the baseline median
    #: before anything but ``steady`` is considered
    burst_ratio: float = 2.0
    #: latest windows at >= this fraction of the recent peak = the
    #: elevation persists (step), below = it is decaying (flash crowd)
    persist_frac: float = 0.6
    #: how many trailing windows count as "recent"
    tail_windows: int = 8
    #: consecutive classifications required before switching regime
    min_dwell: int = 1
    regime: str = "steady"
    shifts: list = field(default_factory=list)
    _pending: str | None = None
    _pending_count: int = 0

    def classify(self, series) -> str:
        """Classify one aggregate series (most recent window last)."""
        y = np.asarray(series, float)
        W = len(y)
        if W < 4:
            return "steady"
        t = min(self.tail_windows, max(W // 4, 1))
        base = float(np.median(y[:-t]))
        if base <= _EPS:
            return "steady"
        r = y[-t:] / base
        peak = float(r.max())
        if peak < self.burst_ratio:
            return "steady"
        last = float(np.mean(r[-max(t // 4, 1):]))
        if last >= self.persist_frac * peak:
            return "step_migration"
        return "flash_crowd"

    def observe(self, series, now_ms: int = 0) -> tuple[str, bool]:
        """Classify and update the dwell state machine; returns
        ``(active regime, shifted this observation)``."""
        label = self.classify(series)
        if label == self.regime:
            self._pending, self._pending_count = None, 0
            return self.regime, False
        if label == self._pending:
            self._pending_count += 1
        else:
            self._pending, self._pending_count = label, 1
        if self._pending_count < self.min_dwell:
            return self.regime, False
        prev, self.regime = self.regime, label
        self._pending, self._pending_count = None, 0
        self.shifts.append({"fromRegime": prev, "toRegime": label,
                            "atMs": int(now_ms)})
        LOG.info("workload regime shift: %s -> %s (at %dms)", prev,
                 label, now_ms)
        return self.regime, True


def aggregate_series(monitor, now_ms: int,
                     metric: int | None = None) -> np.ndarray:
    """Cluster-aggregate per-window series (default LEADER_BYTES_IN)
    from the monitor's partition aggregator — the regime detector's
    live input, read off the SAME dense cube the forecast fit uses.
    Raises ``NotEnoughValidWindowsError`` while no window is valid."""
    from ..core.aggregator import (AggregationOptions, Extrapolation,
                                   NotEnoughValidWindowsError)
    from ..core.metricdef import KafkaMetric
    if metric is None:
        metric = KafkaMetric.LEADER_BYTES_IN
    agg = monitor.partition_aggregator
    result = agg.aggregate(0, now_ms,
                           AggregationOptions(min_valid_windows=1),
                           use_dense=True)
    d = result.dense
    if d is None or not d.window_times_ms:
        raise NotEnoughValidWindowsError(
            "no aggregated windows to classify a regime from")
    no_valid = Extrapolation.NO_VALID_EXTRAPOLATION.value
    valid = d.extrapolations != no_valid                  # [E, W]
    vals = np.where(valid, d.values[:, metric, :], 0.0)
    wvalid = valid.any(axis=0)
    if not wvalid.any():
        raise NotEnoughValidWindowsError(
            "no valid windows to classify a regime from")
    return vals.sum(axis=0)[wvalid]


class RegimeTuningLoop:
    """Continuous tuning: regime shifts re-resolve (and, when budgeted,
    re-tune) the optimizer's schedule.

    ``trials <= 1`` pins the incumbent schedule per regime WITHOUT any
    per-candidate compiles (an empty-override record — the cheap mode
    tier-1 and the scenario-14 smoke run); ``trials > 1`` runs the full
    successive-halving tuner for the new regime's ``(bucket, regime)``
    key. Either way the optimizer's ``active_regime`` flips so its
    ``_prepare`` resolves the regime's schedule on the next optimize —
    and because tuned configs join the chain key, a shift between
    already-warm regimes never recompiles."""

    def __init__(self, optimizer, store, detector: RegimeDetector
                 | None = None, *, trials: int = 0, rungs: int = 1,
                 seed: int = 0, goals=None, constraint=None,
                 options=None, save: bool = True):
        self.optimizer = optimizer
        self.store = store
        self.detector = detector or RegimeDetector()
        self.trials = trials
        self.rungs = rungs
        self.seed = seed
        self.goals = goals
        self.constraint = constraint
        self.options = options
        self.save = save
        self.retunes = 0
        self.events: list[dict] = []
        self._seen: set[str] = set()

    def ensure_tuned(self, model, metadata, regime: str) -> dict:
        """Make sure ``(bucket, regime)`` has a store entry; returns the
        resolved field overrides. Exact-match lookup (no fallback): the
        point is to pin the regime's schedule explicitly."""
        from ..analyzer.tuning import autotune, shape_bucket
        P, B = metadata.num_partitions, metadata.num_brokers
        fields = self.store.lookup(P, B, regime=regime, fallback=False)
        if fields is not None:
            return fields
        if self.trials > 1 and model is not None:
            fields, _history, _bucket = autotune(
                model, metadata, base=self.optimizer.config,
                store=self.store, trials=self.trials, rungs=self.rungs,
                seed=self.seed, goals=self.goals,
                constraint=self.constraint, options=self.options,
                save=self.save, regime=regime)
        else:
            # Pin the incumbent schedule for this regime — zero compiles
            # now, and an explicit slot the offline tuner can improve.
            fields = {}
            self.store.record(P, B, fields, regime=regime,
                              save=self.save)
        self.retunes += 1
        LOG.info("regime %s tuned for bucket %s: %s", regime,
                 shape_bucket(P, B, regime=regime), fields or "incumbent")
        return fields

    def on_series(self, series, model, metadata,
                  now_ms: int = 0) -> dict | None:
        """One loop iteration: classify, flip ``active_regime``, tune on
        shift (or on first sight of a regime for this shape). Returns
        the event dict when anything changed, else None."""
        regime, shifted = self.detector.observe(series, now_ms)
        self.optimizer.active_regime = regime
        from ..analyzer.tuning import shape_bucket
        key = shape_bucket(metadata.num_partitions,
                           metadata.num_brokers, regime=regime)
        if not shifted and key in self._seen:
            return None
        self._seen.add(key)
        fields = self.ensure_tuned(model, metadata, regime)
        event = {"regime": regime, "shifted": shifted, "bucket": key,
                 "fields": dict(fields), "atMs": int(now_ms)}
        self.events.append(event)
        return event


class RegimeShiftDetector:
    """The scheduled serving-path hook (``tuning.regime.enabled``):
    reads the aggregate series off the live monitor each round, drives
    the tuning loop, and meters shifts/retunes. Implements the detector
    protocol (``detect(now_ms) -> []``) — a regime shift is an input to
    tuning, not an anomaly to heal, so it never raises anomalies."""

    def __init__(self, monitor, loop: RegimeTuningLoop, *,
                 model_fn=None, registry=None) -> None:
        self.monitor = monitor
        self.loop = loop
        #: optional () -> (model, metadata) supplier for full re-tuning;
        #: None = incumbent-pinning mode reading shapes off the monitor
        self.model_fn = model_fn
        if registry is not None:
            from ..core.sensors import MetricRegistry
            name = MetricRegistry.name
            self._shift_meter = registry.meter(
                name("WorkloadRegime", "shift-rate"))
            self._retune_meter = registry.meter(
                name("WorkloadRegime", "retune-rate"))
            registry.gauge(
                name("WorkloadRegime", "active-regime-code"),
                lambda: REGIMES.index(self.loop.detector.regime))
        else:
            self._shift_meter = self._retune_meter = None

    def detect(self, now_ms: int) -> list:
        from ..core.aggregator import NotEnoughValidWindowsError
        try:
            series = aggregate_series(self.monitor, now_ms)
        except NotEnoughValidWindowsError:
            return []
        model = metadata = None
        if self.model_fn is not None:
            try:
                model, metadata = self.model_fn()
            except Exception:
                model = metadata = None
        if metadata is None:
            try:
                result = self.monitor.cluster_model(now_ms)
                metadata = result.metadata
            except Exception:
                return []
        before = self.loop.retunes
        event = self.loop.on_series(series, model, metadata, now_ms)
        if event is not None:
            if event["shifted"] and self._shift_meter is not None:
                self._shift_meter.mark()
            if self.loop.retunes > before \
                    and self._retune_meter is not None:
                self._retune_meter.mark()
        return []
