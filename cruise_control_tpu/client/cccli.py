"""``cccli`` — the command-line client (rebuild of
``cruise-control-client/cruisecontrolclient/client/cccli.py:209`` and the
per-endpoint classes in ``client/Endpoint.py:158-575``).

One subcommand per endpoint, typed flags per the reference's CCParameter
validation, long-poll handling honoring the ``User-Task-ID`` header (ref
``client/Responder.py`` / ``ExecutionContext.py``): an async endpoint that
returns 202 is re-polled with the same task id until the final response.

``python -m cruise_control_tpu.client.cccli -a localhost:9090 rebalance --dryrun``
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.parse
import urllib.request

GET_ENDPOINTS = {"state", "load", "partition_load", "proposals",
                 "kafka_cluster_state", "user_tasks", "review_board",
                 "permissions", "bootstrap", "train", "openapi"}


class CruiseControlClient:
    def __init__(self, address: str, *, auth: tuple[str, str] | None = None,
                 poll_interval_s: float = 2.0, timeout_s: float = 600.0):
        self.base = f"http://{address}/kafkacruisecontrol"
        self.auth = auth
        self.poll_interval_s = poll_interval_s
        self.timeout_s = timeout_s

    def _request(self, method: str, endpoint: str, params: dict,
                 user_task_id: str | None = None):
        query = urllib.parse.urlencode(
            {k: v for k, v in params.items() if v is not None})
        url = f"{self.base}/{endpoint}"
        data = None
        if method == "GET":
            url += f"?{query}" if query else ""
        else:
            data = query.encode()
        req = urllib.request.Request(url, data=data, method=method)
        if user_task_id:
            req.add_header("User-Task-ID", user_task_id)
        if self.auth:
            import base64
            raw = base64.b64encode(f"{self.auth[0]}:{self.auth[1]}".encode())
            req.add_header("Authorization", f"Basic {raw.decode()}")
        try:
            with urllib.request.urlopen(req, timeout=120) as resp:
                raw = resp.read()
                # json=false answers server-rendered text/plain tables;
                # everything else (including every error and 202) is JSON.
                if resp.headers.get("Content-Type",
                                    "").startswith("text/plain"):
                    return resp.status, raw.decode(), dict(resp.headers)
                return resp.status, json.loads(raw), dict(resp.headers)
        except urllib.error.HTTPError as e:
            raw = e.read() or b"{}"
            # Mirror the success path: a reference-compatible server (or
            # an intermediary) may render errors as text/HTML.
            if not e.headers.get("Content-Type",
                                 "").startswith("application/json"):
                return e.code, {"errorMessage": raw.decode(errors="replace")
                                }, dict(e.headers)
            try:
                body = json.loads(raw)
            except json.JSONDecodeError:
                body = {"errorMessage": raw.decode(errors="replace")}
            return e.code, body, dict(e.headers)

    def call(self, endpoint: str, params: dict | None = None) -> dict | str:
        """Issue the request; keep long-polling 202s with the returned
        User-Task-ID until the operation completes (ref Responder.py).
        Returns the parsed JSON dict — or the raw text document when the
        request asked for ``json=false`` (server-rendered plaintext)."""
        method = "GET" if endpoint in GET_ENDPOINTS else "POST"
        params = dict(params or {})
        deadline = time.monotonic() + self.timeout_s
        status, body, headers = self._request(method, endpoint, params)
        task_id = headers.get("User-Task-ID")
        while status == 202 and task_id and "reviewResult" not in body:
            if time.monotonic() > deadline:
                raise TimeoutError(f"{endpoint} still running; "
                                   f"User-Task-ID={task_id}")
            time.sleep(self.poll_interval_s)
            status, body, headers = self._request(method, endpoint, params,
                                                  user_task_id=task_id)
        if status >= 400:
            raise RuntimeError(body.get("errorMessage", f"HTTP {status}"))
        return body


def _add_common(p: argparse.ArgumentParser, *flags: str) -> None:
    if "dryrun" in flags:
        p.add_argument("--dryrun", action="store_true", default=None)
        p.add_argument("--no-dryrun", dest="dryrun", action="store_false")
    if "goals" in flags:
        p.add_argument("--goals", help="comma-separated goal names")
    if "brokers" in flags:
        p.add_argument("--brokers", required=True,
                       help="comma-separated broker ids")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="cccli",
                                 description="cruise-control-tpu client")
    ap.add_argument("-a", "--address", required=True, help="host:port")
    ap.add_argument("--user", help="basic auth user")
    ap.add_argument("--password", help="basic auth password")
    fmt = ap.add_mutually_exclusive_group()
    fmt.add_argument("--json", action="store_true", help="raw JSON output")
    fmt.add_argument("--plaintext", action="store_true",
                     help="server-rendered fixed-width tables (json=false, "
                          "the reference's plaintext response UX)")
    sub = ap.add_subparsers(dest="endpoint", required=True)

    for name in ("state", "kafka_cluster_state", "user_tasks",
                 "review_board", "permissions", "proposals", "load", "train",
                 "openapi"):
        sub.add_parser(name)
    p = sub.add_parser("partition_load")
    p.add_argument("--resource", default="DISK")
    p.add_argument("--entries", type=int, default=20)
    p = sub.add_parser("rebalance")
    _add_common(p, "dryrun", "goals")
    p.add_argument("--ignore-proposal-cache", action="store_true")
    p.add_argument("--excluded-topics")
    for name in ("add_broker", "remove_broker", "demote_broker"):
        p = sub.add_parser(name)
        _add_common(p, "dryrun", "goals", "brokers")
    p = sub.add_parser("fix_offline_replicas")
    _add_common(p, "dryrun", "goals")
    p = sub.add_parser("topic_configuration")
    _add_common(p, "dryrun")
    p.add_argument("--topic", required=True)
    p.add_argument("--replication-factor", type=int, required=True)
    p = sub.add_parser("rightsize")
    p = sub.add_parser("remove_disks")
    _add_common(p, "dryrun")
    p.add_argument("--brokerid-and-logdirs", required=True,
                   help="<id>-<logdir>[,<id>-<logdir>...]")
    p = sub.add_parser("stop_proposal_execution")
    for name in ("pause_sampling", "resume_sampling"):
        p = sub.add_parser(name)
        p.add_argument("--reason", default="")
    p = sub.add_parser("bootstrap")
    p.add_argument("--start", type=int, required=True)
    p.add_argument("--end", type=int, required=True)
    p = sub.add_parser("review")
    p.add_argument("--approve", help="comma-separated review ids")
    p.add_argument("--discard", help="comma-separated review ids")
    p.add_argument("--reason", default="")
    p = sub.add_parser("admin")
    p.add_argument("--concurrent-partition-movements-per-broker", type=int)
    p.add_argument("--concurrent-leader-movements", type=int)
    p.add_argument("--disable-self-healing-for")
    p.add_argument("--enable-self-healing-for")
    return ap


def _params_from_args(args: argparse.Namespace) -> dict:
    skip = {"address", "user", "password", "json", "endpoint",
            "plaintext"}
    params = {}
    for k, v in vars(args).items():
        if k in skip or v is None:
            continue
        key = k.replace("-", "_")
        if key == "brokers":
            key = "brokerid"
        if isinstance(v, bool):
            v = "true" if v else "false"
        params[key] = v
    return params


def _summarize(endpoint: str, body: dict) -> str:
    if endpoint == "state":
        lines = []
        for section, payload in body.items():
            if section == "version":
                continue
            lines.append(f"{section}: "
                         f"{json.dumps(payload, default=str)[:160]}")
        return "\n".join(lines)
    if endpoint in ("rebalance", "add_broker", "remove_broker",
                    "demote_broker", "proposals", "fix_offline_replicas",
                    "topic_configuration"):
        s = body.get("summary", {})
        lines = [f"proposals: {s.get('numProposals')} "
                 f"(replica moves {s.get('numReplicaMovements')}, "
                 f"leader moves {s.get('numLeaderMovements')})"]
        for g in body.get("goalSummary", []):
            lines.append(f"  {g['goal']}: {g['status']} "
                         f"({g['violationBefore']:.1f} -> "
                         f"{g['violationAfter']:.1f})")
        for g in body.get("hardGoalAudit", []):
            lines.append(f"  [audit] {g['goal']}: {g['status']} "
                         f"({g['violationBefore']:.1f} -> "
                         f"{g['violationAfter']:.1f})")
        if "executionResult" in body:
            lines.append(f"execution: {body['executionResult']}")
        return "\n".join(lines)
    if endpoint == "load":
        lines = [f"{b['Broker']:>6} {b['BrokerState']:<6} "
                 f"replicas={b['Replicas']:<6} leaders={b['Leaders']:<6} "
                 f"disk={b['DiskMB']:.0f}MB nwIn={b['NwInRate']:.0f} "
                 f"nwOut={b['NwOutRate']:.0f} cpu={b['CpuPct']:.1f}"
                 for b in body.get("brokers", [])]
        return "BROKER STATE  LOAD\n" + "\n".join(lines)
    return json.dumps(body, indent=2, default=str)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    client = CruiseControlClient(
        args.address,
        auth=(args.user, args.password) if args.user else None)
    params = _params_from_args(args)
    if args.plaintext:
        params["json"] = "false"
    body = client.call(args.endpoint, params)
    if isinstance(body, str):             # server-rendered plaintext table
        print(body, end="" if body.endswith("\n") else "\n")
    else:
        print(json.dumps(body, indent=2, default=str) if args.json
              else _summarize(args.endpoint, body))
    return 0


if __name__ == "__main__":
    sys.exit(main())
