"""Python client (rebuild of ``cruise-control-client``): see :mod:`.cccli`."""

from .cccli import CruiseControlClient

__all__ = ["CruiseControlClient"]
