"""Cluster workload model layer (reference: ``cruise-control/.../model/``).

The reference's mutable rack->host->broker->disk->replica object graph
(``model/ClusterModel.java:48``) becomes two pieces here:

- :mod:`~cruise_control_tpu.model.spec` — a host-side, human-assemblable
  description of the cluster (brokers, racks, capacities, partitions, loads),
  playing the role of the object graph for building/serialization; and
- :mod:`~cruise_control_tpu.model.flat` — ``FlatClusterModel``, an immutable
  pytree of padded device arrays that the analyzer kernels operate on. The
  reference already sketches this layout in ``ClusterModel.utilizationMatrix()``
  (``ClusterModel.java:1332``); here it is the primary representation, not a
  derived view.
"""

from .flat import FlatClusterModel, Moves, MOVE_INTER_BROKER, MOVE_LEADERSHIP
from .spec import BrokerSpec, PartitionSpec, ClusterSpec, ClusterMetadata, flatten_spec

__all__ = [
    "FlatClusterModel", "Moves", "MOVE_INTER_BROKER", "MOVE_LEADERSHIP",
    "BrokerSpec", "PartitionSpec", "ClusterSpec", "ClusterMetadata", "flatten_spec",
]
