"""``FlatClusterModel`` — the cluster workload model as device arrays.

Rebuild of ``model/ClusterModel.java:48``. Instead of a mutable object graph
with ``relocateReplica`` (``:380``) / ``relocateLeadership`` (``:409``)
mutators, the model is an immutable pytree of padded, statically-shaped
arrays; "mutation" is the pure function :func:`apply_moves` which returns a
new model, and every read the goals need (``Load.expectedUtilizationFor``
``Load.java:81-97``, ``ClusterModel.utilizationMatrix()`` ``:1332``,
``brokerStats`` ``:1303``) is a vectorized reduction over these arrays.

Layout (P = padded partition count, R = padded max replication factor,
B = padded broker count, 4 = resources CPU/NW_IN/NW_OUT/DISK):

- ``replica_broker  int32[P, R]`` — broker index per replica; **slot 0 is the
  leader** (ref ``Partition.java`` keeps leader + follower list; we encode
  leadership positionally). Empty replica slots and padding partitions hold
  the sentinel ``B`` (one-past-last broker row) so scatter-adds land in a
  discard row.
- ``leader_load / follower_load  float32[P, 4]`` — per-partition resource
  load when hosting the leader vs a follower (ref ``Load.java``: leader
  carries CPU(leader), NW_IN, NW_OUT, DISK; followers carry CPU(follower),
  replication NW_IN, zero NW_OUT, DISK). Each entry is the reference's
  *representative* windowed value per ``KafkaMetricDef``'s
  ValueComputingStrategy (``ModelUtils.java:162`` /
  ``KafkaMetricDef.java:43-46``): AVG over valid windows for CPU/NW_IN/
  NW_OUT, LATEST valid window for DISK — so goal kernels score exactly
  what ``Load.expectedUtilizationFor(resource)`` returns. The full
  ``[entity, metric, window]`` grid stays host-side on
  ``ClusterModelResult.partition_windows`` for the max/latest-window
  consumers (``/partition_load?max_load``, anomaly detectors).
- ``partition_topic int32[P]``, ``partition_valid bool[P]``.
- ``replica_offline bool[P, R]`` — replica currently on a dead broker or bad
  disk (ref ``Replica.isCurrentOffline``); these MUST move.
- broker-side: ``broker_capacity float32[B, 4]`` (ref capacity resolver),
  ``broker_rack int32[B]``, ``broker_host int32[B]``, boolean state masks
  mirroring ``ClusterModel``'s alive/dead/new/broken sets (``:57-77``), and
  ``broker_set int32[B]`` for BrokerSetAwareGoal.

All arrays are padded to static shapes so every analyzer kernel compiles
once per (P, R, B) bucket.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..core.resources import NUM_RESOURCES

# Move types (ref ActionType.java:23-28; intra-broker variants live in the
# disk extension of Moves). MOVE_SWAP is INTER_BROKER_REPLICA_SWAP: two
# replicas of different partitions exchange brokers (count-neutral).
MOVE_INTER_BROKER = 0
MOVE_LEADERSHIP = 1
MOVE_SWAP = 2


@struct.dataclass
class FlatClusterModel:
    # --- replica/partition axis ------------------------------------------
    replica_broker: jax.Array      # int32[P, R], sentinel B for empty slots
    leader_load: jax.Array         # float32[P, 4]
    follower_load: jax.Array       # float32[P, 4]
    partition_topic: jax.Array     # int32[P]
    partition_valid: jax.Array     # bool[P]
    replica_offline: jax.Array     # bool[P, R]
    #: position of each slot's broker in Kafka's *preferred* replica order
    #: (slot 0 = current leader; pref_pos[p, 0] != 0 means leadership has
    #: drifted from the preferred replica — PLE's target state)
    replica_pref_pos: jax.Array    # int32[P, R]
    # --- broker axis ------------------------------------------------------
    broker_capacity: jax.Array     # float32[B, 4]
    broker_rack: jax.Array         # int32[B]
    broker_host: jax.Array         # int32[B]
    broker_set: jax.Array          # int32[B]
    broker_alive: jax.Array        # bool[B]  (ref Broker.State ALIVE/NEW)
    broker_new: jax.Array          # bool[B]  (ref ClusterModel.newBrokers)
    broker_demoted: jax.Array      # bool[B]  (ref DEMOTED state)
    broker_broken_disk: jax.Array  # bool[B]  (ref brokenBrokers / BAD_DISKS)
    broker_valid: jax.Array        # bool[B]  (padding mask)

    # ------------------------------------------------------------ properties
    @classmethod
    def from_numpy(cls, *, mesh=None, **arrays) -> "FlatClusterModel":
        """Build from host-side numpy arrays. The assembly point for
        every array-native construction path — ``flatten_spec``, the
        monitor's dense pipeline, bench's direct builders — which also
        makes it the ONE choke point for host->device transfer
        accounting: every model upload is metered on the PROCESS-DEFAULT
        device-runtime collector (metadata only, no sync). Deliberately
        the default, not an injected collector: a classmethod constructor
        has no wiring surface, and every production path runs on the
        default ledger — stacks built with a private collector miss
        these bytes (documented tradeoff).

        ``mesh``: place each field directly under the partition-axis
        layout (``parallel/sharding.py``: [P, ...] fields shard, broker
        fields replicate) via per-field ``jax.device_put`` — the runtime
        then ships per-device SHARDS instead of one monolithic array
        that a downstream ``shard_model`` would immediately re-lay-out;
        at 1M partitions that monolithic round trip is the host-assembly
        bottleneck the 10Kx1M tier profiles. Metered at addressable-shard
        sizes (replicated fields genuinely cost one copy per device)."""
        from ..core.runtime_obs import default_collector
        if mesh is None:
            default_collector().record_h2d(
                sum(int(a.nbytes) for a in arrays.values()
                    if isinstance(a, np.ndarray)))
            return cls(**{name: jnp.asarray(a)
                          for name, a in arrays.items()})
        from ..core.runtime_obs import device_bytes
        from ..parallel.sharding import host_array_shardings
        from .spec import check_even_sharding
        Ppad = arrays["replica_broker"].shape[0]
        check_even_sharding(Ppad, int(mesh.devices.size),
                            what="padded partition count")
        shardings = host_array_shardings(arrays, mesh, Ppad)
        placed = {name: jax.device_put(a, shardings[name])
                  for name, a in arrays.items()}
        default_collector().record_h2d(
            sum(device_bytes(placed[name]) for name, a in arrays.items()
                if isinstance(a, np.ndarray)))
        return cls(**placed)

    @property
    def num_partitions_padded(self) -> int:
        return self.replica_broker.shape[0]

    @property
    def max_replication_factor(self) -> int:
        return self.replica_broker.shape[1]

    @property
    def num_brokers_padded(self) -> int:
        return self.broker_capacity.shape[0]

    @property
    def broker_sentinel(self) -> int:
        return self.num_brokers_padded

    @property
    def replica_valid(self) -> jax.Array:
        """bool[P, R] — true where a real replica occupies the slot."""
        return self.replica_broker < self.broker_sentinel

    @property
    def leader_broker(self) -> jax.Array:
        """int32[P] — broker of the leader replica (slot 0)."""
        return self.replica_broker[:, 0]


# ---------------------------------------------------------------------------
# Derived reductions (the reads every goal kernel is built from)
# ---------------------------------------------------------------------------

def replica_loads(model: FlatClusterModel) -> jax.Array:
    """float32[P, R, 4] — the load each replica slot contributes to its broker.

    Slot 0 gets ``leader_load``, the rest ``follower_load``; empty slots get
    zeros. This is the vectorized ``Load.expectedUtilizationFor`` across the
    whole cluster (ref Load.java:81-97).
    """
    P, R = model.replica_broker.shape
    is_leader_slot = (jnp.arange(R) == 0)[None, :, None]            # [1, R, 1]
    loads = jnp.where(is_leader_slot, model.leader_load[:, None, :],
                      model.follower_load[:, None, :])               # [P, R, 4]
    return jnp.where(model.replica_valid[:, :, None], loads, 0.0)


def broker_utilization(model: FlatClusterModel) -> jax.Array:
    """float32[B, 4] — per-broker resource utilization.

    The dense equivalent of ``ClusterModel.utilizationMatrix()``
    (``ClusterModel.java:1332``), computed as one scatter-add of replica
    loads into broker rows (sentinel row dropped).
    """
    B = model.num_brokers_padded
    loads = replica_loads(model)                                     # [P, R, 4]
    flat_idx = model.replica_broker.reshape(-1)                      # [P*R]
    flat_loads = loads.reshape(-1, NUM_RESOURCES)
    util = jnp.zeros((B + 1, NUM_RESOURCES), flat_loads.dtype)
    util = util.at[flat_idx].add(flat_loads)
    return util[:B]


def broker_replica_counts(model: FlatClusterModel) -> jax.Array:
    """int32[B] — replicas per broker (ref Broker.replicas().size())."""
    B = model.num_brokers_padded
    flat_idx = model.replica_broker.reshape(-1)
    counts = jnp.zeros((B + 1,), jnp.int32).at[flat_idx].add(1)
    return counts[:B]


def broker_leader_counts(model: FlatClusterModel) -> jax.Array:
    """int32[B] — leader replicas per broker (ref Broker.leaderReplicas())."""
    B = model.num_brokers_padded
    counts = jnp.zeros((B + 1,), jnp.int32).at[model.leader_broker].add(1)
    return counts[:B]


def broker_potential_nw_out(model: FlatClusterModel) -> jax.Array:
    """float32[B] — potential leadership NW_OUT load per broker.

    Ref ``ClusterModel.potentialLeadershipLoadFor`` (``ClusterModel.java:66``,
    used by PotentialNwOutGoal): the NW_OUT the broker would serve if every
    replica it hosts became the leader of its partition.
    """
    from ..core.resources import Resource
    B = model.num_brokers_padded
    potential = model.leader_load[:, Resource.NW_OUT][:, None]       # [P, 1]
    potential = jnp.where(model.replica_valid, potential, 0.0)       # [P, R]
    flat_idx = model.replica_broker.reshape(-1)
    out = jnp.zeros((B + 1,), potential.dtype).at[flat_idx].add(potential.reshape(-1))
    return out[:B]


def topic_broker_replica_counts(model: FlatClusterModel, num_topics: int) -> jax.Array:
    """int32[T, B] — replicas of each topic on each broker.

    Backs TopicReplicaDistributionGoal / MinTopicLeadersPerBrokerGoal. Dense
    [T, B] is only materialized when the caller asks (T×B can be large); the
    scatter is a single ``at[].add`` on a flattened (topic*B' + broker) index.
    """
    B = model.num_brokers_padded
    Bp = B + 1
    topic = model.partition_topic[:, None]                           # [P, 1]
    idx = topic * Bp + model.replica_broker                          # [P, R]
    counts = jnp.zeros((num_topics * Bp,), jnp.int32).at[idx.reshape(-1)].add(
        jnp.where(model.replica_valid, 1, 0).reshape(-1),
        mode="drop")
    return counts.reshape(num_topics, Bp)[:, :B]


def topic_broker_leader_counts(model: FlatClusterModel, num_topics: int) -> jax.Array:
    """int32[T, B] — leaders of each topic on each broker."""
    B = model.num_brokers_padded
    Bp = B + 1
    idx = model.partition_topic * Bp + model.leader_broker           # [P]
    counts = jnp.zeros((num_topics * Bp,), jnp.int32).at[idx].add(
        jnp.where(model.partition_valid, 1, 0), mode="drop")
    return counts.reshape(num_topics, Bp)[:, :B]


def leader_bytes_in(model: FlatClusterModel) -> jax.Array:
    """float32[B] — leader-only NW_IN per broker (ref LeaderBytesInDistributionGoal)."""
    from ..core.resources import Resource
    B = model.num_brokers_padded
    lbi = jnp.where(model.partition_valid, model.leader_load[:, Resource.NW_IN], 0.0)
    out = jnp.zeros((B + 1,), lbi.dtype).at[model.leader_broker].add(lbi)
    return out[:B]


# ---------------------------------------------------------------------------
# Moves: the pure-functional mutation (ref relocateReplica/relocateLeadership)
# ---------------------------------------------------------------------------

@struct.dataclass
class Moves:
    """A batch of balancing actions as a struct-of-arrays.

    Equivalent of a list of ``BalancingAction`` (ref BalancingAction.java:20):
    each entry is (partition, slot, destination broker, type). For
    INTER_BROKER_REPLICA_MOVEMENT the replica in ``slot`` relocates to
    ``destination``; for LEADERSHIP_MOVEMENT the replica in ``slot`` swaps
    positions with slot 0 (becoming the leader). Inactive entries (padding)
    use ``partition == -1``.
    """

    partition: jax.Array   # int32[M]
    slot: jax.Array        # int32[M]
    destination: jax.Array  # int32[M] (ignored for leadership moves)
    kind: jax.Array        # int32[M]: MOVE_INTER_BROKER | MOVE_LEADERSHIP

    @property
    def capacity(self) -> int:
        return self.partition.shape[0]

    @property
    def active(self) -> jax.Array:
        return self.partition >= 0

    @staticmethod
    def empty(capacity: int) -> "Moves":
        return Moves(partition=jnp.full((capacity,), -1, jnp.int32),
                     slot=jnp.zeros((capacity,), jnp.int32),
                     destination=jnp.zeros((capacity,), jnp.int32),
                     kind=jnp.zeros((capacity,), jnp.int32))


def apply_moves(model: FlatClusterModel, moves: Moves) -> FlatClusterModel:
    """Apply a batch of moves, returning a new model (pure).

    Replaces the reference's in-place ``relocateReplica``
    (``ClusterModel.java:380``) and ``relocateLeadership`` (``:409``). Moves
    are applied in array order; later moves see earlier moves' effect via the
    sequential scatter semantics of ``at[].set`` only when they touch
    *different* (partition, slot) cells — the optimizer guarantees one move
    per partition per batch, so order never matters in practice.
    """
    rb = model.replica_broker
    off = model.replica_offline
    P = model.num_partitions_padded
    active = moves.active
    slot = moves.slot

    # Inactive / other-kind entries are routed to the out-of-bounds partition
    # index P and dropped by the scatter, so they can never collide with a
    # real move targeting partition 0.
    is_move = active & (moves.kind == MOVE_INTER_BROKER)
    mpart = jnp.where(is_move, moves.partition, P)
    rb = rb.at[mpart, slot].set(moves.destination, mode="drop")
    # A relocated replica is no longer offline (it moved to a live broker).
    off = off.at[mpart, slot].set(False, mode="drop")

    # Leadership transfer: swap slot <-> 0 (gathers on OOB rows clamp and are
    # harmless because the corresponding writes are dropped).
    is_lead = active & (moves.kind == MOVE_LEADERSHIP)
    lpart = jnp.where(is_lead, moves.partition, P)
    old_leader = rb[lpart, 0]
    new_leader = rb[lpart, slot]
    rb = rb.at[lpart, 0].set(new_leader, mode="drop")
    rb = rb.at[lpart, slot].set(old_leader, mode="drop")
    old_leader_off = off[lpart, 0]
    slot_off = off[lpart, slot]
    off = off.at[lpart, 0].set(slot_off, mode="drop")
    off = off.at[lpart, slot].set(old_leader_off, mode="drop")

    return model.replace(replica_broker=rb, replica_offline=off)


def validation_issue_counts(replica_broker: np.ndarray,
                            partition_valid: np.ndarray,
                            broker_valid: np.ndarray) -> dict[str, int]:
    """Vectorized structural checks over host-side arrays — the shared
    math behind :func:`sanity_check` AND the monitor's
    ``flat-model-validation-issues`` meter (the monitor calls this on the
    numpy arrays it just assembled, BEFORE the device upload, so metering
    every model build costs no device sync and no Python-per-partition
    loop). All zeros means healthy."""
    rb = np.asarray(replica_broker)
    pvalid = np.asarray(partition_valid)
    bvalid = np.asarray(broker_valid)
    sentinel = bvalid.shape[0]
    valid = rb < sentinel
    issues: dict[str, int] = {}
    # Valid partitions must have a leader in slot 0.
    issues["partitions_without_leader"] = int((pvalid & ~valid[:, 0]).sum())
    # No two replicas of one partition on the same broker: per sorted row,
    # each adjacent equal pair below the sentinel is one duplicate (the
    # count equals len(brokers) - len(set(brokers)) of the old per-row
    # loop).
    srt = np.sort(np.where(valid, rb, sentinel), axis=1)
    dup = (srt[:, 1:] == srt[:, :-1]) & (srt[:, 1:] < sentinel)
    issues["duplicate_replica_brokers"] = int(dup[pvalid].sum())
    # Replicas must sit on valid broker rows.
    on_invalid = valid & ~np.pad(bvalid, (0, 1))[rb]
    issues["replicas_on_invalid_brokers"] = int(on_invalid.sum())
    # Padding partitions must be fully empty.
    issues["padding_with_replicas"] = int((~pvalid[:, None] & valid).sum())
    return issues


def sanity_check(model: FlatClusterModel) -> dict[str, Any]:
    """Host-side invariant checks (ref ClusterModel.sanityCheck :1147).

    Returns a dict of violation counts; all zeros means healthy. NumPy-side —
    not jitted — because it is a test/debug utility (the three
    ``np.asarray`` reads below each fetch a device array).
    """
    return validation_issue_counts(np.asarray(model.replica_broker),
                                   np.asarray(model.partition_valid),
                                   np.asarray(model.broker_valid))
