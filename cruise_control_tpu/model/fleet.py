"""``FleetModel`` — many clusters' flat models stacked on a leading
``[C, ...]`` cluster axis.

One control plane balancing hundreds of clusters must not run one device
program per cluster: the fleet layer pads every member's
``FlatClusterModel`` to ONE shape bucket ``(B_f, P_f, R_f)`` (the shared
:func:`..parallel.batching.pad_model_to` re-pad — new rows arrive
invalid/empty, so a padded member scores bit-identically to its
original), stacks the members into ``[C_pad, ...]`` arrays with a
per-cluster validity mask, and hands the stack to ``fleet/engine.py``
for one batched optimize/score dispatch. The cluster axis is itself
padded to a bucket (``cluster_pad_multiple``; the fleet engine picks its
device count as the multiple) so fleets of nearby sizes reuse one
compiled program — the same bucket discipline the what-if engine applies
to its scenario axis.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.batching import pad_model_to, round_up
from .flat import FlatClusterModel


@dataclass
class FleetMember:
    """One cluster's slice of the fleet: its id, its model padded to the
    fleet bucket, and its own (un-padded, real-count) metadata."""

    cluster_id: str
    model: FlatClusterModel        # padded to the fleet bucket
    metadata: object               # ClusterMetadata (real counts)
    generation: int = 0
    stale: bool = False


@dataclass
class FleetModel:
    """Per-cluster members + the ``[C_pad, ...]`` stacked model.

    ``stacked`` is a ``FlatClusterModel`` whose every leaf carries a
    leading cluster axis; slot ``c >= num_real`` replicates member 0
    (cheap, structurally valid padding — the engine masks those slots out
    of every result). ``cluster_valid`` is the authoritative mask."""

    members: list[FleetMember]
    stacked: FlatClusterModel
    cluster_valid: np.ndarray       # bool[C_pad]
    bucket: dict = field(default_factory=dict)

    @property
    def num_clusters(self) -> int:
        return len(self.members)

    @property
    def num_clusters_padded(self) -> int:
        return int(self.cluster_valid.shape[0])

    def member_index(self, cluster_id: str) -> int:
        for i, m in enumerate(self.members):
            if m.cluster_id == cluster_id:
                return i
        raise KeyError(cluster_id)

    @classmethod
    def stack(cls, members, *, broker_pad_multiple: int = 8,
              partition_pad_multiple: int = 128,
              cluster_pad_multiple: int = 1) -> "FleetModel":
        """Stack ``members`` — an iterable of ``(cluster_id, model,
        metadata)`` or ``(cluster_id, model, metadata, generation,
        stale)`` tuples — into one fleet bucket.

        The bucket is the max padded shape over members, rounded up to
        the configured multiples (wire the SAME ``model.*.pad.multiple``
        values the monitors build with, or heterogeneous growth lands on
        off-bucket shapes and compiles extra fleet programs per step).
        """
        rows = [tuple(m) for m in members]
        if not rows:
            raise ValueError("FleetModel.stack requires at least one member")
        ids = [r[0] for r in rows]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate cluster ids in fleet: {ids}")
        models = [r[1] for r in rows]
        B_f = round_up(max(m.num_brokers_padded for m in models),
                       broker_pad_multiple)
        P_f = round_up(max(m.num_partitions_padded for m in models),
                       partition_pad_multiple)
        R_f = max(m.max_replication_factor for m in models)
        padded = [pad_model_to(m, B_f, P_f, R_f) for m in models]
        C = len(padded)
        C_pad = round_up(C, cluster_pad_multiple)
        fleet_members = []
        for r, model in zip(rows, padded):
            generation = r[3] if len(r) > 3 else 0
            stale = bool(r[4]) if len(r) > 4 else False
            fleet_members.append(FleetMember(
                cluster_id=r[0], model=model, metadata=r[2],
                generation=generation, stale=stale))
        # Padding slots replicate member 0: structurally valid arrays the
        # engine can run (and discard) without NaN hazards — an all-invalid
        # dummy would divide by zero capacities in several goal kernels.
        stack_list = padded + [padded[0]] * (C_pad - C)
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stack_list)
        cluster_valid = np.zeros(C_pad, bool)
        cluster_valid[:C] = True
        return cls(members=fleet_members, stacked=stacked,
                   cluster_valid=cluster_valid,
                   bucket={"clusters": C, "clustersPadded": C_pad,
                           "brokersPadded": B_f, "partitionsPadded": P_f,
                           "replicaSlots": R_f})
