"""Linear-regression CPU estimation (ref ``model/ModelParameters.java`` +
``LinearRegressionModelParameters.java``): the TRAIN endpoint collects
(leader bytes-in, bytes-out) -> CPU observations from broker metrics and
fits ``cpu ~ a*bytes_in + b*bytes_out (+ c)``; when trained, the monitor
can estimate partition CPU from byte rates instead of attribution."""

from __future__ import annotations

import threading

import numpy as np


class LinearRegressionModelParameters:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._obs: list[tuple[float, float, float]] = []  # (in, out, cpu)
        self.coefficients: np.ndarray | None = None       # [a, b, c]
        self.training_completed = False

    def add_observation(self, bytes_in: float, bytes_out: float,
                        cpu: float) -> None:
        with self._lock:
            self._obs.append((bytes_in, bytes_out, cpu))

    @property
    def num_observations(self) -> int:
        with self._lock:
            return len(self._obs)

    def fit(self, min_observations: int = 10) -> bool:
        with self._lock:
            if len(self._obs) < min_observations:
                return False
            arr = np.asarray(self._obs, dtype=np.float64)
            x = np.column_stack([arr[:, 0], arr[:, 1],
                                 np.ones(arr.shape[0])])
            coef, *_ = np.linalg.lstsq(x, arr[:, 2], rcond=None)
            self.coefficients = coef
            self.training_completed = True
            return True

    def estimate(self, bytes_in: float, bytes_out: float) -> float | None:
        if not self.training_completed:
            return None
        a, b, c = self.coefficients
        return float(max(a * bytes_in + b * bytes_out + c, 0.0))

    def to_json(self) -> dict:
        return {"trainingCompleted": self.training_completed,
                "numObservations": self.num_observations,
                "coefficients": (None if self.coefficients is None
                                 else [float(v) for v in self.coefficients])}
