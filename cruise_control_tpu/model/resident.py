"""Device-resident cluster state with incremental metric-delta ingest.

Every propose cycle used to reassemble ``FlatClusterModel`` host-side and
re-upload the ENTIRE padded tensor set through ``from_numpy`` — even when
only a sliver of metric windows changed since the last cycle (the
``transfer_bytes_per_cycle`` waste PR 6's accounting made visible).
:class:`ResidentClusterState` keeps the canonical model **resident on
device** and splits updates into two regimes:

- **Metric-only cycles** (the steady state): the monitor's dense
  assembler produces the same host arrays it always did; this class diffs
  the load planes (``leader_load``/``follower_load``) against its host
  mirrors, uploads only the changed partition rows as a compact
  ``(idx, leader_rows, follower_rows)`` payload, and applies them with
  ONE jitted scatter program (``resident.delta-ingest``, a generalization
  of the PR 2 dense-ingest scatter). Unchanged structural arrays —
  replica placement, topology masks, broker axes — are literally the same
  device buffers cycle after cycle. A cycle whose arrays are all
  unchanged uploads nothing at all (a ``noop``).
- **Structural cycles**: any change outside the load planes (broker
  add/remove/death, partition add/remove, leadership or placement drift,
  capacity/rack/broker-set change, padded-shape change) bumps the
  **epoch** and falls back to one full rebuild + upload — correctness
  first, the delta path never guesses about topology.

Parity is by construction: the delta scatter writes the exact float32
rows the full rebuild would have uploaded, so N delta cycles produce a
model bit-identical to a from-scratch build (property-tested in
``tests/test_resident.py``).

Delta payloads are padded to power-of-two row buckets (floor
``delta_pad_multiple``) so the scatter compiles O(log P) programs, not
one per delta size; :meth:`warmup` pre-compiles the smallest bucket at
startup so steady-state cycles dispatch with zero compiles (the tier-1
resident gate asserts exactly that through ``/devicestats``).

Memory note: the host mirrors double the model's host-side footprint
(they are the previous cycle's assembled arrays, kept by reference — the
assembler builds fresh arrays every cycle and this class takes ownership;
callers must not mutate arrays after passing them in).
"""

from __future__ import annotations

import logging
import threading

import numpy as np

LOG = logging.getLogger(__name__)

#: sensor group for the resident-state series (``ResidentState.*``).
RESIDENT_SENSOR = "ResidentState"

#: the two per-partition load planes the delta path may update; every
#: other ``from_numpy`` field is structural and forces an epoch bump.
METRIC_FIELDS = ("leader_load", "follower_load")


def _same(a: np.ndarray, b: np.ndarray) -> bool:
    """Exact array equality (NaN == NaN), shape/dtype included."""
    if a.shape != b.shape or a.dtype != b.dtype:
        return False
    if np.issubdtype(a.dtype, np.floating):
        return bool(np.array_equal(a, b, equal_nan=True))
    return bool(np.array_equal(a, b))


def _changed_rows(new: np.ndarray, old: np.ndarray) -> np.ndarray:
    """bool[P] — rows whose values differ (NaN-aware, exact)."""
    eq = (new == old) | (np.isnan(new) & np.isnan(old))
    return ~eq.all(axis=1)


def _delta_scatter(lead, foll, idx, lead_rows, foll_rows):
    """The jitted delta-ingest program: scatter the changed load rows
    into the resident planes. Padding entries carry an out-of-bounds
    index (``P``) and are dropped, so one compiled program serves any
    delta size within its row bucket."""
    return (lead.at[idx].set(lead_rows, mode="drop"),
            foll.at[idx].set(foll_rows, mode="drop"))


class ResidentClusterState:
    """Owns the device-side ``FlatClusterModel`` buffers + epoch counter.

    Thread-safe (the monitor allows concurrent model builds); one
    instance per monitor. ``update`` is the single write path — it
    returns the resident model the caller should serve.
    """

    def __init__(self, *, registry=None, collector=None, tracer=None,
                 delta_pad_multiple: int = 512, mesh=None) -> None:
        import jax

        from ..core.runtime_obs import default_collector
        from ..core.sensors import MetricRegistry
        from ..core.tracing import default_tracer
        self.collector = collector or default_collector()
        self.tracer = tracer or default_tracer()
        self.registry = registry or MetricRegistry()
        #: smallest delta row bucket; buckets double up to the padded
        #: partition count, bounding compiled scatter variants to
        #: O(log P) while keeping small steady-state deltas in ONE
        #: pre-warmable bucket.
        self.delta_pad_multiple = int(delta_pad_multiple)
        #: optional jax.sharding.Mesh: full rebuilds upload per-device
        #: SHARDS straight into the partition-axis layout
        #: (from_numpy(mesh=...)), so the resident buffers are already
        #: laid out for the sharded optimizer/what-if programs and no
        #: cycle ever re-shards them; the delta scatter runs on the
        #: sharded planes (GSPMD partitions the row scatter, payloads
        #: replicate — they are KB-sized).
        self.mesh = mesh
        self._lock = threading.Lock()
        self._model = None                      # FlatClusterModel | None
        self._host: dict[str, np.ndarray] = {}  # host mirrors, by field
        #: bumps on every structural full rebuild; 0 = nothing resident yet
        self.epoch = 0
        #: counts every state-changing ingest (full rebuild or delta; a
        #: noop leaves it alone) — the contiguity chain replication
        #: frames carry (core/replication.py): a replica applies a delta
        #: only when its own ingest_seq equals the delta's base.
        self.ingest_seq = 0
        #: replica-side deltas applied via :meth:`apply_delta`
        self.applied_deltas = 0
        #: leader-side capture log for the stream publisher; None until
        #: :meth:`enable_delta_capture` (the default path pays nothing).
        self._delta_log: list | None = None
        self._delta_log_limit = 0
        self._delta_overflow = False
        self.full_rebuilds = 0
        self.delta_cycles = 0
        self.noop_cycles = 0
        self.restores = 0
        self.last_update: str | None = None      # "full" | "delta" | "noop"
        self.last_delta_rows = 0
        self.last_delta_bytes = 0
        self.last_full_bytes = 0
        self._scatter = self.collector.track(
            "resident.delta-ingest", jax.jit(_delta_scatter))
        name = MetricRegistry.name
        g = RESIDENT_SENSOR
        self._full_counter = self.registry.counter(name(g, "full-rebuilds"))
        self._delta_counter = self.registry.counter(name(g, "delta-cycles"))
        self._noop_counter = self.registry.counter(name(g, "noop-cycles"))
        self.registry.gauge(name(g, "epoch"), lambda: self.epoch)
        self.registry.gauge(name(g, "last-delta-rows"),
                            lambda: self.last_delta_rows)
        self.registry.gauge(name(g, "last-delta-bytes"),
                            lambda: self.last_delta_bytes)

    # ------------------------------------------------------------- update
    @property
    def model(self):
        """The resident :class:`FlatClusterModel` (None before the first
        build/restore). The replication follower-serving path reads this
        directly: on a stream-fed replica the resident state IS the
        serving model — no local sample history exists to rebuild from."""
        return self._model

    def update(self, arrays: dict[str, np.ndarray]):
        """Fold one assembled cycle into the resident state.

        ``arrays`` is exactly the ``FlatClusterModel.from_numpy`` kwarg
        set the dense assembler produces (ownership transfers — the
        caller must not mutate them afterwards). Returns the resident
        ``FlatClusterModel``.
        """
        with self._lock, self.tracer.span("resident.update") as sp:
            structural = self._model is None or any(
                not _same(arrays[f], self._host[f])
                for f in arrays if f not in METRIC_FIELDS)
            if structural:
                self._full_rebuild(arrays)
            else:
                self._metric_delta(arrays)
            sp.set(update=self.last_update, epoch=self.epoch,
                   rows=self.last_delta_rows)
            return self._model

    def _full_rebuild(self, arrays: dict[str, np.ndarray]) -> None:
        from .flat import FlatClusterModel
        self.epoch += 1
        self.ingest_seq += 1
        if self._delta_log is not None:
            # A structural rebuild cannot ship as a delta: drop the
            # pending entries and leave a marker so the publisher tells
            # followers to resync from the next snapshot.
            self._delta_log.clear()
            self._delta_log.append({"structural": True,
                                    "ingest": self.ingest_seq,
                                    "epoch": self.epoch})
        self.full_rebuilds += 1
        self._full_counter.inc()
        self._model = FlatClusterModel.from_numpy(mesh=self.mesh, **arrays)
        self._host = dict(arrays)
        self.last_update = "full"
        self.last_delta_rows = 0
        self.last_delta_bytes = 0
        self.last_full_bytes = sum(int(a.nbytes) for a in arrays.values())
        LOG.info("resident state epoch %d: full rebuild (%d bytes uploaded)",
                 self.epoch, self.last_full_bytes)

    def _metric_delta(self, arrays: dict[str, np.ndarray]) -> None:
        lead, foll = arrays["leader_load"], arrays["follower_load"]
        changed = (_changed_rows(lead, self._host["leader_load"])
                   | _changed_rows(foll, self._host["follower_load"]))
        rows = np.nonzero(changed)[0]
        if rows.size == 0:
            self.noop_cycles += 1
            self._noop_counter.inc()
            self.last_update = "noop"
            self.last_delta_rows = 0
            self.last_delta_bytes = 0
            return
        P = lead.shape[0]
        K = self._bucket(int(rows.size), P)
        # Padding rows point one past the partition axis; the scatter's
        # drop mode discards them, so the payload stays dense and the
        # program compiles once per (P, K) bucket.
        idx = np.full(K, P, np.int32)
        idx[:rows.size] = rows
        lead_rows = np.zeros((K, lead.shape[1]), lead.dtype)
        lead_rows[:rows.size] = lead[rows]
        foll_rows = np.zeros((K, foll.shape[1]), foll.dtype)
        foll_rows[:rows.size] = foll[rows]
        nbytes = idx.nbytes + lead_rows.nbytes + foll_rows.nbytes
        self.collector.record_h2d(nbytes)
        new_lead, new_foll = self._scatter(
            self._model.leader_load, self._model.follower_load,
            idx, lead_rows, foll_rows)
        self._model = self._model.replace(leader_load=new_lead,
                                          follower_load=new_foll)
        self._host["leader_load"] = lead
        self._host["follower_load"] = foll
        base = self.ingest_seq
        self.ingest_seq += 1
        if self._delta_log is not None:
            # The padded payload arrays are freshly built and never
            # mutated after the scatter — safe to share by reference.
            self._delta_log.append({
                "structural": False, "baseIngest": base,
                "ingest": self.ingest_seq, "epoch": self.epoch,
                "idx": rows.astype(np.int32),
                "lead": lead_rows[:rows.size],
                "foll": foll_rows[:rows.size]})
            while len(self._delta_log) > self._delta_log_limit:
                self._delta_log.pop(0)
                self._delta_overflow = True
        self.delta_cycles += 1
        self._delta_counter.inc()
        self.last_update = "delta"
        self.last_delta_rows = int(rows.size)
        self.last_delta_bytes = int(nbytes)

    def _bucket(self, n: int, padded: int) -> int:
        k = self.delta_pad_multiple
        while k < n:
            k *= 2
        return min(k, padded)

    # ------------------------------------------------- delta streaming
    def enable_delta_capture(self, limit: int = 64) -> None:
        """Start logging metric-delta payloads for the replication
        publisher (core/replication.py). ``limit`` bounds host memory:
        overflow drops the oldest entries and flags the drain, which the
        publisher turns into a follower resync marker."""
        with self._lock:
            self._delta_log_limit = int(limit)
            if self._delta_log is None:
                self._delta_log = []

    def drain_deltas(self) -> tuple[list, bool]:
        """``(entries, overflowed)``: the captured delta entries since
        the last drain (ownership transfers to the caller). Entries are
        ingest-chained dicts — see ``_metric_delta`` / ``_full_rebuild``
        for the two shapes."""
        with self._lock:
            if self._delta_log is None:
                return [], False
            entries, self._delta_log = self._delta_log, []
            overflow, self._delta_overflow = self._delta_overflow, False
            return entries, overflow

    def apply_delta(self, entry: dict) -> bool:
        """Replica-side ingest of one streamed delta entry: scatter the
        rows into the resident device planes and the host mirrors,
        exactly as the leader's ``_metric_delta`` did. Applies ONLY when
        contiguous (same epoch, ``baseIngest`` equals this replica's
        ``ingest_seq``) — anything else returns False and the caller
        must resync from a full snapshot; a divergent model is never
        served."""
        with self._lock:
            if (self._model is None or entry.get("structural")
                    or int(entry.get("epoch", -1)) != self.epoch
                    or int(entry.get("baseIngest", -1)) != self.ingest_seq):
                return False
            idx = np.asarray(entry["idx"], np.int32)
            lead_rows = np.asarray(entry["lead"])
            foll_rows = np.asarray(entry["foll"])
            n = int(idx.size)
            host_lead = self._host["leader_load"]
            P = host_lead.shape[0]
            K = self._bucket(n, P)
            pidx = np.full(K, P, np.int32)
            pidx[:n] = idx
            plead = np.zeros((K, lead_rows.shape[1]), host_lead.dtype)
            plead[:n] = lead_rows
            pfoll = np.zeros((K, foll_rows.shape[1]),
                             self._host["follower_load"].dtype)
            pfoll[:n] = foll_rows
            self.collector.record_h2d(
                pidx.nbytes + plead.nbytes + pfoll.nbytes)
            new_lead, new_foll = self._scatter(
                self._model.leader_load, self._model.follower_load,
                pidx, plead, pfoll)
            self._model = self._model.replace(leader_load=new_lead,
                                              follower_load=new_foll)
            # Host mirrors are replaced wholesale, never mutated in
            # place (snapshot export shares them by reference).
            for field, rows_arr in (("leader_load", lead_rows),
                                    ("follower_load", foll_rows)):
                mirror = self._host[field].copy()
                mirror[idx] = rows_arr
                self._host[field] = mirror
            self.ingest_seq = int(entry["ingest"])
            self.applied_deltas += 1
            self.delta_cycles += 1
            self._delta_counter.inc()
            self.last_update = "delta"
            self.last_delta_rows = n
            return True

    # -------------------------------------------------- snapshot/restore
    def export_state(self) -> tuple[int, dict[str, np.ndarray]] | None:
        """``(epoch, host mirrors)`` for the crash-safe snapshot
        (core/snapshot.py), or None before the first full rebuild. The
        mirrors are returned by reference — they are never mutated in
        place (update() replaces them wholesale), so the snapshot writer
        may serialize them without copying."""
        with self._lock:
            if self._model is None:
                return None
            return self.epoch, dict(self._host)

    def restore(self, epoch: int, arrays: dict[str, np.ndarray], *,
                ingest_seq: int | None = None) -> None:
        """Rebuild the resident device buffers from a snapshot's host
        mirrors. The device model is bit-identical to the pre-crash one
        by construction (``from_numpy`` is deterministic over the same
        host arrays); the epoch resumes at ``max(saved, current)`` so
        post-restore structural changes still bump monotonically. Counts
        as a ``restore``, not a full rebuild — dashboards can tell a
        warm restart from a structural churn storm."""
        from .flat import FlatClusterModel
        with self._lock, self.tracer.span("resident.restore"):
            self._model = FlatClusterModel.from_numpy(mesh=self.mesh,
                                                      **arrays)
            self._host = dict(arrays)
            self.epoch = max(self.epoch, int(epoch))
            if ingest_seq is not None:
                # Rejoining a replication stream: the snapshot pins the
                # contiguity chain position the next delta must extend.
                self.ingest_seq = int(ingest_seq)
            if self._delta_log is not None:
                self._delta_log.clear()
                self._delta_overflow = False
            self.restores += 1
            self.last_update = "restore"
            self.last_delta_rows = 0
            self.last_delta_bytes = 0
            self.last_full_bytes = sum(int(a.nbytes)
                                       for a in arrays.values())
            LOG.info("resident state restored from snapshot (epoch %d, "
                     "%d bytes uploaded)", self.epoch, self.last_full_bytes)

    # ------------------------------------------------------------ warmup
    def warmup(self) -> bool:
        """Pre-compile the delta-ingest program for the smallest row
        bucket against the current resident shapes (an all-dropped
        scatter — no state change), so the first real metric-only cycle
        after startup dispatches with zero compiles. No-op (returns
        False) before the first full rebuild."""
        with self._lock:
            if self._model is None:
                return False
            lead = self._host["leader_load"]
            P = lead.shape[0]
            K = self._bucket(1, P)
            idx = np.full(K, P, np.int32)
            zeros = np.zeros((K, lead.shape[1]), lead.dtype)
            self._scatter(self._model.leader_load,
                          self._model.follower_load, idx, zeros, zeros)
            return True

    # ------------------------------------------------------------- reads
    # Deliberately lockless: an observability scrape (/devicestats,
    # /state) must never block behind an in-flight structural rebuild —
    # at roadmap scale that upload takes whole seconds, exactly during
    # the topology event the operator is trying to observe. Reads are
    # single attribute loads (GIL-atomic); a scrape racing an update may
    # see a transiently mixed view (epoch bumped, lastUpdate not yet) —
    # a documented non-issue for counters.
    @property
    def model(self):
        return self._model

    def invalidate(self) -> None:
        """Drop the resident buffers; the next update is a full rebuild
        (epoch bump)."""
        with self._lock:
            self._model = None
            self._host = {}

    def to_json(self) -> dict:
        """The ``resident`` section of ``/devicestats`` (lockless — see
        the reads note above)."""
        model = self._model
        out = {
            "epoch": self.epoch,
            "ingestSeq": self.ingest_seq,
            "appliedDeltas": self.applied_deltas,
            "fullRebuilds": self.full_rebuilds,
            "deltaCycles": self.delta_cycles,
            "noopCycles": self.noop_cycles,
            "restores": self.restores,
            "lastUpdate": self.last_update,
            "lastDeltaRows": self.last_delta_rows,
            "lastDeltaBytes": self.last_delta_bytes,
            "lastFullBytes": self.last_full_bytes,
        }
        if model is not None:
            out["shapes"] = {
                "partitionsPadded": model.num_partitions_padded,
                "brokersPadded": model.num_brokers_padded,
                "maxReplicationFactor": model.max_replication_factor,
            }
        return out
