"""Cluster-level statistics (ref ``model/ClusterModelStats.java``).

Per-resource average / standard deviation / max / min of broker utilization
across alive brokers, plus replica- and leader-count statistics — the numbers
goal comparators compare (ref ``ClusterModelStats`` fields consumed by
``Goal.clusterModelStatsComparator``) and the payload of ``brokerStats``
(``ClusterModel.java:1303``) responses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.resources import NUM_RESOURCES, RESOURCE_NAMES
from .flat import (FlatClusterModel, broker_leader_counts, broker_replica_counts,
                   broker_utilization, broker_potential_nw_out)


def cluster_stats(model: FlatClusterModel) -> dict[str, jax.Array]:
    """Device-side stats pytree. All entries are computed over alive brokers
    (dead/padding brokers excluded, matching ref ClusterModelStats which
    iterates aliveBrokers)."""
    util = broker_utilization(model)                      # [B, 4]
    replicas = broker_replica_counts(model)               # [B]
    leaders = broker_leader_counts(model)                 # [B]
    potential_out = broker_potential_nw_out(model)        # [B]
    alive = model.broker_alive & model.broker_valid
    n = jnp.maximum(alive.sum(), 1)

    def _stats(values: jax.Array) -> dict[str, jax.Array]:
        # Mask along the broker axis (axis 0) regardless of value rank.
        mask = alive[:, None] if values.ndim > 1 else alive
        masked = jnp.where(mask, values, 0.0)
        mean = masked.sum(axis=0) / n
        var = jnp.where(mask, (values - mean) ** 2, 0.0).sum(axis=0) / n
        big = jnp.where(mask, values, -jnp.inf).max(axis=0)
        small = jnp.where(mask, values, jnp.inf).min(axis=0)
        return {"avg": mean, "std": jnp.sqrt(var), "max": big, "min": small}

    util_stats = _stats(util)
    return {
        "num_alive_brokers": alive.sum(),
        "utilization": util,
        "resource": util_stats,                            # each entry [4]
        "replica_count": _stats(replicas.astype(jnp.float32)),
        "leader_count": _stats(leaders.astype(jnp.float32)),
        "potential_nw_out": _stats(potential_out),
        "num_replicas": jnp.where(model.replica_valid, 1, 0).sum(),
        "num_leaders": jnp.where(model.partition_valid, 1, 0).sum(),
    }


def resource_cv(stats: dict[str, jax.Array]) -> jax.Array:
    """Coefficient of variation per resource — the reference's balance metric
    (``ClusterModelStats.variance()`` normalized, cf. ClusterModel.java:1315)."""
    res = stats["resource"]
    return res["std"] / jnp.maximum(res["avg"], 1e-9)


def stats_summary(model: FlatClusterModel) -> dict:
    """Host-side JSON-friendly summary (for /state and /load responses)."""
    stats = jax.device_get(cluster_stats(model))
    out = {"numAliveBrokers": int(stats["num_alive_brokers"]),
           "numReplicas": int(stats["num_replicas"]),
           "numLeaders": int(stats["num_leaders"]),
           "resources": {}}
    for r in range(NUM_RESOURCES):
        out["resources"][RESOURCE_NAMES[r]] = {
            "avg": float(stats["resource"]["avg"][r]),
            "std": float(stats["resource"]["std"][r]),
            "max": float(stats["resource"]["max"][r]),
            "min": float(stats["resource"]["min"][r]),
        }
    for key in ("replica_count", "leader_count", "potential_nw_out"):
        out[key] = {k: float(v) for k, v in stats[key].items()}
    return out
