"""Host-side cluster description and flattening into ``FlatClusterModel``.

``ClusterSpec`` plays the role of the reference's object-graph building path
(``LoadMonitor.clusterModel`` ``LoadMonitor.java:439`` populating
``ClusterModel.createReplica``/``setReplicaLoad``): it is what the monitor
layer assembles from aggregated samples + capacity/rack metadata, what tests
hand-build (like the reference's ``DeterministicCluster``), and what the API
layer serializes. :func:`flatten_spec` turns it into padded device arrays.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from ..core.resources import NUM_RESOURCES, Resource


def _round_up(n: int, multiple: int) -> int:
    if n <= 0:
        return multiple
    return ((n + multiple - 1) // multiple) * multiple


def check_even_sharding(count: int, n_devices: int, *, what: str,
                        exc: type = ValueError) -> None:
    """The partition axis must split EVENLY across a mesh —
    ``jax.device_put`` rejects uneven shardings with an error naming
    neither the knob nor the fix (layout rule: parallel/sharding.py).
    One definition shared by the config parse check, the startup
    re-check with the resolved device count, and the sharded upload
    path, so the rule can never drift between them. Lives here (not in
    ``parallel/``) so config parsing stays jax-import-free."""
    if n_devices and count % n_devices:
        raise exc(
            f"{what}={count} is not divisible by the mesh device count "
            f"{n_devices}: padded partition counts could not shard "
            "evenly across the mesh (every model placement would "
            "fail). Pick a value divisible by the device count — the "
            "default pad multiple 128 works for any power-of-two mesh "
            "up to 128 (docs/scaling.md).")


@dataclass
class BrokerSpec:
    """One broker (ref ``model/Broker.java``): identity, placement, capacity,
    liveness state."""

    broker_id: int
    rack: str
    host: str | None = None
    capacity: Sequence[float] = (100.0, 10_000.0, 10_000.0, 100_000.0)  # ref config/capacity.json default
    alive: bool = True
    new: bool = False
    demoted: bool = False
    broken_disk: bool = False
    broker_set: str | None = None


@dataclass
class PartitionSpec:
    """One partition (ref ``model/Partition.java``): replica broker list with
    the leader first, plus the leader/follower resource loads."""

    topic: str
    partition: int
    replicas: Sequence[int]                      # broker ids, leader first
    leader_load: Sequence[float] = (0.0, 0.0, 0.0, 0.0)    # CPU,NW_IN,NW_OUT,DISK
    follower_load: Sequence[float] | None = None  # default derived from leader
    offline_replicas: Sequence[int] = ()          # broker ids currently offline
    #: Kafka's *preferred* replica order (the assignment list). When the
    #: current leader (replicas[0]) has drifted from the preferred leader
    #: (preferred_replicas[0]), PreferredLeaderElectionGoal restores it.
    #: None = current order is the preferred order.
    preferred_replicas: Sequence[int] | None = None

    def derived_follower_load(self) -> tuple[float, ...]:
        """Follower load derived from leader load when not given explicitly.

        Ref ``Load``/``SamplingUtils``: followers replicate the leader's
        bytes-in (NW_IN), serve no client traffic (NW_OUT = 0), consume a
        fraction of leader CPU (``ModelUtils.FOLLOWER_CPU_RATIO``-style
        estimate), and hold the same DISK footprint.
        """
        if self.follower_load is not None:
            return tuple(self.follower_load)
        cpu, nw_in, _nw_out, disk = self.leader_load
        return (0.5 * cpu, nw_in, 0.0, disk)


@dataclass
class ClusterSpec:
    brokers: list[BrokerSpec] = field(default_factory=list)
    partitions: list[PartitionSpec] = field(default_factory=list)

    def broker_ids(self) -> list[int]:
        return [b.broker_id for b in self.brokers]

    def topics(self) -> list[str]:
        seen: dict[str, None] = {}
        for p in self.partitions:
            seen.setdefault(p.topic, None)
        return list(seen)

    def max_replication_factor(self) -> int:
        return max((len(p.replicas) for p in self.partitions), default=1)


@dataclass
class ClusterMetadata:
    """Host-side lookup tables pairing a ``FlatClusterModel`` with names.

    Keeps the string/broker-id world out of the device arrays: broker row ->
    broker id, topic id -> topic name, partition row -> (topic, partition).
    """

    broker_ids: list[int]
    broker_index: dict[int, int]
    topics: list[str]
    topic_index: dict[str, int]
    partition_keys: list[tuple[str, int]]
    partition_index: dict[tuple[str, int], int]
    racks: list[str]
    hosts: list[str]
    broker_sets: list[str]

    @property
    def num_brokers(self) -> int:
        return len(self.broker_ids)

    @property
    def num_partitions(self) -> int:
        return len(self.partition_keys)

    @property
    def num_topics(self) -> int:
        return len(self.topics)


@dataclass
class BrokerArrays:
    """The broker half of a flattened model: padded numpy arrays plus the
    id/name lookup tables. Shared by :func:`flatten_spec` and the
    monitor's dense pipeline (which builds partition arrays by whole-array
    gathers and only needs the broker axis flattened once)."""

    broker_ids: list[int]
    broker_index: dict[int, int]
    racks: list[str]
    hosts: list[str]
    broker_sets: list[str]
    capacity: np.ndarray   # float32[Bpad, 4]
    rack: np.ndarray       # int32[Bpad]
    host: np.ndarray       # int32[Bpad]
    broker_set: np.ndarray  # int32[Bpad]
    alive: np.ndarray      # bool[Bpad]
    new: np.ndarray        # bool[Bpad]
    demoted: np.ndarray    # bool[Bpad]
    broken: np.ndarray     # bool[Bpad]
    valid: np.ndarray      # bool[Bpad]

    @property
    def padded(self) -> int:
        return self.capacity.shape[0]


def flatten_brokers(brokers: list[BrokerSpec], *,
                    pad_brokers_to: int | None = None,
                    broker_pad_multiple: int = 8) -> BrokerArrays:
    """Flatten the broker axis of a model into :class:`BrokerArrays`."""
    broker_ids = [b.broker_id for b in brokers]
    broker_index = {bid: i for i, bid in enumerate(broker_ids)}
    if len(broker_index) != len(broker_ids):
        raise ValueError("duplicate broker ids in spec")

    racks: list[str] = []
    rack_index: dict[str, int] = {}
    hosts: list[str] = []
    host_index: dict[str, int] = {}
    broker_sets: list[str] = []
    broker_set_index: dict[str, int] = {}

    B = len(broker_ids)
    Bpad = pad_brokers_to or _round_up(B, broker_pad_multiple)
    if Bpad < B:
        raise ValueError("pad_brokers_to smaller than broker count")

    out = BrokerArrays(
        broker_ids=broker_ids, broker_index=broker_index,
        racks=racks, hosts=hosts, broker_sets=broker_sets,
        capacity=np.zeros((Bpad, NUM_RESOURCES), np.float32),
        rack=np.zeros(Bpad, np.int32),
        host=np.zeros(Bpad, np.int32),
        broker_set=np.full(Bpad, -1, np.int32),
        alive=np.zeros(Bpad, bool),
        new=np.zeros(Bpad, bool),
        demoted=np.zeros(Bpad, bool),
        broken=np.zeros(Bpad, bool),
        valid=np.zeros(Bpad, bool))

    for i, b in enumerate(brokers):
        out.capacity[i] = np.asarray(b.capacity, np.float32)
        if b.rack not in rack_index:
            rack_index[b.rack] = len(racks)
            racks.append(b.rack)
        out.rack[i] = rack_index[b.rack]
        host = b.host if b.host is not None else f"host-{b.broker_id}"
        if host not in host_index:
            host_index[host] = len(hosts)
            hosts.append(host)
        out.host[i] = host_index[host]
        if b.broker_set is not None:
            if b.broker_set not in broker_set_index:
                broker_set_index[b.broker_set] = len(broker_sets)
                broker_sets.append(b.broker_set)
            out.broker_set[i] = broker_set_index[b.broker_set]
        out.alive[i] = b.alive
        out.new[i] = b.new
        out.demoted[i] = b.demoted
        out.broken[i] = b.broken_disk
        out.valid[i] = True
    return out


def flatten_spec(spec: ClusterSpec, *, pad_partitions_to: int | None = None,
                 pad_brokers_to: int | None = None,
                 pad_rf_to: int | None = None,
                 partition_pad_multiple: int = 128,
                 broker_pad_multiple: int = 8):
    """Flatten a ``ClusterSpec`` into (FlatClusterModel, ClusterMetadata).

    Shapes are padded (partitions to a multiple of ``partition_pad_multiple``,
    brokers to ``broker_pad_multiple``) so repeated model builds for a slowly
    growing cluster hit the same compiled analyzer kernels.
    """
    from .flat import FlatClusterModel

    ba = flatten_brokers(spec.brokers, pad_brokers_to=pad_brokers_to,
                         broker_pad_multiple=broker_pad_multiple)
    broker_ids, broker_index = ba.broker_ids, ba.broker_index
    Bpad = ba.padded

    topics = []
    topic_index: dict[str, int] = {}
    partition_keys: list[tuple[str, int]] = []
    P = len(spec.partitions)
    Ppad = pad_partitions_to or _round_up(P, partition_pad_multiple)
    if Ppad < P:
        raise ValueError("pad_partitions_to smaller than partition count")
    R = max(spec.max_replication_factor(), 1)
    Rpad = pad_rf_to or R
    if Rpad < R:
        raise ValueError("pad_rf_to smaller than max replication factor")

    sentinel = Bpad
    rb = np.full((Ppad, Rpad), sentinel, np.int32)
    lead_load = np.zeros((Ppad, NUM_RESOURCES), np.float32)
    foll_load = np.zeros((Ppad, NUM_RESOURCES), np.float32)
    ptopic = np.full(Ppad, -1, np.int32)
    pvalid = np.zeros(Ppad, bool)
    offline = np.zeros((Ppad, Rpad), bool)
    # Position of each slot's broker in the preferred order; default = slot
    # index (current order == preferred order).
    pref_pos = np.tile(np.arange(Rpad, dtype=np.int32), (Ppad, 1))

    for p, part in enumerate(spec.partitions):
        key = (part.topic, part.partition)
        partition_keys.append(key)
        if part.topic not in topic_index:
            topic_index[part.topic] = len(topics)
            topics.append(part.topic)
        ptopic[p] = topic_index[part.topic]
        pvalid[p] = True
        if len(set(part.replicas)) != len(part.replicas):
            raise ValueError(f"partition {key}: duplicate replica brokers")
        offline_ids = set(part.offline_replicas)
        pref = (list(part.preferred_replicas)
                if part.preferred_replicas is not None else None)
        if pref is not None and sorted(pref) != sorted(part.replicas):
            raise ValueError(
                f"partition {key}: preferred_replicas must be a permutation "
                "of replicas")
        for r, bid in enumerate(part.replicas):
            if bid not in broker_index:
                raise ValueError(f"partition {key}: unknown broker {bid}")
            rb[p, r] = broker_index[bid]
            offline[p, r] = bid in offline_ids
            if pref is not None:
                pref_pos[p, r] = pref.index(bid)
        lead_load[p] = np.asarray(part.leader_load, np.float32)
        foll_load[p] = np.asarray(part.derived_follower_load(), np.float32)

    partition_index = {key: i for i, key in enumerate(partition_keys)}
    if len(partition_index) != len(partition_keys):
        raise ValueError("duplicate (topic, partition) in spec")

    model = FlatClusterModel.from_numpy(
        replica_broker=rb,
        leader_load=lead_load,
        follower_load=foll_load,
        partition_topic=ptopic,
        partition_valid=pvalid,
        replica_offline=offline,
        replica_pref_pos=pref_pos,
        broker_capacity=ba.capacity,
        broker_rack=ba.rack,
        broker_host=ba.host,
        broker_set=ba.broker_set,
        broker_alive=ba.alive,
        broker_new=ba.new,
        broker_demoted=ba.demoted,
        broker_broken_disk=ba.broken,
        broker_valid=ba.valid,
    )
    metadata = ClusterMetadata(
        broker_ids=broker_ids,
        broker_index=broker_index,
        topics=topics,
        topic_index=topic_index,
        partition_keys=partition_keys,
        partition_index=partition_index,
        racks=ba.racks,
        hosts=ba.hosts,
        broker_sets=ba.broker_sets,
    )
    return model, metadata
