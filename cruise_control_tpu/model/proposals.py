"""Execution proposals: the diff between two cluster models.

Rebuild of ``ExecutionProposal`` and ``AnalyzerUtils.getDiff``
(ref ``GoalOptimizer.java:508-513``): compare the initial and optimized
replica placements and emit, per changed partition, the (old leader, old
replica list, new replica list) triple the executor consumes.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from .flat import FlatClusterModel
from .spec import ClusterMetadata


class ExecutionProposal(NamedTuple):
    """One partition's reassignment (ref executor/ExecutionProposal.java).

    A NamedTuple rather than a dataclass: a 10Kx1M rebalance emits ~500K
    proposals and tuple construction is ~5x cheaper than frozen-dataclass
    ``object.__setattr__`` per field — field order/equality semantics are
    identical."""

    topic: str
    partition: int
    old_leader: int                 # broker id
    old_replicas: tuple[int, ...]   # broker ids, leader first
    new_replicas: tuple[int, ...]   # broker ids, leader first

    @property
    def new_leader(self) -> int:
        return self.new_replicas[0]

    @property
    def has_replica_action(self) -> bool:
        return set(self.old_replicas) != set(self.new_replicas)

    @property
    def has_leader_action(self) -> bool:
        return self.old_leader != self.new_leader

    @property
    def replicas_to_add(self) -> tuple[int, ...]:
        old = set(self.old_replicas)
        return tuple(b for b in self.new_replicas if b not in old)

    @property
    def replicas_to_remove(self) -> tuple[int, ...]:
        new = set(self.new_replicas)
        return tuple(b for b in self.old_replicas if b not in new)

    def to_json(self) -> dict:
        return {"topicPartition": {"topic": self.topic, "partition": self.partition},
                "oldLeader": self.old_leader,
                "oldReplicas": list(self.old_replicas),
                "newReplicas": list(self.new_replicas)}


def _padded_broker_ids(metadata: ClusterMetadata,
                       sentinel: int) -> np.ndarray:
    """Padded index -> external broker id lookup (sentinel row = -1)."""
    return np.asarray(metadata.broker_ids
                      + [-1] * (sentinel + 1 - len(metadata.broker_ids)))


def _row_ids(row: np.ndarray, broker_ids: np.ndarray,
             sentinel: int) -> tuple[int, ...]:
    """One padded replica row -> leader-first external broker id tuple."""
    return tuple(int(broker_ids[b]) for b in row if b < sentinel)


def diff_proposals(initial: FlatClusterModel, final: FlatClusterModel,
                   metadata: ClusterMetadata) -> list[ExecutionProposal]:
    """Diff two models sharing one metadata/padding layout into proposals.

    Vectorized for LinkedIn-scale diffs (~500K changed rows at 10Kx1M):
    the padded-index -> external-broker-id mapping happens as two whole-
    array gathers and the per-row work walks plain Python lists — per-
    element ``np`` indexing in a 500K-row loop costs seconds."""
    rb0 = np.asarray(initial.replica_broker)
    rb1 = np.asarray(final.replica_broker)
    # The two placement fetches above are the proposal diff's real
    # device->host cost at scale ([P, R] int32 x 2) — metered on the
    # device-runtime ledger like the optimizer's own fetches.
    from ..core.runtime_obs import default_collector
    default_collector().record_d2h(rb0.nbytes + rb1.nbytes)
    return diff_replica_arrays(rb0, rb1, metadata,
                               initial.broker_sentinel)


def diff_replica_arrays(rb0: np.ndarray, rb1: np.ndarray,
                        metadata: ClusterMetadata,
                        sentinel: int) -> list[ExecutionProposal]:
    """The host half of :func:`diff_proposals`, on already-fetched
    placement arrays — the fleet layer fetches every member's placements
    in ONE stacked device read and diffs each member here, instead of
    paying a per-member fetch round trip."""
    if rb0.shape != rb1.shape:
        raise ValueError("models have different padded shapes")
    changed = np.nonzero((rb0 != rb1).any(axis=1))[0]
    changed = changed[changed < len(metadata.partition_keys)]
    if changed.size == 0:
        return []
    broker_ids = _padded_broker_ids(metadata, sentinel)
    # Gather external ids for every changed row at once; padding slots
    # (>= sentinel) map to the sentinel row's -1 and are filtered per row
    # (a row's valid slots need not be contiguous after RF changes).
    a0 = broker_ids[np.minimum(rb0[changed], sentinel)]
    a1 = broker_ids[np.minimum(rb1[changed], sentinel)]
    keys = metadata.partition_keys
    if not (a0 < 0).any() and not (a1 < 0).any():
        # Fast path — every changed row fully populated (the steady
        # state: RF changes are rare): no per-slot -1 filtering, and
        # row0 != row1 is guaranteed (padded index -> id is injective).
        # Rows materialize as C-built tuples via a column-transposed
        # zip — per-row ``tolist`` list allocation and Python-level
        # ``tuple()`` calls were this diff's hottest host loop when a
        # 16-cluster fleet tick pushes ~300K proposals through here.
        rows0 = zip(*(a0[:, j].tolist() for j in range(a0.shape[1])))
        rows1 = zip(*(a1[:, j].tolist() for j in range(a1.shape[1])))
        return [ExecutionProposal(*keys[p], r0[0], r0, r1)
                for p, r0, r1 in zip(changed.tolist(), rows0, rows1)]
    proposals: list[ExecutionProposal] = []
    for p, row0, row1 in zip(changed.tolist(), a0.tolist(), a1.tolist()):
        old = tuple(b for b in row0 if b >= 0)
        new = tuple(b for b in row1 if b >= 0)
        if old == new:
            continue
        topic, partition = keys[p]
        proposals.append(ExecutionProposal(topic, partition,
                                           old[0] if old else -1, old, new))
    return proposals


def diff_proposals_vs_placement(placement: dict[tuple, list[int]],
                                initial: FlatClusterModel,
                                final: FlatClusterModel,
                                metadata: ClusterMetadata,
                                mutated_keys: set[tuple]
                                ) -> list[ExecutionProposal]:
    """Diff the final model against an explicit prior (live) placement
    ({(topic, partition) -> leader-first broker ids}). Used by flows whose
    optimization *input* already differs from the live cluster (e.g. a
    replication-factor change mutates the spec before optimizing): the
    executable proposals must capture the full live->final change, not
    just the optimizer's own moves — and the two sides may have different
    replication factors, which the padded-model diff cannot express.

    A row can differ from the live placement only if the optimizer moved
    it (vectorized initial-vs-final mask) or the mutator touched it
    (``mutated_keys``, computed cheaply in spec space by the caller) — so
    only that union pays Python-level tuple construction."""
    rb0 = np.asarray(initial.replica_broker)
    rb1 = np.asarray(final.replica_broker)
    sentinel = final.broker_sentinel
    broker_ids = _padded_broker_ids(metadata, sentinel)
    changed = (rb0 != rb1).any(axis=1)
    idx = {key: i for i, key in enumerate(metadata.partition_keys)}
    candidates = set(np.nonzero(changed)[0].tolist())
    candidates.update(idx[k] for k in mutated_keys if k in idx)
    proposals: list[ExecutionProposal] = []
    for p_idx in sorted(candidates):
        if p_idx >= len(metadata.partition_keys):
            continue
        key = metadata.partition_keys[p_idx]
        new = _row_ids(rb1[p_idx], broker_ids, sentinel)
        old = tuple(placement.get(key, new))
        if old == new:
            continue
        proposals.append(ExecutionProposal(topic=key[0], partition=key[1],
                                           old_leader=old[0] if old else -1,
                                           old_replicas=old,
                                           new_replicas=new))
    return proposals


def proposal_summary(proposals: list[ExecutionProposal]) -> dict:
    """Counts mirroring OptimizerResult proposal summary fields."""
    return {
        "numReplicaMovements": sum(len(p.replicas_to_add) for p in proposals),
        "numLeaderMovements": sum(1 for p in proposals
                                  if p.has_leader_action and not p.has_replica_action),
        "numProposals": len(proposals),
        "dataToMoveMB": None,  # filled by caller with disk loads when available
    }
