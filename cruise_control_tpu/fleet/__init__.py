"""Fleet-scale control plane (L-fleet): one process balancing many
clusters through ONE batched device dispatch per tick.

``model/fleet.py`` stacks per-cluster flat models into ``[C, ...]``
arrays; ``engine.py`` runs the full optimize loop (goal chain +
hard-goal audit + polish) and the N-1 resilience sweep over the cluster
axis in one dispatch each; ``registry.py`` is the host side — per-cluster
monitors feeding the shared tick, per-cluster proposal caches, anomaly
fan-out, and the ``/fleet`` API surface.
"""

from ..model.fleet import FleetMember, FleetModel
from .engine import CLUSTER_AXIS, FleetOptimizer
from .registry import FleetRegistry

__all__ = ["FleetMember", "FleetModel", "FleetOptimizer", "FleetRegistry",
           "CLUSTER_AXIS"]
