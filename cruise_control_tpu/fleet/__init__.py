"""Fleet-scale control plane (L-fleet): one process balancing many
clusters through ONE batched device dispatch per tick.

``model/fleet.py`` stacks per-cluster flat models into ``[C, ...]``
arrays; ``engine.py`` runs the full optimize loop (goal chain +
hard-goal audit + polish) and the N-1 resilience sweep over the cluster
axis in one dispatch each; ``registry.py`` is the host side — per-cluster
monitors feeding the shared tick, per-cluster proposal caches, anomaly
fan-out, and the ``/fleet`` API surface.

Fault isolation (PR 19): ``backends.py`` wraps per-member remote
endpoints with deadlines, shared retry, and a per-member circuit
breaker; the registry runs a HEALTHY → DEGRADED → QUARANTINED →
READMITTING health machine per member so one unreachable cluster
endpoint degrades ONE member while siblings keep their tick cadence;
``budget.py`` grants per-tick moves from one fleet-wide budget,
urgency-weighted.
"""

from ..model.fleet import FleetMember, FleetModel
from .backends import (CallDeadlineExceeded, CircuitBreaker,
                       CircuitOpenError, MemberHealth, RemoteBackend)
from .budget import BudgetGrant, BudgetRequest, MoveBudgetCoordinator
from .engine import CLUSTER_AXIS, FleetOptimizer
from .registry import FleetRegistry

__all__ = ["BudgetGrant", "BudgetRequest", "CallDeadlineExceeded",
           "CircuitBreaker", "CircuitOpenError", "FleetMember",
           "FleetModel", "FleetOptimizer", "FleetRegistry",
           "MemberHealth", "MoveBudgetCoordinator", "RemoteBackend",
           "CLUSTER_AXIS"]
