"""Per-member remote backends: the fleet's failure-domain boundary.

PR 10's fleet members were in-process ``LoadMonitor``s sharing one fake
admin/sampler — a single slow or dead cluster endpoint stalled the ONE
shared tick that balances every cluster. This module makes each member a
real failure domain: every admin/sampler call to a member's endpoint
rides a hard per-call deadline plus the shared ``core/retry.py`` policy,
and its outcome feeds a per-member :class:`CircuitBreaker`. The registry
(``fleet/registry.py``) turns breaker state + fetch outcomes into the
member health state machine (HEALTHY → DEGRADED → QUARANTINED →
READMITTING, :class:`MemberHealth`).

Everything here is deterministic under the chaos clock: the breaker's
half-open probe times jitter through ``deterministic_uniform`` keyed on
``(seed, open-episode)``, and the deadline accounting reads the SAME
injected ``now_ms`` the retry policy sleeps against — a chaos run
replayed from its seed walks byte-identical breaker transitions.
"""

from __future__ import annotations

import time as _time
from collections import deque

from ..core.retry import NO_RETRY, RetryPolicy, deterministic_uniform


class MemberHealth:
    """Per-member health states (registry state machine; docs/fleet.md
    §Failure domains)."""

    HEALTHY = "HEALTHY"
    DEGRADED = "DEGRADED"
    QUARANTINED = "QUARANTINED"
    READMITTING = "READMITTING"

    ALL = (HEALTHY, DEGRADED, QUARANTINED, READMITTING)


class CircuitOpenError(RuntimeError):
    """Fail-fast refusal: the member's breaker is OPEN and the half-open
    probe is not due yet. Deliberately NOT an ``AdminTimeoutError`` — a
    retry policy must never spin on a breaker that exists to shed load
    from a failing endpoint."""


class CallDeadlineExceeded(RuntimeError):
    """A backend call (including its retries) outran the hard per-call
    deadline (``fleet.call.deadline.ms``). Like :class:`CircuitOpenError`
    this is not retryable: the time budget is already spent."""


class CircuitBreaker:
    """Rolling-window circuit breaker with seeded half-open probes.

    CLOSED counts failures over a sliding ``window_ms``; at
    ``failure_threshold`` it trips OPEN and schedules ONE half-open probe
    at ``open_ms`` scaled into ``1 ± jitter`` by a deterministic draw
    keyed on the open-episode count (so replays probe at identical sim
    times, but repeated trips don't resonate with a periodic fault).
    ``allow()`` admits exactly one call per due probe (HALF_OPEN); a
    probe success closes the breaker, a probe failure re-opens it with a
    freshly-jittered probe time.
    """

    CLOSED = "CLOSED"
    OPEN = "OPEN"
    HALF_OPEN = "HALF_OPEN"

    def __init__(self, *, window_ms: int = 60_000,
                 failure_threshold: int = 3, open_ms: int = 30_000,
                 jitter: float = 0.2, seed: int = 0,
                 name: str = "") -> None:
        self.window_ms = window_ms
        self.failure_threshold = max(failure_threshold, 1)
        self.open_ms = open_ms
        self.jitter = jitter
        self.seed = seed
        self.name = name
        self.state = self.CLOSED
        self._outcomes: deque[tuple[int, bool]] = deque()
        self.opened_at: int | None = None
        self.probe_at: int | None = None
        #: distinct OPEN episodes — keys the probe jitter draw AND feeds
        #: operator surfaces (a flapping endpoint shows as a high count).
        self.open_count = 0
        self._probe_inflight = False

    # ------------------------------------------------------------ window
    def _prune(self, now: int) -> None:
        floor = now - self.window_ms
        while self._outcomes and self._outcomes[0][0] < floor:
            self._outcomes.popleft()

    def failures_in_window(self, now: int) -> int:
        self._prune(now)
        return sum(1 for _, ok in self._outcomes if not ok)

    # ------------------------------------------------------- transitions
    def _trip_open(self, now: int) -> None:
        self.state = self.OPEN
        self.opened_at = now
        self.open_count += 1
        self._probe_inflight = False
        frac = deterministic_uniform(self.seed, "breaker-probe",
                                     self.name, self.open_count)
        scale = 1.0 + self.jitter * (2.0 * frac - 1.0)
        self.probe_at = now + max(int(self.open_ms * scale), 1)

    def record_success(self, now: int) -> None:
        self._outcomes.append((now, True))
        self._prune(now)
        if self.state in (self.OPEN, self.HALF_OPEN):
            # A successful probe (or an out-of-band success) heals the
            # breaker completely — the window restarts clean so one old
            # burst can't instantly re-trip it.
            self.state = self.CLOSED
            self._outcomes.clear()
            self.opened_at = None
            self.probe_at = None
            self._probe_inflight = False

    def record_failure(self, now: int) -> None:
        self._outcomes.append((now, False))
        self._prune(now)
        if self.state == self.HALF_OPEN:
            self._trip_open(now)   # probe failed: re-open, re-jitter
        elif (self.state == self.CLOSED
              and self.failures_in_window(now) >= self.failure_threshold):
            self._trip_open(now)

    def allow(self, now: int) -> bool:
        """Whether a call may proceed at ``now``. OPEN admits exactly one
        probe once ``probe_at`` is due (transitioning to HALF_OPEN)."""
        if self.state == self.CLOSED:
            return True
        if self.state == self.OPEN and self.probe_at is not None \
                and now >= self.probe_at:
            self.state = self.HALF_OPEN
            self._probe_inflight = True
            return True
        if self.state == self.HALF_OPEN and not self._probe_inflight:
            self._probe_inflight = True
            return True
        return False

    def to_json(self) -> dict:
        return {"state": self.state,
                "failuresInWindow": len([1 for _, ok in self._outcomes
                                         if not ok]),
                "openCount": self.open_count,
                "openedAt": self.opened_at,
                "probeAt": self.probe_at}


class RemoteBackend:
    """Admin/sampler proxy for ONE fleet member's endpoint.

    Wraps every callable attribute of ``target`` (the member's admin or
    sampler client) so that a call (a) fails fast with
    :class:`CircuitOpenError` while the member's breaker is open, (b)
    rides the shared retry policy on the member's clock, (c) is charged
    against the hard per-call deadline — a call whose total elapsed time
    (retries included) exceeds ``call_deadline_ms`` records a breaker
    failure and raises :class:`CallDeadlineExceeded` — and (d) feeds its
    outcome to the breaker either way. Non-callable attributes pass
    through untouched.
    """

    #: attributes served from the proxy itself, never the target
    _OWN = ("member_id", "endpoint", "breaker", "retry",
            "call_deadline_ms", "calls", "failures", "fast_fails",
            "deadline_misses")

    def __init__(self, member_id: str, target, *,
                 endpoint: str = "", breaker: CircuitBreaker | None = None,
                 retry: RetryPolicy = NO_RETRY,
                 call_deadline_ms: int = 0, retry_on: tuple = (),
                 now_ms=None, sleep_ms=None) -> None:
        self.member_id = member_id
        self.endpoint = endpoint
        self.breaker = breaker or CircuitBreaker(name=member_id)
        self.retry = retry
        self.call_deadline_ms = call_deadline_ms
        self._retry_on = retry_on
        self._target = target
        self._now_ms = now_ms or (lambda: int(_time.monotonic() * 1000))
        self._sleep_ms = sleep_ms
        self.calls = 0
        self.failures = 0
        self.fast_fails = 0
        self.deadline_misses = 0

    def _wrap(self, fn):
        def call(*args, **kwargs):
            start = self._now_ms()
            if not self.breaker.allow(start):
                self.fast_fails += 1
                raise CircuitOpenError(
                    f"member {self.member_id!r} breaker is "
                    f"{self.breaker.state} (probe at "
                    f"{self.breaker.probe_at})")
            self.calls += 1
            try:
                out = self.retry.call(fn, *args, retry_on=self._retry_on,
                                      sleep_ms=self._sleep_ms,
                                      now_ms=self._now_ms, **kwargs)
            except Exception:
                self.failures += 1
                self.breaker.record_failure(self._now_ms())
                raise
            end = self._now_ms()
            if self.call_deadline_ms \
                    and end - start > self.call_deadline_ms:
                # The answer arrived too late to be useful: charge the
                # breaker and refuse it, so a slow-but-alive endpoint
                # degrades exactly like a dead one (deterministic on the
                # injected clock — no wall-clock race).
                self.deadline_misses += 1
                self.failures += 1
                self.breaker.record_failure(end)
                raise CallDeadlineExceeded(
                    f"member {self.member_id!r} call {fn.__name__} took "
                    f"{end - start} ms > deadline "
                    f"{self.call_deadline_ms} ms")
            self.breaker.record_success(end)
            return out
        call.__name__ = getattr(fn, "__name__", "call")
        return call

    def __getattr__(self, name):
        # Only fires for attributes not found on the proxy instance
        # itself (member_id, breaker, ... resolve normally).
        attr = getattr(self._target, name)
        if not callable(attr):
            return attr
        return self._wrap(attr)

    def to_json(self) -> dict:
        return {"endpoint": self.endpoint or None,
                "calls": self.calls,
                "failures": self.failures,
                "fastFails": self.fast_fails,
                "deadlineMisses": self.deadline_misses,
                "breaker": self.breaker.to_json()}
