"""Fleet device engine: one dispatch optimizes (and risk-scores) a whole
fleet of clusters.

The scenario axis taught us how to batch *scoring* (``whatif/engine.py``
vmaps a pure scorer over ``[S]``); the cluster axis must batch the full
*search*. vmapping the goal-chain passes is the wrong tool there — the
batching rewrite turns every converged-goal ``lax.cond`` early-exit into
both-branches execution and batches the hot scatter paths, measured
SLOWER than the sequential loop on CPU. Instead the fleet walk shards
the cluster axis over a device mesh (``shard_map``, like
``parallel/branches.py`` does for search branches) and runs the
UNMODIFIED single-cluster pass functions per cluster via ``lax.map``
(a scan — real control flow, no batching rewrite). Consequences:

- **bit-identical by construction**: each cluster executes exactly the
  program the single-cluster optimizer would run on the same (fleet-
  bucket-padded) model, so fleet proposals equal sequential per-cluster
  proposals byte for byte (tier-1 gated in ``tests/test_fleet.py``);
- **real amortization**: clusters run concurrently across devices
  (measured 12x over the sequential loop for 16 x (100 brokers x 20k
  partitions) on a 24-core CPU host with 16 virtual devices) and the
  whole fleet costs ONE dispatch + one host sync per walk instead of
  ``C x G`` dispatches;
- **one compiled program per fleet bucket**: the program cache keys on
  (shapes, cluster bucket, goal binding) through the shared
  ``parallel/batching.ProgramCache`` — the machinery lifted out of the
  what-if engine.

Host-side orchestration (polish rounds, self-check, hard-goal gate,
proposal diffing) mirrors ``TpuGoalOptimizer._optimize_impl``'s per-goal
path exactly, with per-cluster ``enabled`` masks standing in for the
host's per-cluster control flow: a disabled (converged or padding)
cluster's pass is a runtime ``lax.cond`` skip, not a masked execution.
"""

from __future__ import annotations

import logging
import math
import time
from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..analyzer.engine import violation_stack
from ..analyzer.optimizer import (GoalResult, OptimizationFailureError,
                                  OptimizerResult, _as_jnp)
from ..analyzer.options import OptimizationOptions
from ..analyzer.state import build_context, init_state
from ..model.fleet import FleetModel
from ..parallel._compat import shard_map
from ..parallel.batching import ProgramCache, round_up
from ..whatif.engine import (make_scenario_scorer, risk_scores,
                             violated_matrix)

LOG = logging.getLogger(__name__)

CLUSTER_AXIS = "cluster"


def _tree_specs(tree, spec):
    return jax.tree.map(lambda _: spec, tree)


def _shape_sig(*trees) -> tuple:
    return tuple((tuple(a.shape), str(a.dtype))
                 for a in jax.tree.leaves(trees))


class FleetOptimizer:
    """Batched fleet propose/score on top of a single-cluster
    ``TpuGoalOptimizer`` (whose goals, search config, constraint,
    options generator, registered hard goals and compiled-chain registry
    it shares — the fleet walk re-traces the SAME pass functions the
    sequential path compiled, so the process-wide ``_SHARED_CHAINS``
    stays the one source of chain identity).

    Members whose scaled search config or goal binding differ (pattern
    goals resolving against different topic sets, topic-count state of
    different widths) cannot share one traced program; :meth:`propose`
    groups members by that compiled identity and runs one dispatch per
    group — the documented degrade path for heterogeneous fleets
    (docs/fleet.md). A homogeneous fleet is always one group.
    """

    def __init__(self, optimizer, *, max_devices: int | None = None,
                 scenario_pad_multiple: int = 8,
                 program_cache_size: int = 8,
                 registry=None, tracer=None, collector=None) -> None:
        from ..core.runtime_obs import default_collector
        from ..core.sensors import MetricRegistry
        from ..core.tracing import default_tracer
        if getattr(optimizer, "branches", 0) and optimizer.branches > 1:
            raise ValueError(
                "fleet batching and search.branches are mutually "
                "exclusive: both own the device axis")
        if getattr(optimizer, "mesh", None) is not None:
            raise ValueError(
                "fleet batching and search.mesh.devices are mutually "
                "exclusive: the fleet shards the cluster axis, the mesh "
                "the partition axis")
        population = getattr(optimizer, "population", None)
        if population is not None and population.enabled:
            raise ValueError(
                "fleet batching and search.population are mutually "
                "exclusive: the fleet shards the cluster axis over the "
                "local devices, the population replicates per member")
        self.optimizer = optimizer
        self.max_devices = max_devices
        self.scenario_pad_multiple = scenario_pad_multiple
        self._programs = ProgramCache(program_cache_size)
        self._meshes: dict[int, Mesh] = {}
        self.registry = registry or MetricRegistry()
        self.tracer = tracer or default_tracer()
        self.collector = collector or default_collector()
        name = MetricRegistry.name
        self._propose_timer = self.registry.timer(
            name("FleetOptimizer", "propose-timer"))
        self._dispatch_timer = self.registry.timer(
            name("FleetOptimizer", "dispatch-timer"))
        self._clusters_meter = self.registry.meter(
            name("FleetOptimizer", "clusters-proposed"))
        self._groups_gauge_val = 0
        self.registry.gauge(name("FleetOptimizer", "last-propose-groups"),
                            lambda: self._groups_gauge_val)
        #: wall clock of the most recent device dispatch (the
        #: /devicestats fleet section reads this)
        self.last_dispatch_s: float | None = None
        self.last_layout: dict | None = None
        #: cluster-axis shape floor: lay out every batch as if it held at
        #: least this many clusters (padding slots run the per-goal skip
        #: branch, nearly free). The registry pins it to its member count
        #: so a tick over a SUBSET of members (some still warming in)
        #: reuses the full fleet's compiled programs instead of
        #: compiling one program set per distinct subset size.
        self.cluster_bucket_floor: int = 0

    # ---------------------------------------------------------- layout
    def _device_cap(self) -> int:
        cap = self.max_devices or len(jax.devices())
        return max(min(cap, len(jax.devices())), 1)

    def _layout(self, C: int) -> tuple[int, int, int]:
        """(devices D, clusters-per-device k, padded cluster count) for a
        C-cluster group: minimize padding slots subject to the device
        cap — k = ceil(C / cap), D = ceil(C / k) — with the cluster
        bucket floor applied first so nearby batch sizes share one
        compiled shape."""
        cap = self._device_cap()
        C = max(C, self.cluster_bucket_floor or 0, 1)
        k = math.ceil(C / cap)
        D = math.ceil(C / k)
        return D, k, D * k

    def _mesh(self, D: int) -> Mesh:
        mesh = self._meshes.get(D)
        if mesh is None:
            mesh = Mesh(np.asarray(jax.devices()[:D]), (CLUSTER_AXIS,))
            self._meshes[D] = mesh
        return mesh

    # --------------------------------------------------------- propose
    def propose(self, fleet: FleetModel,
                options: OptimizationOptions | None = None) -> list:
        """Optimize every fleet member; returns a list aligned with
        ``fleet.members`` whose entries are ``OptimizerResult``s — or
        ``OptimizationFailureError``s for members whose hard goals stay
        violated under strict options (the sequential path raises; a
        fleet dispatch must not let one member's failure destroy the
        others' results)."""
        options = options or OptimizationOptions()
        t0 = time.monotonic()
        C = fleet.num_clusters
        with self.collector.cycle("fleet-propose"), \
                self.tracer.span("fleet.propose", clusters=C) as sp:
            prepared = [self._prepare_member(m, options)
                        for m in fleet.members]
            groups: dict[tuple, list[int]] = {}
            for i, prep in enumerate(prepared):
                groups.setdefault(prep["group_key"], []).append(i)
            self._groups_gauge_val = len(groups)
            if len(groups) > 1:
                LOG.info(
                    "fleet propose split into %d dispatch groups "
                    "(heterogeneous search configs or goal bindings)",
                    len(groups))
            results: list = [None] * C
            dispatch_s = 0.0
            for idxs in groups.values():
                dispatch_s += self._propose_group(
                    fleet, prepared, idxs, results)
            self.last_dispatch_s = dispatch_s
            sp.set(groups=len(groups),
                   dispatchMs=round(dispatch_s * 1e3, 3))
        self._propose_timer.update(time.monotonic() - t0)
        self._clusters_meter.mark(C)
        return results

    def _prepare_member(self, member, options: OptimizationOptions) -> dict:
        """Mirror of ``TpuGoalOptimizer._prepare`` for one member (minus
        mesh/chain-warmup): generated options, scaled config, bound
        goals, audit set, search context and initial state — plus the
        compiled-identity group key."""
        opt = self.optimizer
        md = member.metadata
        model = member.model
        opts = options
        if opt.options_generator is not None:
            opts = opt.options_generator.generate(opts, md)
        # Tuned schedules (analyzer/tuning.py), the sequential
        # _prepare's rule: per-shape-bucket overrides fold in BEFORE the
        # tiny-model clamp. The resulting cfg is part of group_key below,
        # so members in differently-tuned buckets split into separate
        # dispatch GROUPS (each group one traced program under its own
        # schedule) instead of silently running member 0's schedule —
        # the same degrade path heterogeneous goal bindings take.
        base_cfg = opt.config
        if opt.tuned_store is not None:
            base_cfg = opt.tuned_store.apply(
                base_cfg, md.num_partitions, md.num_brokers,
                regime=opt.active_regime)
        cfg = base_cfg.scaled_for(md.num_partitions, md.num_brokers)
        if opts.fast_mode:
            cfg = replace(
                cfg,
                max_iters_per_goal=max(cfg.max_iters_per_goal // 4, 16)
            ).scaled_for(max(md.num_partitions // 4, 8), md.num_brokers)
        goals = [g.bind(md) for g in opt.goals]
        audit = opt._audit_goals_for(goals, md, opts)
        Pn = model.num_partitions_padded
        B = model.num_brokers_padded
        masks = (opts.excluded_partition_mask(md, Pn),
                 opts.replica_move_exclusion_mask(md, B),
                 opts.broker_mask(md, B,
                                  opts.excluded_brokers_for_leadership))
        needs_tlc = any(g.uses_topic_leader_counts for g in goals + audit)
        needs_topics = needs_tlc or any(g.uses_topic_counts
                                        for g in goals + audit)
        num_topics = md.num_topics if needs_topics else None
        group_key = (
            cfg,
            tuple((type(g), g.name, g.hard,
                   getattr(g, "constraint", None), g.bind_signature())
                  for g in goals),
            tuple((g.name, g.bind_signature()) for g in audit),
            num_topics, needs_tlc,
            tuple(m is None for m in masks),
            # The PRNG stream is shared across a group (one keys array
            # per dispatch): an options generator varying the seed per
            # cluster must split groups, or members would silently run
            # under member 0's stream and break sequential parity.
            opts.seed)
        return {"member": member, "opts": opts, "cfg": cfg,
                "goals": goals, "audit": audit, "masks": masks,
                "num_topics": num_topics, "needs_tlc": needs_tlc,
                "group_key": group_key}

    @staticmethod
    def _member_state_ctx(prep):
        """Eager per-member state/ctx — exactly the sequential
        ``_prepare``'s construction; the fallback when request options
        carry exclusion masks (which are per-metadata arrays the batched
        prepare program cannot bake in)."""
        model = prep["member"].model
        excluded_parts, repl_mask, lead_mask = prep["masks"]
        ctx = build_context(
            model,
            excluded_partitions=None if excluded_parts is None
            else jnp.asarray(excluded_parts),
            excluded_brokers_for_replica_move=_as_jnp(repl_mask),
            excluded_brokers_for_leadership=_as_jnp(lead_mask))
        state = init_state(
            model,
            with_topic_counts=prep["num_topics"],
            with_topic_leader_counts=prep["needs_tlc"])
        return state, ctx

    def _stack_padded(self, trees, pad: int):
        """Stack per-member pytrees on a new leading axis, replicating
        entry 0 into ``pad`` trailing padding slots (structurally valid,
        engine-masked)."""
        rows = list(trees) + [trees[0]] * pad
        return jax.tree.map(lambda *xs: jnp.stack(xs), *rows)

    def _propose_group(self, fleet, prepared, idxs, results) -> float:
        preps = [prepared[i] for i in idxs]
        cfg = preps[0]["cfg"]
        goals = preps[0]["goals"]
        audit = preps[0]["audit"]
        G = len(goals)
        Cg = len(idxs)
        D, k, C_pad = self._layout(Cg)
        mesh = self._mesh(D)
        # The process-wide compiled-chain registry is the one source of
        # pass-function identity — the fleet walk re-traces exactly the
        # passes the sequential path runs/compiled for this chain.
        chain = self.optimizer._chain_for(cfg, goals)
        pass_fns = list(chain._pass_fns)

        if idxs == list(range(fleet.num_clusters)) \
                and fleet.num_clusters_padded == C_pad:
            # Whole-fleet single group: the FleetModel's stack IS the
            # group stack — re-stacking 16 members' models every tick is
            # measurable host time on the dispatch path.
            group_models = fleet.stacked
        else:
            group_models = self._stack_padded(
                [p["member"].model for p in preps], C_pad - Cg)
        if all(m is None for p in preps for m in p["masks"]):
            # Batched prepare: one program builds every member's search
            # state + context from the stacked models — the eager
            # per-member construction is 16 clusters' worth of small
            # dispatches on the host's critical path every tick.
            prepare = self._prepare_program(
                _shape_sig(group_models), preps[0]["num_topics"],
                preps[0]["needs_tlc"], mesh, D)
            states, ctxs = prepare(group_models)
        else:
            pairs = [self._member_state_ctx(p) for p in preps]
            states = self._stack_padded([s for s, _ in pairs], C_pad - Cg)
            ctxs = self._stack_padded([c for _, c in pairs], C_pad - Cg)
        shape_sig = _shape_sig(states, ctxs)
        walk = self._walk_program(shape_sig, cfg, goals, pass_fns, mesh, D)
        audit_fn = (self._audit_program(shape_sig, audit, mesh, D)
                    if audit else None)
        seed_key = jax.random.PRNGKey(preps[0]["opts"].seed)
        keys_main = jnp.stack([jax.random.fold_in(seed_key, i)
                               for i in range(G)])

        enabled = np.zeros((C_pad, G), bool)
        enabled[:Cg] = True
        t_disp = time.monotonic()
        audit_before = audit_fn(states, ctxs) if audit_fn is not None \
            else None
        with self.tracer.span("fleet.walk", clusters=Cg, devices=D,
                              goals=G):
            states, aux, iters, bounds, moves = walk(
                states, ctxs, jnp.asarray(enabled), keys_main)
            fetched = jax.device_get((aux, iters, bounds, moves))
        self.collector.record_d2h(self.collector.tree_bytes(fetched))
        (has_broken, scales, v0), iters_np, bounds_np, moves_np = fetched
        iters_np = np.asarray(iters_np, np.int64)
        moves_np = np.asarray(moves_np, np.int64)
        bounds_np = np.asarray(bounds_np)

        # Per-cluster trajectories/accounting, exactly the sequential
        # walk's host bookkeeping (self-check included).
        traj = [[[float(x) for x in v0[c]]] for c in range(Cg)]
        accepted = np.zeros((Cg, G), np.int64)
        #: each goal's PRE-pass reading — stack row i of the walk (the
        #: stack after goal i-1; row 0 is the initial stack), exactly the
        #: boundary the sequential loop records as violation_before.
        before = np.zeros((Cg, G))
        iters_total = iters_np[:Cg].copy()
        prev_moves = np.zeros(Cg, np.int64)
        for c in range(Cg):
            cid = preps[c]["member"].cluster_id
            boundary = np.asarray(v0[c])
            for i, g in enumerate(goals):
                before_i = float(boundary[i])
                before[c, i] = before_i
                boundary = bounds_np[c, i]
                traj[c].append([float(x) for x in boundary])
                accepted[c, i] = moves_np[c, i] - prev_moves[c]
                prev_moves[c] = moves_np[c, i]
                after_i = float(boundary[i])
                if after_i > before_i * (1 + 1e-6) + 1e-6:
                    if bool(has_broken[c]):
                        LOG.warning(
                            "fleet[%s]: goal %s worsened its own "
                            "violation %.6g -> %.6g while draining broken"
                            " brokers (self-check exempt)", cid, g.name,
                            before_i, after_i)
                    else:
                        raise RuntimeError(
                            f"fleet optimization self-check failed for "
                            f"cluster {cid}: goal {g.name} worsened its "
                            f"own violation {before_i:.6g} -> "
                            f"{after_i:.6g}")

        # Polish rounds — the per-goal path's semantics with per-cluster
        # enabled masks: todo is each cluster's residual goals at round
        # start, keys fold_in(key, 1000*(rnd+1)+i), and a fully-converged
        # cluster runs nothing further. `~(x <= eps)` keeps NaN residuals
        # in the todo set (broken-kernel case), like sequential.
        polish_eps = min(cfg.epsilon, 1e-6)
        boundary_np = bounds_np[:, -1, :].copy()        # [C_pad, G]
        rounds = cfg.polish_passes + 1 if cfg.polish_passes else 0
        for rnd in range(rounds):
            enab = ~(boundary_np <= polish_eps)
            enab[Cg:] = False
            if not enab.any():
                break
            keys_rnd = jnp.stack([
                jax.random.fold_in(seed_key, 1000 * (rnd + 1) + i)
                for i in range(G)])
            with self.tracer.span("fleet.polish", round=rnd,
                                  clusters=int(enab.any(axis=1).sum())):
                states, _aux2, it2, b2, m2 = walk(
                    states, ctxs, jnp.asarray(enab), keys_rnd)
                fetched = jax.device_get((it2, b2, m2))
            self.collector.record_d2h(self.collector.tree_bytes(fetched))
            it2, b2, m2 = (np.asarray(fetched[0], np.int64),
                           np.asarray(fetched[1]),
                           np.asarray(fetched[2], np.int64))
            for c in range(Cg):
                if not enab[c].any():
                    continue       # cluster converged: no further rounds
                for i in range(G):
                    if not enab[c, i]:
                        continue
                    accepted[c, i] += m2[c, i] - prev_moves[c]
                    prev_moves[c] = m2[c, i]
                    iters_total[c, i] += it2[c, i]
                # One trajectory row per polish ROUND, the sequential
                # convention (the round-end boundary stack).
                traj[c].append([float(x) for x in b2[c, -1]])
            boundary_np = b2[:, -1, :].copy()
        dispatch_s = time.monotonic() - t_disp

        audit_after = None
        if audit_fn is not None:
            audit_before = jax.device_get(audit_before)
            audit_after = jax.device_get(audit_fn(states, ctxs))
            self.collector.record_d2h(self.collector.tree_bytes(
                (audit_before, audit_after)))

        # Batched finish: the per-member device work the sequential
        # _finish pays one cluster at a time — placement planes for the
        # proposal diff, the provision verdict's utilization recompute
        # and broker planes — runs as ONE program and ONE stacked fetch
        # for the whole group; everything after is per-member numpy.
        finish = self._finish_program(shape_sig, mesh, D)
        fetched = jax.device_get(
            (finish(group_models, states.rb, states.offline, states.pos),
             states.moves_applied))
        self.collector.record_d2h(self.collector.tree_bytes(fetched))
        (util_np, rb0_np, rb1_np, alive_np, caps_np, racks_np), moves_a \
            = fetched
        moves_applied = np.asarray(moves_a, np.int64)
        walk_share = dispatch_s / max(Cg, 1)
        for c, idx in enumerate(idxs):
            results[idx] = self._finish_member(
                fleet, preps[c], states, c, goals, audit,
                audit_before, audit_after,
                before=before[c], scales=np.asarray(scales[c]),
                boundary=boundary_np[c], iters=iters_total[c],
                accepted=accepted[c], trajectory=traj[c],
                num_moves=int(moves_applied[c]), walk_share=walk_share,
                util=np.asarray(util_np[c]),
                rb0=np.asarray(rb0_np[c]), rb1=np.asarray(rb1_np[c]),
                alive=np.asarray(alive_np[c]),
                caps=np.asarray(caps_np[c]),
                racks=np.asarray(racks_np[c]))
        return dispatch_s

    def _prepare_program(self, models_sig, num_topics, needs_tlc, mesh,
                         D):
        """Batched maskless prepare: ``stacked models -> (states, ctxs)``
        via the same ``init_state``/``build_context`` the sequential path
        runs eagerly, one cluster at a time inside ``lax.map`` (scan, no
        batching rewrite — the ops and their results are the sequential
        constructions')."""
        key = (("fleet-prepare",) + models_sig + (num_topics, needs_tlc,
                                                  D))

        def build():
            def one(model):
                state = init_state(model,
                                   with_topic_counts=num_topics,
                                   with_topic_leader_counts=needs_tlc)
                return state, build_context(model)

            def body(models):
                return jax.lax.map(one, models)

            def run(models):
                in_specs = (_tree_specs(models, P(CLUSTER_AXIS)),)
                out_shape = jax.eval_shape(body, models)
                out_specs = _tree_specs(out_shape, P(CLUSTER_AXIS))
                return shard_map(body, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs)(models)

            return self.collector.track("fleet-prepare", jax.jit(run))

        return self._programs.get_or_build(key, build)

    def _finish_program(self, shape_sig, mesh, D):
        """One batched program computing everything the per-member finish
        reads off the device: initial/final placement planes, the
        provision verdict's from-scratch broker utilization (matching the
        sequential path's recompute, not the incrementally-maintained
        ``state.util``), and the static broker planes."""
        from ..model.flat import broker_utilization
        key = (("fleet-finish",) + shape_sig + (D,))

        def build():
            def one(t):
                model, rb, offline, pos = t
                final = model.replace(replica_broker=rb,
                                      replica_offline=offline,
                                      replica_pref_pos=pos)
                return (broker_utilization(final), model.replica_broker,
                        rb, model.broker_alive & model.broker_valid,
                        model.broker_capacity, model.broker_rack)

            def body(models, rb, offline, pos):
                return jax.lax.map(one, (models, rb, offline, pos))

            def run(models, rb, offline, pos):
                args = (models, rb, offline, pos)
                in_specs = tuple(_tree_specs(a, P(CLUSTER_AXIS))
                                 for a in args)
                out_shape = jax.eval_shape(body, *args)
                out_specs = _tree_specs(out_shape, P(CLUSTER_AXIS))
                return shard_map(body, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs)(*args)

            return self.collector.track("fleet-finish", jax.jit(run))

        return self._programs.get_or_build(key, build)

    def _finish_member(self, fleet, prep, states, c, goals, audit,
                       audit_before, audit_after, *, before, scales,
                       boundary, iters, accepted, trajectory, num_moves,
                       walk_share, util, rb0, rb1, alive, caps, racks):
        """Per-member ``_finish`` on pre-fetched arrays (the batched
        finish program's stacked read): proposal diff, audit verdicts,
        provision verdict, telemetry — and the hard-goal gate, captured
        as a returned ``OptimizationFailureError`` instead of raised."""
        member = prep["member"]
        opts = prep["opts"]
        G = len(goals)
        total_iters = max(int(iters.sum()), 1)
        goal_results = []
        for i, g in enumerate(goals):
            goal_results.append(GoalResult(
                name=g.name, hard=g.hard,
                violation_before=float(before[i]),
                violation_after=float(boundary[i]),
                duration_s=walk_share * int(iters[i]) / total_iters,
                iterations=int(iters[i]),
                scale=float(scales[i]),
                accepted=int(accepted[i])))
        audit_results = []
        if audit:
            (va, sa) = audit_after
            (vb, _sb) = audit_before
            audit_results = [
                GoalResult(name=g.name, hard=True,
                           violation_before=float(vb[c][i]),
                           violation_after=float(va[c][i]),
                           duration_s=0.0, iterations=0,
                           scale=float(sa[c][i]))
                for i, g in enumerate(audit)]
        final = member.model.replace(replica_broker=states.rb[c],
                                     replica_offline=states.offline[c],
                                     replica_pref_pos=states.pos[c])
        from ..model.proposals import diff_replica_arrays
        proposals = diff_replica_arrays(rb0, rb1, member.metadata,
                                        member.model.broker_sentinel)
        result = OptimizerResult(
            proposals=proposals, goal_results=goal_results,
            num_moves=num_moves,
            duration_s=walk_share, final_model=final,
            provision_response=self.optimizer._provision_verdict_from_host(
                util, alive, caps, member.model.num_brokers_padded,
                goal_results, placement=lambda: (rb1, racks)),
            hard_goal_audit=audit_results,
            telemetry=self.optimizer._record_goal_telemetry(
                goal_results, trajectory, num_moves),
            stale_model=member.stale)
        if result.violated_hard_goals and not opts.skip_hard_goal_check:
            return OptimizationFailureError(
                f"fleet[{member.cluster_id}]: hard goals still violated "
                f"after optimization: {result.violated_hard_goals}",
                result)
        return result

    # ------------------------------------------------------ walk program
    def _walk_program(self, shape_sig, cfg, goals, pass_fns, mesh, D):
        key = (("fleet-walk",) + shape_sig
               + (cfg, tuple((type(g), g.name, g.bind_signature())
                             for g in goals), D))
        return self._programs.get_or_build(
            key, lambda: self._build_walk(goals, pass_fns, mesh))

    def _build_walk(self, goals, pass_fns, mesh):
        goals = tuple(goals)

        def one_cluster(state, ctx, enabled, keys):
            has_broken = state.offline.any()
            scales = jnp.stack([g.violation_scale(state, ctx)
                                for g in goals])
            v0 = violation_stack(goals, state, ctx)
            prev = v0
            iters, bounds, moves = [], [], []
            for i, run in enumerate(pass_fns):
                def _do(st, _run=run, _i=i):
                    return _run(st, ctx, keys[_i])

                def _skip(st, _prev=prev):
                    return (st, jnp.zeros((), jnp.int32), _prev,
                            st.moves_applied)

                state, it, stack, m = jax.lax.cond(
                    enabled[i], _do, _skip, state)
                prev = stack
                iters.append(it)
                bounds.append(stack)
                moves.append(m)
            return (state, (has_broken, scales, v0), jnp.stack(iters),
                    jnp.stack(bounds), jnp.stack(moves))

        def body(states, ctxs, enabled, keys):
            # lax.map is a scan: clusters on one device run sequentially
            # through REAL control flow (cond picks one branch at
            # runtime, while_loops trip per cluster) — no vmap batching
            # rewrite, hence bit-parity with the sequential path.
            return jax.lax.map(
                lambda t: one_cluster(t[0], t[1], t[2], keys),
                (states, ctxs, enabled))

        def run(states, ctxs, enabled, keys):
            in_specs = (_tree_specs(states, P(CLUSTER_AXIS)),
                        _tree_specs(ctxs, P(CLUSTER_AXIS)),
                        P(CLUSTER_AXIS), P())
            out_shape = jax.eval_shape(body, states, ctxs, enabled, keys)
            out_specs = _tree_specs(out_shape, P(CLUSTER_AXIS))
            return shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)(states, ctxs, enabled,
                                                  keys)

        return self.collector.track(
            "fleet-walk", jax.jit(run, donate_argnums=(0,)))

    def _audit_program(self, shape_sig, audit, mesh, D):
        audit = tuple(audit)
        key = (("fleet-audit",) + shape_sig
               + (tuple((g.name, g.bind_signature()) for g in audit), D))

        def build():
            def one(state, ctx):
                return (violation_stack(audit, state, ctx),
                        jnp.stack([g.violation_scale(state, ctx)
                                   for g in audit]))

            def body(states, ctxs):
                return jax.lax.map(lambda t: one(t[0], t[1]),
                                   (states, ctxs))

            def run(states, ctxs):
                in_specs = (_tree_specs(states, P(CLUSTER_AXIS)),
                            _tree_specs(ctxs, P(CLUSTER_AXIS)))
                out_shape = jax.eval_shape(body, states, ctxs)
                out_specs = _tree_specs(out_shape, P(CLUSTER_AXIS))
                return shard_map(body, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs)(states, ctxs)

            return self.collector.track("fleet-audit", jax.jit(run))

        return self._programs.get_or_build(key, build)

    # ------------------------------------------------------- N-1 sweep
    def sweep_n1(self, fleet: FleetModel) -> list[dict]:
        """Per-cluster N-1 resilience risk for the whole fleet in ONE
        dispatch: every alive broker of every member killed in turn,
        scored by the shared scenario scorer (``whatif/engine.py``) over
        a ``[C, S]`` grid — the cluster axis sharded like the walk, the
        scenario axis vmapped like ``/simulate``. Returns one summary
        dict per member (maxRisk / riskiestBroker / violatedHardGoals of
        the riskiest loss), with risk numbers identical to a per-cluster
        ``WhatIfEngine`` N-1 sweep at the same shapes."""
        t0 = time.monotonic()
        with self.collector.cycle("fleet-sweep"), \
                self.tracer.span("fleet.sweep-n1",
                                 clusters=fleet.num_clusters):
            out = self._sweep_n1_impl(fleet)
        self.last_dispatch_s = time.monotonic() - t0
        return out

    def _sweep_n1_impl(self, fleet: FleetModel) -> list[dict]:
        members = fleet.members
        C = len(members)
        binds = [tuple((g.name, g.bind_signature())
                       for g in (gg.bind(m.metadata)
                                 for gg in self.optimizer.goals))
                 for m in members]
        topics = [m.metadata.num_topics for m in members]
        if any(b != binds[0] for b in binds) or \
                any(t != topics[0] for t in topics):
            # Degrade path (docs/fleet.md): heterogeneous goal bindings /
            # topic widths cannot share one scorer program — group like
            # propose() would; for the sweep the simple split is
            # per-subfleet recursion. The cluster-bucket floor is
            # suspended for the sub-sweeps: padding a C=1 sweep up to
            # the fleet size would score floor x S dead slots per member
            # (the sweep has no skip mask — every slot is real work).
            out: list[dict] = []
            floor = self.cluster_bucket_floor
            self.cluster_bucket_floor = 0
            try:
                for m in members:
                    sub = FleetModel.stack([(m.cluster_id, m.model,
                                             m.metadata)])
                    out.extend(self._sweep_n1_impl(sub))
            finally:
                self.cluster_bucket_floor = floor
            return out

        goals = [g.bind(members[0].metadata) for g in self.optimizer.goals]
        needs_tlc = any(g.uses_topic_leader_counts for g in goals)
        needs_topics = needs_tlc or any(g.uses_topic_counts for g in goals)
        num_topics = topics[0]
        B_f = members[0].model.num_brokers_padded
        P_f = members[0].model.num_partitions_padded

        alive_rows = []
        for m in members:
            bvalid = np.asarray(m.model.broker_valid)
            balive = np.asarray(m.model.broker_alive)
            alive_rows.append(np.nonzero(bvalid & balive)[0])
        S = max((len(r) for r in alive_rows), default=1)
        S_pad = round_up(S, self.scenario_pad_multiple)
        D, k, C_pad = self._layout(C)
        mesh = self._mesh(D)

        dead = np.zeros((C_pad, S_pad, B_f), bool)
        for c, rows in enumerate(alive_rows):
            dead[c, np.arange(len(rows)), rows] = True
        add = np.zeros((C_pad, B_f), bool)
        cap_scale = np.ones((C_pad, B_f, 4), np.float32)

        stacked = jax.tree.map(
            lambda a: (jnp.concatenate(
                [a, jnp.repeat(a[:1], C_pad - C, axis=0)])
                if C_pad > C else a), fleet.stacked)
        pscale = jnp.ones((C_pad, P_f), jnp.float32)
        pvalid = stacked.partition_valid

        sig = _shape_sig(stacked) + (S_pad,)
        key = (("fleet-sweep",) + sig
               + (tuple((g.name, g.bind_signature()) for g in goals),
                  num_topics if needs_topics else None, needs_tlc, D))

        def build():
            scorer = make_scenario_scorer(
                goals, self.optimizer.constraint.capacity_threshold,
                num_topics=num_topics, needs_topics=needs_topics,
                needs_tlc=needs_tlc)

            def one(model, dead_c, add_c, cap_c, ps_c, pv_c):
                viol, vscale, _hr, _hf, pressure, unavailable, n_off = \
                    scorer(model, dead_c, add_c, cap_c, ps_c, pv_c)
                return viol, vscale, pressure, unavailable, n_off

            def per_cluster(t):
                model, dead_c, add_c, cap_c, ps_c, pv_c = t
                return jax.vmap(
                    one, in_axes=(None, 0, None, None, None, None))(
                    model, dead_c, add_c, cap_c, ps_c, pv_c)

            def body(models, dead_b, add_b, cap_b, ps_b, pv_b):
                return jax.lax.map(per_cluster,
                                   (models, dead_b, add_b, cap_b, ps_b,
                                    pv_b))

            def run(models, dead_b, add_b, cap_b, ps_b, pv_b):
                args = (models, dead_b, add_b, cap_b, ps_b, pv_b)
                in_specs = tuple(_tree_specs(a, P(CLUSTER_AXIS))
                                 for a in args)
                out_shape = jax.eval_shape(body, *args)
                out_specs = _tree_specs(out_shape, P(CLUSTER_AXIS))
                return shard_map(body, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs)(*args)

            return self.collector.track("fleet-sweep", jax.jit(run))

        program = self._programs.get_or_build(key, build)
        self.collector.record_h2d(dead.nbytes + add.nbytes
                                  + cap_scale.nbytes)
        out = program(stacked, jnp.asarray(dead), jnp.asarray(add),
                      jnp.asarray(cap_scale), pscale, pvalid)
        fetched = jax.device_get(out)
        self.collector.record_d2h(self.collector.tree_bytes(fetched))
        viol, vscale, pressure, unavailable, _n_off = (
            np.asarray(a) for a in fetched)

        hard = np.array([g.hard for g in goals], bool)
        violated = violated_matrix(viol, vscale)           # [C_pad, S, G]
        n_hard = max(int(hard.sum()), 1)
        n_soft = max(int((~hard).sum()), 1)
        hard_frac = violated[..., hard].sum(axis=-1) / n_hard
        soft_frac = violated[..., ~hard].sum(axis=-1) / n_soft
        valid_parts = np.maximum(
            np.asarray(jax.device_get(pvalid)).sum(axis=1), 1)[:, None]
        risk = risk_scores(hard_frac, soft_frac, pressure,
                           unavailable.astype(int), valid_parts)

        summaries = []
        for c, m in enumerate(members):
            rows = alive_rows[c]
            n = len(rows)
            if n == 0:
                summaries.append({"clusterId": m.cluster_id, "maxRisk": 0.0,
                                  "riskiestBroker": None, "scenarios": 0})
                continue
            r = risk[c, :n]
            worst = int(np.argmax(r))
            broker_ids = m.metadata.broker_ids
            worst_row = int(rows[worst])
            summaries.append({
                "clusterId": m.cluster_id,
                "maxRisk": round(float(r[worst]), 4),
                "riskiestBroker": (broker_ids[worst_row]
                                   if worst_row < len(broker_ids)
                                   else worst_row),
                "violatedHardGoals": [
                    g.name for g, v in zip(goals, violated[c, worst])
                    if v and g.hard],
                "scenarios": n})
        return summaries

    # ------------------------------------------------- trajectory sweep
    def sweep_trajectories(self, fleet: FleetModel, trajectories
                           ) -> list[dict]:
        """Forecast trajectory sweep across the whole fleet in ONE
        dispatch: the ``[S]`` projected-load scenario axis composed with
        the ``[C]`` cluster axis — cluster axis sharded like the walk,
        scenario axis vmapped like ``/simulate``, scored by the SAME
        shared scenario scorer, so a fleet-projected risk means exactly
        what a single-cluster forecast sweep reports.

        ``trajectories`` is either one scenario list (every member
        scores the same horizon/quantile grid, factors resolved against
        each member's own topics) or ``{cluster_id: [scenarios]}`` with
        equal lengths (each member its own fitted factors). Returns one
        summary per member with per-scenario risk/pressure rows."""
        t0 = time.monotonic()
        with self.collector.cycle("fleet-forecast"), \
                self.tracer.span("fleet.sweep-trajectories",
                                 clusters=fleet.num_clusters):
            out = self._sweep_trajectories_impl(fleet, trajectories)
        self.last_dispatch_s = time.monotonic() - t0
        return out

    def _sweep_trajectories_impl(self, fleet: FleetModel, trajectories
                                 ) -> list[dict]:
        from ..whatif.engine import trajectory_pscale_row
        members = fleet.members
        C = len(members)
        if isinstance(trajectories, dict):
            missing = [m.cluster_id for m in members
                       if m.cluster_id not in trajectories]
            if missing:
                raise ValueError(
                    f"sweep_trajectories: no trajectory grid for fleet "
                    f"member(s) {missing}; the per-cluster dict form "
                    f"must cover every member")
            per_member = [trajectories[m.cluster_id] for m in members]
        else:
            per_member = [list(trajectories)] * C
        if not per_member or not per_member[0]:
            raise ValueError("sweep_trajectories requires at least one "
                             "scenario")
        S = len(per_member[0])
        if any(len(t) != S for t in per_member):
            raise ValueError(
                "every member must score the same scenario count (one "
                "compiled [C, S] grid); pad shorter trajectories with "
                "no-op scenarios")

        binds = [tuple((g.name, g.bind_signature())
                       for g in (gg.bind(m.metadata)
                                 for gg in self.optimizer.goals))
                 for m in members]
        topics = [m.metadata.num_topics for m in members]
        if any(b != binds[0] for b in binds) or \
                any(t != topics[0] for t in topics):
            # Heterogeneous bindings: per-member recursion, same degrade
            # path (and bucket-floor suspension) as the N-1 sweep.
            out: list[dict] = []
            floor = self.cluster_bucket_floor
            self.cluster_bucket_floor = 0
            try:
                for m, traj in zip(members, per_member):
                    sub = FleetModel.stack([(m.cluster_id, m.model,
                                             m.metadata)])
                    out.extend(self._sweep_trajectories_impl(sub, traj))
            finally:
                self.cluster_bucket_floor = floor
            return out

        goals = [g.bind(members[0].metadata) for g in self.optimizer.goals]
        needs_tlc = any(g.uses_topic_leader_counts for g in goals)
        needs_topics = needs_tlc or any(g.uses_topic_counts for g in goals)
        num_topics = topics[0]
        B_f = members[0].model.num_brokers_padded
        P_f = members[0].model.num_partitions_padded
        S_pad = round_up(S, self.scenario_pad_multiple)
        D, k, C_pad = self._layout(C)
        mesh = self._mesh(D)

        # Per-(cluster, scenario) load-scale planes: each member's
        # factors resolve against its OWN topic ids; padding rows (both
        # axes) are factor-1 no-ops.
        pscale = np.ones((C_pad, S_pad, P_f), np.float32)
        for c, (m, traj) in enumerate(zip(members, per_member)):
            ptopic = np.asarray(m.model.partition_topic)
            for s, scn in enumerate(traj):
                pscale[c, s] = trajectory_pscale_row(
                    scn, m.metadata.topic_index, ptopic)

        stacked = jax.tree.map(
            lambda a: (jnp.concatenate(
                [a, jnp.repeat(a[:1], C_pad - C, axis=0)])
                if C_pad > C else a), fleet.stacked)
        pvalid = stacked.partition_valid

        sig = _shape_sig(stacked) + (S_pad,)
        key = (("fleet-forecast",) + sig
               + (tuple((g.name, g.bind_signature()) for g in goals),
                  num_topics if needs_topics else None, needs_tlc, D))

        def build():
            scorer = make_scenario_scorer(
                goals, self.optimizer.constraint.capacity_threshold,
                num_topics=num_topics, needs_topics=needs_topics,
                needs_tlc=needs_tlc)

            def one(model, ps, pv):
                B = model.num_brokers_padded
                no_dead = jnp.zeros((B,), bool)
                no_cap = jnp.ones((B, 4), jnp.float32)
                viol, vscale, _hr, _hf, pressure, unavailable, n_off = \
                    scorer(model, no_dead, no_dead, no_cap, ps, pv)
                return viol, vscale, pressure, unavailable, n_off

            def per_cluster(t):
                model, ps_c, pv_c = t
                return jax.vmap(one, in_axes=(None, 0, None))(
                    model, ps_c, pv_c)

            def body(models, ps_b, pv_b):
                return jax.lax.map(per_cluster, (models, ps_b, pv_b))

            def run(models, ps_b, pv_b):
                args = (models, ps_b, pv_b)
                in_specs = tuple(_tree_specs(a, P(CLUSTER_AXIS))
                                 for a in args)
                out_shape = jax.eval_shape(body, *args)
                out_specs = _tree_specs(out_shape, P(CLUSTER_AXIS))
                return shard_map(body, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs)(*args)

            return self.collector.track("fleet-forecast", jax.jit(run))

        program = self._programs.get_or_build(key, build)
        self.collector.record_h2d(pscale.nbytes)
        out = program(stacked, jnp.asarray(pscale), pvalid)
        fetched = jax.device_get(out)
        self.collector.record_d2h(self.collector.tree_bytes(fetched))
        viol, vscale, pressure, unavailable, _n_off = (
            np.asarray(a) for a in fetched)

        hard = np.array([g.hard for g in goals], bool)
        violated = violated_matrix(viol, vscale)        # [C_pad, S_pad, G]
        n_hard = max(int(hard.sum()), 1)
        n_soft = max(int((~hard).sum()), 1)
        hard_frac = violated[..., hard].sum(axis=-1) / n_hard
        soft_frac = violated[..., ~hard].sum(axis=-1) / n_soft
        valid_parts = np.maximum(
            np.asarray(jax.device_get(pvalid)).sum(axis=1), 1)[:, None]
        risk = risk_scores(hard_frac, soft_frac, pressure,
                           unavailable.astype(int), valid_parts)

        summaries = []
        for c, (m, traj) in enumerate(zip(members, per_member)):
            rows = [{"scenario": scn.name,
                     "horizonMs": scn.horizon_ms,
                     "quantile": scn.quantile,
                     "risk": round(float(risk[c, s]), 4),
                     "capacityPressure": round(float(pressure[c, s]), 4),
                     "violatedHardGoals": [
                         g.name for g, v in zip(goals, violated[c, s])
                         if v and g.hard]}
                    for s, scn in enumerate(traj)]
            worst = max(range(S), key=lambda s: risk[c, s])
            summaries.append({"clusterId": m.cluster_id,
                              "maxRisk": round(float(risk[c, worst]), 4),
                              "riskiest": traj[worst].name,
                              "scenarios": rows})
        return summaries
