"""Global move-budget coordinator: one fleet-wide movement allowance.

N clusters healing at once each execute within their OWN concurrency
caps, but the caps don't compose: simultaneous heals multiply into the
shared network/ops capacity behind every cluster (the cross-cluster
mirrors, the shared object store, the on-call). The coordinator hands
out per-tick move/leadership grants from ONE configurable fleet-wide
budget (``fleet.move.budget.per.tick``), weighted by per-member urgency:
hard-goal violations first, then time-to-breach from the PR-13 capacity
forecast. Unspent budget carries over (bounded by
``fleet.budget.carry.max.ticks`` ticks' worth) so a quiet tick buys a
burst later instead of evaporating.

Allocation is deterministic: members sort by (hard violations desc,
time-to-breach asc, cluster id), weights are pure arithmetic on the
request fields, and leftover units distribute one-by-one in sort order —
the same requests always produce the same grants, which the chaos
replay gate relies on. Grants, denials, and carry-over are metered and
journaled (``fleet`` category) per tick.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class BudgetRequest:
    """One member's ask for this tick."""

    cluster_id: str
    #: moves the member's current proposal set wants to execute
    requested: int
    #: hard-goal violations outstanding (primary urgency key)
    hard_violations: int = 0
    #: forecast time-to-breach in ms (secondary urgency key; None = no
    #: projected breach)
    time_to_breach_ms: int | None = None


@dataclass(frozen=True)
class BudgetGrant:
    cluster_id: str
    requested: int
    granted: int
    urgency: float

    @property
    def denied(self) -> int:
        return self.requested - self.granted

    def to_json(self) -> dict:
        return {"requested": self.requested, "granted": self.granted,
                "denied": self.denied,
                "urgency": round(self.urgency, 4)}


class MoveBudgetCoordinator:
    """Per-tick urgency-weighted grants from one fleet-wide budget."""

    def __init__(self, *, budget_per_tick: int = 0,
                 carry_max_ticks: int = 2, registry=None,
                 journal=None) -> None:
        #: 0 = unbudgeted: every request is granted in full (the
        #: coordinator still meters, so turning a budget on later starts
        #: from observed demand).
        self.budget_per_tick = max(budget_per_tick, 0)
        self.carry_max = self.budget_per_tick * max(carry_max_ticks, 0)
        self.carry = 0
        self.journal = journal
        self.ticks = 0
        self.total_granted = 0
        self.total_denied = 0
        self.last_grants: dict[str, BudgetGrant] = {}
        self._granted_meter = self._denied_meter = None
        if registry is not None:
            from ..core.sensors import MetricRegistry
            name = MetricRegistry.name
            self._granted_meter = registry.meter(
                name("FleetBudget", "moves-granted-rate"))
            self._denied_meter = registry.meter(
                name("FleetBudget", "moves-denied-rate"))
            registry.gauge(name("FleetBudget", "carry-over"),
                           lambda: self.carry)

    @staticmethod
    def urgency(req: BudgetRequest) -> float:
        """Pure urgency score: each outstanding hard violation adds a
        full unit; a projected breach adds up to one more unit scaling
        inversely with how far out it is (a breach 1 minute away ≈ +0.5,
        one an hour away ≈ +0.02)."""
        score = 1.0 + req.hard_violations
        if req.time_to_breach_ms is not None:
            score += 1.0 / (1.0 + req.time_to_breach_ms / 60_000.0)
        return score

    def allocate(self, requests: list[BudgetRequest],
                 now_ms: int = 0) -> dict[str, BudgetGrant]:
        """Grant this tick's budget across ``requests``. Returns grants
        keyed by cluster id (every requester gets an entry, possibly
        granted=0)."""
        self.ticks += 1
        if not requests:
            self.last_grants = {}
            return {}
        ordered = sorted(
            requests,
            key=lambda r: (-r.hard_violations,
                           float("inf") if r.time_to_breach_ms is None
                           else r.time_to_breach_ms,
                           r.cluster_id))
        if self.budget_per_tick <= 0:
            grants = {r.cluster_id: BudgetGrant(r.cluster_id, r.requested,
                                                r.requested,
                                                self.urgency(r))
                      for r in ordered}
            return self._finish(grants, now_ms, unbudgeted=True)
        available = self.budget_per_tick + self.carry
        weights = {r.cluster_id: self.urgency(r) for r in ordered}
        total_w = sum(weights[r.cluster_id] for r in ordered
                      if r.requested > 0) or 1.0
        granted = {}
        for r in ordered:
            share = int(available * weights[r.cluster_id] / total_w) \
                if r.requested > 0 else 0
            granted[r.cluster_id] = min(share, r.requested)
        spent = sum(granted.values())
        # Leftover (rounding remainders + capped shares) distributes
        # one-by-one in priority order to members still short — the
        # deterministic largest-need pass.
        leftover = available - spent
        progress = True
        while leftover > 0 and progress:
            progress = False
            for r in ordered:
                if leftover <= 0:
                    break
                if granted[r.cluster_id] < r.requested:
                    granted[r.cluster_id] += 1
                    leftover -= 1
                    progress = True
        self.carry = min(leftover, self.carry_max)
        grants = {r.cluster_id: BudgetGrant(r.cluster_id, r.requested,
                                            granted[r.cluster_id],
                                            weights[r.cluster_id])
                  for r in ordered}
        return self._finish(grants, now_ms, unbudgeted=False)

    def _finish(self, grants: dict[str, BudgetGrant], now_ms: int,
                *, unbudgeted: bool) -> dict[str, BudgetGrant]:
        tick_granted = sum(g.granted for g in grants.values())
        tick_denied = sum(g.denied for g in grants.values())
        self.total_granted += tick_granted
        self.total_denied += tick_denied
        self.last_grants = grants
        if self._granted_meter is not None:
            self._granted_meter.mark(tick_granted)
            self._denied_meter.mark(tick_denied)
        if self.journal is not None and grants:
            self.journal.record(
                "fleet", "budget-allocated",
                detail={"granted": tick_granted, "denied": tick_denied,
                        "carry": self.carry,
                        "budget": (None if unbudgeted
                                   else self.budget_per_tick),
                        "grants": {cid: g.to_json()
                                   for cid, g in grants.items()}})
        return grants

    def to_json(self) -> dict:
        return {"budgetPerTick": self.budget_per_tick or None,
                "carry": self.carry,
                "carryMax": self.carry_max,
                "ticks": self.ticks,
                "totalGranted": self.total_granted,
                "totalDenied": self.total_denied,
                "lastGrants": {cid: g.to_json()
                               for cid, g in self.last_grants.items()}}
