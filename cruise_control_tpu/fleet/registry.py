"""Fleet registry: the host side of the fleet control plane.

Owns per-cluster ``LoadMonitor`` instances (and their cluster-scoped
``ProposalCache``s), drives ONE shared tick that builds every member's
model, runs the batched fleet propose (and, on its configured cadence,
the batched N-1 resilience sweep) through :class:`..fleet.FleetOptimizer`
in one device dispatch, unstacks the per-cluster results back into each
member's cache, and fans anomaly detection out per cluster. Surfaced as
``GET /fleet`` (summary) and ``POST /fleet/rebalance`` (forced tick)
through ``api/server.py``/``facade.py``, and as the ``fleet`` section of
``/devicestats``.
"""

from __future__ import annotations

import logging
import threading
import time as _time
from dataclasses import dataclass, field

from ..analyzer import OptimizationOptions
from ..analyzer.optimizer import OptimizationFailureError
from ..api.precompute import ProposalCache
from ..model.fleet import FleetModel
from .engine import FleetOptimizer

LOG = logging.getLogger(__name__)


@dataclass
class FleetClusterHandle:
    """One registered cluster: its monitor, its cluster-scoped proposal
    cache, an optional per-cluster anomaly detector, and the registry's
    last per-cluster readouts."""

    cluster_id: str
    monitor: object
    cache: ProposalCache | None = None
    detector: object = None
    ready: bool = False
    generation: int | None = None
    last_error: str | None = None
    last_risk: dict | None = None
    last_forecast: dict | None = None
    last_summary: dict = field(default_factory=dict)


class FleetRegistry:
    """One control plane, many clusters, one dispatch per tick.

    Members register with their own ``LoadMonitor`` (each monitor keeps
    its private sample history and model generation); the shared tick
    builds every ready member's model host-side (the members' resident
    device state and delta-ingest paths apply unchanged), stacks them
    into a :class:`FleetModel` shape bucket, and runs optimize across
    the ``[C, ...]`` cluster axis as one device dispatch. Results land
    in each member's generation- AND cluster-keyed cache, so the
    members' ``/proposals`` reads stay cache hits with the same
    freshness machinery the single-cluster path uses.
    """

    def __init__(self, optimizer, *, max_clusters: int = 64,
                 broker_pad_multiple: int = 8,
                 partition_pad_multiple: int = 128,
                 risk_sweep_every: int = 1,
                 options: OptimizationOptions | None = None,
                 registry=None, tracer=None, collector=None,
                 now_ms=None, max_devices: int | None = None) -> None:
        from ..core.runtime_obs import default_collector
        from ..core.sensors import MetricRegistry
        from ..core.tracing import default_tracer
        self.max_clusters = max_clusters
        self.broker_pad_multiple = broker_pad_multiple
        self.partition_pad_multiple = partition_pad_multiple
        #: run the batched N-1 resilience sweep every Nth tick (0 = off).
        self.risk_sweep_every = risk_sweep_every
        #: the fleet tick is the members' background proposal refresher,
        #: so it computes with the cache's dry-run semantics: an
        #: unfixable hard goal is a cacheable finding, not an error to
        #: re-burn one fleet dispatch on every tick.
        self.options = options or OptimizationOptions(
            skip_hard_goal_check=True)
        self._now_ms = now_ms or (lambda: int(_time.time() * 1000))
        self.registry = registry or MetricRegistry()
        self.tracer = tracer or default_tracer()
        self.collector = collector or default_collector()
        self.engine = FleetOptimizer(optimizer, max_devices=max_devices,
                                     registry=self.registry,
                                     tracer=self.tracer,
                                     collector=self.collector)
        self._members: dict[str, FleetClusterHandle] = {}
        self._lock = threading.RLock()
        #: serializes whole ticks: the background ticker and a forced
        #: POST /fleet/rebalance must never run two fleet dispatches
        #: concurrently (duplicate device work + racing per-member
        #: readout writes).
        self._tick_lock = threading.Lock()
        self._ticker: threading.Thread | None = None
        self._stop = threading.Event()
        self.ticks = 0
        self.last_tick_ms: int | None = None
        self.last_bucket: dict | None = None
        name = MetricRegistry.name
        self._tick_timer = self.registry.timer(
            name("FleetRegistry", "tick-timer"))
        self._tick_errors = self.registry.meter(
            name("FleetRegistry", "tick-failure-rate"))
        self.registry.gauge(name("FleetRegistry", "clusters"),
                            lambda: len(self._members))
        self.registry.gauge(
            name("FleetRegistry", "last-dispatch-ms"),
            lambda: (None if self.engine.last_dispatch_s is None
                     else round(self.engine.last_dispatch_s * 1e3, 3)))

    # ----------------------------------------------------------- members
    def register(self, cluster_id: str, monitor, *,
                 proposal_cache: ProposalCache | None = None,
                 detector=None) -> FleetClusterHandle:
        """Add a cluster. ``proposal_cache`` defaults to a fresh
        cluster-scoped cache over this monitor and the shared optimizer
        (pass the facade's cache for the local cluster so ``/proposals``
        serves fleet-computed results). The cache must carry this
        cluster's id — that scoping is what makes cross-serving
        impossible (``ProposalCache.store``)."""
        with self._lock:
            if cluster_id in self._members:
                raise ValueError(f"cluster {cluster_id!r} already "
                                 "registered")
            if len(self._members) >= self.max_clusters:
                raise ValueError(
                    f"fleet is full: {self.max_clusters} clusters "
                    "(fleet.max.clusters)")
            if proposal_cache is None:
                proposal_cache = ProposalCache(
                    monitor, self.engine.optimizer,
                    now_ms=self._now_ms, cache_id=cluster_id)
            elif proposal_cache.cache_id != cluster_id:
                raise ValueError(
                    f"proposal cache id {proposal_cache.cache_id!r} does "
                    f"not match cluster {cluster_id!r}")
            handle = FleetClusterHandle(cluster_id=cluster_id,
                                        monitor=monitor,
                                        cache=proposal_cache,
                                        detector=detector)
            self._members[cluster_id] = handle
            return handle

    def deregister(self, cluster_id: str) -> None:
        with self._lock:
            self._members.pop(cluster_id, None)

    @property
    def cluster_ids(self) -> list[str]:
        with self._lock:
            return list(self._members)

    def member(self, cluster_id: str) -> FleetClusterHandle:
        with self._lock:
            return self._members[cluster_id]

    def scrape_registries(self) -> list:
        """Cluster-namespaced views of every member's sensor registries
        for the merged ``/metrics`` exposition: families render as
        ``cc_<cluster>_LoadMonitor_...`` etc., so two members' identical
        sensor names never collapse into unlabeled numeric-suffix
        duplicates (tests/prom_lint.py rejects those)."""
        from ..core.sensors import NamespacedRegistry
        out = [self.registry]
        with self._lock:
            members = list(self._members.values())
        for h in members:
            reg = getattr(h.monitor, "registry", None)
            if reg is not None:
                out.append(NamespacedRegistry(reg, h.cluster_id))
            if h.cache is not None and h.cache.registry is not reg:
                # Cluster-scoped caches already carry the id in their
                # group name (ProposalCache.<id>.*) — no second prefix.
                out.append(h.cache.registry)
        return out

    # -------------------------------------------------------------- tick
    def tick(self, now_ms: int | None = None, *,
             force: bool = False) -> dict:
        """One fleet cycle: build every member's model; when ANY member's
        cache no longer answers its monitor generation (or ``force``),
        run the batched propose for EVERY ready member — the dispatch is
        batched anyway, and proposing only the stale subset would both
        compile one program set per distinct subset size and leave the
        others' risk readouts stale; then (on its cadence) the batched
        N-1 risk sweep and the per-cluster anomaly fan-out. Ticks are
        serialized (the background ticker vs a forced
        ``/fleet/rebalance``). Returns the tick summary."""
        with self._tick_lock:
            return self._tick_locked(now_ms, force)

    def _tick_locked(self, now_ms: int | None, force: bool) -> dict:
        now = now_ms if now_ms is not None else self._now_ms()
        t0 = _time.monotonic()
        with self._lock:
            members = list(self._members.values())
        # Pin the engine's cluster-axis shape floor to the fleet size so
        # a partial-readiness tick reuses the full fleet's compiled
        # programs (padding slots are skip-branch cheap).
        self.engine.cluster_bucket_floor = len(members)
        ready: list[tuple[FleetClusterHandle, object]] = []
        with self.tracer.span("fleet.tick", clusters=len(members)), \
                self.collector.cycle("fleet-tick"):
            for h in members:
                try:
                    result = h.monitor.cluster_model(now)
                except Exception as e:
                    h.ready = False
                    h.last_error = f"{type(e).__name__}: {e}"
                    continue
                h.ready = True
                h.last_error = None
                h.generation = result.generation
                ready.append((h, result))
            summary = {"clusters": len(members), "ready": len(ready),
                       "proposed": 0, "errors": 0, "skipped": 0}
            if not ready:
                self.ticks += 1
                self.last_tick_ms = now
                self._tick_timer.update(_time.monotonic() - t0)
                return summary
            need = force or any(h.cache is None or not h.cache.valid()
                                for h, _ in ready)
            todo = ready if need else []
            summary["skipped"] = len(ready) - len(todo)
            sweep_due = bool(self.risk_sweep_every
                             and self.ticks % self.risk_sweep_every == 0)
            if not todo and not sweep_due:
                # Nothing to compute: don't pay the fleet stack (pad +
                # device upload of every member's model) for a tick that
                # would use none of it.
                self.ticks += 1
                self.last_tick_ms = now
                self._tick_timer.update(_time.monotonic() - t0)
                return summary
            fleet = FleetModel.stack(
                [(h.cluster_id, r.model, r.metadata, r.generation,
                  r.stale) for h, r in ready],
                broker_pad_multiple=self.broker_pad_multiple,
                partition_pad_multiple=self.partition_pad_multiple)
            self.last_bucket = fleet.bucket
            if todo:
                results = self.engine.propose(fleet, self.options)
                for (h, r), res in zip(todo, results):
                    if isinstance(res, OptimizationFailureError):
                        h.last_error = str(res)
                        summary["errors"] += 1
                        res = res.result
                    h.last_summary = self._cluster_summary(h, res)
                    if h.cache is not None:
                        stored = h.cache.store(res,
                                               generation=r.generation,
                                               cache_id=h.cluster_id)
                        if not stored:
                            LOG.info(
                                "fleet[%s]: generation moved mid-"
                                "dispatch (%s -> %s); result dropped",
                                h.cluster_id, r.generation,
                                h.monitor.generation)
                    summary["proposed"] += 1
            if sweep_due:
                try:
                    risks = self.engine.sweep_n1(fleet)
                except Exception:
                    LOG.warning("fleet N-1 sweep failed", exc_info=True)
                    self._tick_errors.mark()
                else:
                    by_id = {r["clusterId"]: r for r in risks}
                    for h, _ in ready:
                        if h.cluster_id in by_id:
                            h.last_risk = by_id[h.cluster_id]
            # Anomaly fan-out: each member's detector sweep runs on the
            # shared tick (AnomalyDetectorManager.run_once semantics) —
            # one scheduler, per-cluster detection and self-healing.
            for h, _ in ready:
                if h.detector is None:
                    continue
                try:
                    h.detector.run_once(now)
                except Exception:
                    LOG.warning("fleet[%s]: anomaly fan-out failed",
                                h.cluster_id, exc_info=True)
                    self._tick_errors.mark()
        self.ticks += 1
        self.last_tick_ms = now
        self._tick_timer.update(_time.monotonic() - t0)
        return summary

    def forecast_sweep(self, trajectories, now_ms: int | None = None
                       ) -> list[dict]:
        """Sweep projected load trajectories across EVERY ready member
        in one batched ``[C, S]`` dispatch (``FleetOptimizer.
        sweep_trajectories`` — the scenario axis composed with the
        cluster axis). ``trajectories`` is one
        :class:`~..whatif.TrajectoryScale` grid (each member's factors
        resolve against its own topics) or ``{cluster_id: grid}``.
        Per-member summaries land on the handles for ``/fleet``.
        Serialized with the background tick on the tick mutex — both
        paths dispatch on the shared engine and pin its cluster-axis
        shape floor."""
        now = now_ms if now_ms is not None else self._now_ms()
        with self._tick_lock:
            return self._forecast_sweep_locked(trajectories, now)

    def _forecast_sweep_locked(self, trajectories, now: int) -> list[dict]:
        with self._lock:
            members = list(self._members.values())
        self.engine.cluster_bucket_floor = len(members)
        ready = []
        for h in members:
            try:
                result = h.monitor.cluster_model(now)
            except Exception as e:
                h.ready = False
                h.last_error = f"{type(e).__name__}: {e}"
                continue
            h.ready = True
            h.last_error = None
            ready.append((h, result))
        if not ready:
            return []
        fleet = FleetModel.stack(
            [(h.cluster_id, r.model, r.metadata, r.generation, r.stale)
             for h, r in ready],
            broker_pad_multiple=self.broker_pad_multiple,
            partition_pad_multiple=self.partition_pad_multiple)
        self.last_bucket = fleet.bucket
        summaries = self.engine.sweep_trajectories(fleet, trajectories)
        by_id = {s["clusterId"]: s for s in summaries}
        for h, _ in ready:
            if h.cluster_id in by_id:
                h.last_forecast = by_id[h.cluster_id]
        return summaries

    @staticmethod
    def _cluster_summary(h: FleetClusterHandle, res) -> dict:
        total = max(len(res.goal_results), 1)
        violated = [g.name for g in res.goal_results if not g.satisfied]
        return {
            # Documented in docs/fleet.md: the fraction of the chain's
            # goals currently satisfied — 1.0 is a fully balanced member.
            "balanceScore": round(1.0 - len(violated) / total, 4),
            "violatedGoals": violated,
            "violatedHardGoals": res.violated_hard_goals,
            "numProposals": len(res.proposals),
            "numMoves": res.num_moves,
            "staleModel": res.stale_model,
        }

    # -------------------------------------------------- background loop
    def start(self, tick_interval_s: float) -> None:
        """Background shared tick (fleet.tick.ms); idempotent."""
        if self._ticker is not None and self._ticker.is_alive():
            return
        stop = threading.Event()
        self._stop = stop

        def loop():
            while not stop.wait(tick_interval_s):
                try:
                    self.tick()
                except Exception:
                    LOG.warning("fleet tick failed", exc_info=True)
                    self._tick_errors.mark()

        self._ticker = threading.Thread(target=loop, daemon=True,
                                        name="fleet-tick")
        self._ticker.start()

    def stop(self) -> None:
        self._stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=5)
            self._ticker = None

    # ----------------------------------------------------------- surface
    def summary_json(self, now_ms: int | None = None) -> dict:
        """The ``GET /fleet`` payload: per-cluster balance score,
        freshness and risk, plus the shared bucket/dispatch readout."""
        now = now_ms if now_ms is not None else self._now_ms()
        with self._lock:
            members = list(self._members.values())
        clusters = []
        for h in members:
            row = {"clusterId": h.cluster_id,
                   "ready": h.ready,
                   "generation": h.generation,
                   "lastError": h.last_error,
                   **h.last_summary}
            if h.cache is not None:
                row["freshness"] = h.cache.freshness_json(now)
            if h.last_risk is not None:
                row["risk"] = h.last_risk
            if h.last_forecast is not None:
                row["forecast"] = {
                    "maxRisk": h.last_forecast.get("maxRisk"),
                    "riskiest": h.last_forecast.get("riskiest")}
            clusters.append(row)
        return {"enabled": True,
                "numClusters": len(members),
                "ticks": self.ticks,
                "lastTickMs": self.last_tick_ms,
                "bucket": self.last_bucket,
                "lastDispatchMs": (
                    None if self.engine.last_dispatch_s is None
                    else round(self.engine.last_dispatch_s * 1e3, 3)),
                "clusters": clusters}

    def stats_json(self) -> dict:
        """The ``fleet`` section of ``/devicestats``: cluster count,
        current shape bucket, last dispatch wall clock."""
        return {"clusterCount": len(self._members),
                "ticks": self.ticks,
                "bucket": self.last_bucket,
                "lastDispatchMs": (
                    None if self.engine.last_dispatch_s is None
                    else round(self.engine.last_dispatch_s * 1e3, 3)),
                "lastTickMs": self.last_tick_ms}

    def rebalance(self, now_ms: int | None = None) -> dict:
        """``POST /fleet/rebalance``: force one tick now (every member
        recomputes regardless of cache validity) and return the summary.
        Proposals land in the members' caches; EXECUTION stays a
        per-cluster decision through each cluster's own endpoints — a
        fleet-wide execute-everything switch is exactly the blast radius
        this layer exists to avoid."""
        tick = self.tick(now_ms, force=True)
        return {"tick": tick, **self.summary_json(now_ms)}
