"""Fleet registry: the host side of the fleet control plane.

Owns per-cluster ``LoadMonitor`` instances (and their cluster-scoped
``ProposalCache``s), drives ONE shared tick that builds every member's
model, runs the batched fleet propose (and, on its configured cadence,
the batched N-1 resilience sweep) through :class:`..fleet.FleetOptimizer`
in one device dispatch, unstacks the per-cluster results back into each
member's cache, and fans anomaly detection out per cluster. Surfaced as
``GET /fleet`` (summary) and ``POST /fleet/rebalance`` (forced tick)
through ``api/server.py``/``facade.py``, and as the ``fleet`` section of
``/devicestats``.
"""

from __future__ import annotations

import logging
import threading
import time as _time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from ..analyzer import OptimizationOptions
from ..analyzer.optimizer import OptimizationFailureError
from ..api.precompute import ProposalCache
from ..core.aggregator import NotEnoughValidWindowsError
from ..model.fleet import FleetModel
from .backends import CircuitBreaker, MemberHealth
from .budget import BudgetRequest, MoveBudgetCoordinator
from .engine import FleetOptimizer

LOG = logging.getLogger(__name__)


@dataclass
class FleetClusterHandle:
    """One registered cluster: its monitor, its cluster-scoped proposal
    cache, an optional per-cluster anomaly detector, its failure-domain
    state (endpoint backend + circuit breaker + health machine), and the
    registry's last per-cluster readouts."""

    cluster_id: str
    monitor: object
    cache: ProposalCache | None = None
    detector: object = None
    #: the member's RemoteBackend (fleet/backends.py) when its admin/
    #: sampler ride a per-cluster endpoint; None for in-process members
    backend: object = None
    #: per-member circuit breaker — shared with the backend when one is
    #: wired, so registry fetch outcomes and backend call outcomes feed
    #: ONE rolling window
    breaker: CircuitBreaker | None = None
    endpoint: str = ""
    health: str = MemberHealth.HEALTHY
    degraded_ticks: int = 0
    health_since_ms: int | None = None
    #: journal seq of the latest health transition (cause-chain anchor)
    health_seq: int | None = None
    ready: bool = False
    generation: int | None = None
    last_error: str | None = None
    last_risk: dict | None = None
    last_forecast: dict | None = None
    last_summary: dict = field(default_factory=dict)


class FleetRegistry:
    """One control plane, many clusters, one dispatch per tick.

    Members register with their own ``LoadMonitor`` (each monitor keeps
    its private sample history and model generation); the shared tick
    builds every ready member's model host-side (the members' resident
    device state and delta-ingest paths apply unchanged), stacks them
    into a :class:`FleetModel` shape bucket, and runs optimize across
    the ``[C, ...]`` cluster axis as one device dispatch. Results land
    in each member's generation- AND cluster-keyed cache, so the
    members' ``/proposals`` reads stay cache hits with the same
    freshness machinery the single-cluster path uses.
    """

    def __init__(self, optimizer, *, max_clusters: int = 64,
                 broker_pad_multiple: int = 8,
                 partition_pad_multiple: int = 128,
                 risk_sweep_every: int = 1,
                 options: OptimizationOptions | None = None,
                 registry=None, tracer=None, collector=None,
                 now_ms=None, max_devices: int | None = None,
                 quarantine_after: int = 3, fetch_workers: int = 4,
                 fetch_deadline_ms: int = 0, seed: int = 0,
                 breaker_window_ms: int = 60_000,
                 breaker_failures: int = 3, breaker_open_ms: int = 30_000,
                 journal=None, notifier=None,
                 budget: MoveBudgetCoordinator | None = None) -> None:
        from ..core.runtime_obs import default_collector
        from ..core.sensors import MetricRegistry
        from ..core.tracing import default_tracer
        self.max_clusters = max_clusters
        self.broker_pad_multiple = broker_pad_multiple
        self.partition_pad_multiple = partition_pad_multiple
        #: run the batched N-1 resilience sweep every Nth tick (0 = off).
        self.risk_sweep_every = risk_sweep_every
        #: the fleet tick is the members' background proposal refresher,
        #: so it computes with the cache's dry-run semantics: an
        #: unfixable hard goal is a cacheable finding, not an error to
        #: re-burn one fleet dispatch on every tick.
        self.options = options or OptimizationOptions(
            skip_hard_goal_check=True)
        self._now_ms = now_ms or (lambda: int(_time.time() * 1000))
        self.registry = registry or MetricRegistry()
        self.tracer = tracer or default_tracer()
        self.collector = collector or default_collector()
        self.engine = FleetOptimizer(optimizer, max_devices=max_devices,
                                     registry=self.registry,
                                     tracer=self.tracer,
                                     collector=self.collector)
        #: consecutive degraded ticks before a member quarantines
        #: (fleet.quarantine.after.ticks)
        self.quarantine_after = max(quarantine_after, 1)
        #: per-member fetch-round pool size (fleet.fetch.workers):
        #: 0 = fully serial fetches in registration order, the chaos
        #: harness's deterministic mode — threads racing a shared sim
        #: clock would make replays diverge
        self.fetch_workers = max(fetch_workers, 0)
        #: wall-clock cap per member fetch future (fleet.fetch.deadline
        #: .ms, pool mode only): a hung endpoint forfeits ITS tick while
        #: siblings proceed. 0 = unbounded (serial mode relies on the
        #: backend's per-call deadline instead).
        self.fetch_deadline_ms = fetch_deadline_ms
        self.seed = seed
        self.breaker_window_ms = breaker_window_ms
        self.breaker_failures = breaker_failures
        self.breaker_open_ms = breaker_open_ms
        #: flight recorder (core/events.py, ``fleet`` category) — health
        #: transitions journal with cause links; None = silent
        self.journal = journal
        #: anomaly notifier fed FLEET_MEMBER_QUARANTINED; None = silent
        self.notifier = notifier
        #: global move-budget coordinator (fleet/budget.py); None = no
        #: budget accounting
        self.budget = budget
        self._pool = (ThreadPoolExecutor(max_workers=self.fetch_workers,
                                         thread_name_prefix="fleet-fetch")
                      if self.fetch_workers > 0 else None)
        self._members: dict[str, FleetClusterHandle] = {}
        self._lock = threading.RLock()
        #: serializes whole ticks: the background ticker and a forced
        #: POST /fleet/rebalance must never run two fleet dispatches
        #: concurrently (duplicate device work + racing per-member
        #: readout writes).
        self._tick_lock = threading.Lock()
        self._ticker: threading.Thread | None = None
        self._stop = threading.Event()
        self.ticks = 0
        self.last_tick_ms: int | None = None
        self.last_bucket: dict | None = None
        name = MetricRegistry.name
        self._tick_timer = self.registry.timer(
            name("FleetRegistry", "tick-timer"))
        self._tick_errors = self.registry.meter(
            name("FleetRegistry", "tick-failure-rate"))
        self.registry.gauge(name("FleetRegistry", "clusters"),
                            lambda: len(self._members))
        self._degradations = self.registry.meter(
            name("FleetRegistry", "member-degradation-rate"))
        self._quarantines = self.registry.meter(
            name("FleetRegistry", "member-quarantine-rate"))
        self._readmissions = self.registry.meter(
            name("FleetRegistry", "member-readmission-rate"))
        self.registry.gauge(
            name("FleetRegistry", "quarantined-members"),
            lambda: sum(1 for h in list(self._members.values())
                        if h.health == MemberHealth.QUARANTINED))
        self.registry.gauge(
            name("FleetRegistry", "last-dispatch-ms"),
            lambda: (None if self.engine.last_dispatch_s is None
                     else round(self.engine.last_dispatch_s * 1e3, 3)))

    # ----------------------------------------------------------- members
    def register(self, cluster_id: str, monitor, *,
                 proposal_cache: ProposalCache | None = None,
                 detector=None, backend=None, endpoint: str = "",
                 breaker: CircuitBreaker | None = None
                 ) -> FleetClusterHandle:
        """Add a cluster. ``proposal_cache`` defaults to a fresh
        cluster-scoped cache over this monitor and the shared optimizer
        (pass the facade's cache for the local cluster so ``/proposals``
        serves fleet-computed results). The cache must carry this
        cluster's id — that scoping is what makes cross-serving
        impossible (``ProposalCache.store``). ``backend`` is the
        member's :class:`~.backends.RemoteBackend` when its admin rides
        a per-cluster endpoint; its breaker (or an explicit ``breaker``,
        or a fresh one seeded from the registry) becomes the member's
        health-machine breaker — one rolling window fed by both backend
        calls and registry fetch outcomes."""
        with self._lock:
            if cluster_id in self._members:
                raise ValueError(f"cluster {cluster_id!r} already "
                                 "registered")
            if len(self._members) >= self.max_clusters:
                raise ValueError(
                    f"fleet is full: {self.max_clusters} clusters "
                    "(fleet.max.clusters)")
            if proposal_cache is None:
                proposal_cache = ProposalCache(
                    monitor, self.engine.optimizer,
                    now_ms=self._now_ms, cache_id=cluster_id)
            elif proposal_cache.cache_id != cluster_id:
                raise ValueError(
                    f"proposal cache id {proposal_cache.cache_id!r} does "
                    f"not match cluster {cluster_id!r}")
            if breaker is None:
                breaker = getattr(backend, "breaker", None)
            if breaker is None:
                breaker = CircuitBreaker(
                    window_ms=self.breaker_window_ms,
                    failure_threshold=self.breaker_failures,
                    open_ms=self.breaker_open_ms,
                    seed=self.seed, name=cluster_id)
            if backend is not None and not endpoint:
                endpoint = getattr(backend, "endpoint", "")
            handle = FleetClusterHandle(cluster_id=cluster_id,
                                        monitor=monitor,
                                        cache=proposal_cache,
                                        detector=detector,
                                        backend=backend,
                                        breaker=breaker,
                                        endpoint=endpoint)
            self._members[cluster_id] = handle
            return handle

    @staticmethod
    def member_endpoints(config) -> dict[str, str]:
        """Resolve ``fleet.member.<id>.endpoint`` keys from a config's
        raw originals (the keys are dynamic — one per member — so they
        can't be predeclared in the definition table). Returns
        ``{member_id: endpoint}`` sorted by id; empty values are
        ignored."""
        out = {}
        prefix, suffix = "fleet.member.", ".endpoint"
        for key, val in config.originals().items():
            if key.startswith(prefix) and key.endswith(suffix):
                mid = key[len(prefix):-len(suffix)]
                if mid and val:
                    out[mid] = str(val)
        return dict(sorted(out.items()))

    def deregister(self, cluster_id: str) -> None:
        with self._lock:
            self._members.pop(cluster_id, None)

    @property
    def cluster_ids(self) -> list[str]:
        with self._lock:
            return list(self._members)

    def member(self, cluster_id: str) -> FleetClusterHandle:
        with self._lock:
            return self._members[cluster_id]

    def scrape_registries(self) -> list:
        """Cluster-namespaced views of every member's sensor registries
        for the merged ``/metrics`` exposition: families render as
        ``cc_<cluster>_LoadMonitor_...`` etc., so two members' identical
        sensor names never collapse into unlabeled numeric-suffix
        duplicates (tests/prom_lint.py rejects those)."""
        from ..core.sensors import NamespacedRegistry
        out = [self.registry]
        with self._lock:
            members = list(self._members.values())
        for h in members:
            reg = getattr(h.monitor, "registry", None)
            if reg is not None:
                out.append(NamespacedRegistry(reg, h.cluster_id))
            if h.cache is not None and h.cache.registry is not reg:
                # Cluster-scoped caches already carry the id in their
                # group name (ProposalCache.<id>.*) — no second prefix.
                out.append(h.cache.registry)
        return out

    # -------------------------------------------------------------- tick
    def tick(self, now_ms: int | None = None, *,
             force: bool = False) -> dict:
        """One fleet cycle: build every member's model; when ANY member's
        cache no longer answers its monitor generation (or ``force``),
        run the batched propose for EVERY ready member — the dispatch is
        batched anyway, and proposing only the stale subset would both
        compile one program set per distinct subset size and leave the
        others' risk readouts stale; then (on its cadence) the batched
        N-1 risk sweep and the per-cluster anomaly fan-out. Ticks are
        serialized (the background ticker vs a forced
        ``/fleet/rebalance``). Returns the tick summary."""
        with self._tick_lock:
            return self._tick_locked(now_ms, force)

    # ------------------------------------------------- health transitions
    def _journal_health(self, h: FleetClusterHandle, action: str,
                        severity: str, detail: dict) -> int | None:
        if self.journal is None:
            return None
        return self.journal.record(
            "fleet", action, severity=severity, cause=h.health_seq,
            detail={"clusterId": h.cluster_id, "health": h.health,
                    "degradedTicks": h.degraded_ticks,
                    "breaker": (h.breaker.state if h.breaker else None),
                    **detail})

    def _on_fetch_ok(self, h: FleetClusterHandle, now: int,
                     result) -> None:
        prev = h.health
        h.ready = True
        h.last_error = None
        h.generation = result.generation
        h.degraded_ticks = 0
        if h.breaker is not None:
            h.breaker.record_success(now)
        if prev != MemberHealth.HEALTHY:
            h.health = MemberHealth.HEALTHY
            h.health_since_ms = now
            self._readmissions.mark()
            h.health_seq = self._journal_health(
                h, "member-readmitted", "info", {"from": prev})
            LOG.info("fleet[%s]: %s -> HEALTHY", h.cluster_id, prev)

    def _on_fetch_not_ready(self, h: FleetClusterHandle,
                            err: str) -> None:
        """The monitor has no servable model yet (completeness): a cold
        data plane behind a perfectly healthy endpoint. The member is
        skipped this tick (``ready: false``, ``lastError`` on
        ``/fleet``) without touching the breaker or the health machine —
        a cold cluster must never walk to QUARANTINED, and a READMITTING
        member warming back up must not be re-quarantined for it."""
        h.ready = False
        h.last_error = err

    def _on_fetch_fail(self, h: FleetClusterHandle, now: int,
                       err: str) -> None:
        h.ready = False
        h.last_error = err
        if h.breaker is not None:
            h.breaker.record_failure(now)
        if h.health == MemberHealth.READMITTING:
            # Readmission hysteresis: a member that fails its first
            # post-probe fetch goes straight back to QUARANTINED — it
            # must not flap through the healthy pool.
            self._quarantine(h, now, action="member-requarantined")
            return
        h.degraded_ticks += 1
        if h.health != MemberHealth.DEGRADED:
            h.health = MemberHealth.DEGRADED
            h.health_since_ms = now
            self._degradations.mark()
            h.health_seq = self._journal_health(
                h, "member-degraded", "warn", {"error": err})
        # The member is skipped THIS tick; its last-good proposals keep
        # serving but flip stale so the execution gate refuses them.
        if h.cache is not None and h.cache.mark_stale():
            LOG.warning("fleet[%s]: degraded (%s); cached proposals "
                        "stale-flagged", h.cluster_id, err)
        if h.degraded_ticks >= self.quarantine_after:
            self._quarantine(h, now)

    def _quarantine(self, h: FleetClusterHandle, now: int, *,
                    action: str = "member-quarantined") -> None:
        h.health = MemberHealth.QUARANTINED
        h.health_since_ms = now
        self._quarantines.mark()
        h.health_seq = self._journal_health(
            h, action, "error", {"error": h.last_error})
        if h.cache is not None:
            h.cache.mark_stale()
        if self.notifier is not None:
            from ..detector.anomalies import FleetMemberQuarantined
            anomaly = FleetMemberQuarantined(
                detected_ms=now, cluster_id=h.cluster_id,
                degraded_ticks=h.degraded_ticks,
                breaker_state=(h.breaker.state if h.breaker else ""),
                last_error=h.last_error, journal_seq=h.health_seq)
            try:
                self.notifier.on_anomaly(anomaly, now)
            except Exception:
                LOG.warning("fleet[%s]: quarantine notification failed",
                            h.cluster_id, exc_info=True)
        LOG.error("fleet[%s]: QUARANTINED after %d degraded ticks (%s)",
                  h.cluster_id, h.degraded_ticks, h.last_error)

    # ------------------------------------------------------- fetch rounds
    def _fetch_member(self, h: FleetClusterHandle, now: int):
        """One member's model build. The breaker gates the attempt
        (OPEN = fail fast without touching the endpoint; a due half-open
        probe is admitted) — its outcome is recorded by the health
        transition handlers, ONE record per tick, on top of whatever the
        member's backend recorded per admin call."""
        if h.breaker is not None and not h.breaker.allow(now):
            from .backends import CircuitOpenError
            raise CircuitOpenError(
                f"breaker {h.breaker.state} until probe at "
                f"{h.breaker.probe_at}")
        return h.monitor.cluster_model(now)

    def _fetch_round(self, active: list, now: int) -> list:
        """Fetch every active member's model: on the bounded pool when
        one is configured (a hung endpoint forfeits its tick at the
        fetch deadline while siblings' futures proceed), serially in
        registration order otherwise (the chaos mode — deterministic
        under a shared simulated clock). Returns ``[(handle, result |
        None, error | None, fault)]`` in registration order either way;
        ``fault`` is False for :class:`NotEnoughValidWindowsError` — a
        cold data plane, not an endpoint fault, so it must never feed
        the breaker or walk the member toward QUARANTINED."""
        if self._pool is None or len(active) <= 1:
            out = []
            for h in active:
                try:
                    out.append((h, self._fetch_member(h, now), None,
                                False))
                except NotEnoughValidWindowsError as e:
                    out.append((h, None, f"{type(e).__name__}: {e}",
                                False))
                except Exception as e:   # noqa: BLE001 — per-member
                    out.append((h, None, f"{type(e).__name__}: {e}",
                                True))
            return out
        futures = [(h, self._pool.submit(self._fetch_member, h, now))
                   for h in active]
        timeout = (self.fetch_deadline_ms / 1000.0
                   if self.fetch_deadline_ms else None)
        out = []
        for h, fut in futures:
            try:
                out.append((h, fut.result(timeout=timeout), None, False))
            except TimeoutError:
                fut.cancel()
                out.append((h, None,
                            f"fetch deadline {self.fetch_deadline_ms} "
                            "ms missed", True))
            except NotEnoughValidWindowsError as e:
                out.append((h, None, f"{type(e).__name__}: {e}", False))
            except Exception as e:   # noqa: BLE001 — per-member
                out.append((h, None, f"{type(e).__name__}: {e}", True))
        return out

    def _submit_probes(self, quarantined: list, now: int) -> list:
        """Start (or, serial mode, defer) the quarantined members' due
        half-open probe fetches. Returns ``[(handle, future | None)]``
        for :meth:`_collect_probes` — with a pool the probes genuinely
        overlap the device dispatch running between the two calls."""
        due = [h for h in quarantined
               if h.breaker is None or h.breaker.allow(now)]
        if self._pool is None:
            return [(h, None) for h in due]
        return [(h, self._pool.submit(h.monitor.cluster_model, now))
                for h in due]

    def _collect_probes(self, probes: list, now: int) -> None:
        for h, fut in probes:
            try:
                if fut is None:
                    h.monitor.cluster_model(now)
                else:
                    timeout = (self.fetch_deadline_ms / 1000.0
                               if self.fetch_deadline_ms else None)
                    fut.result(timeout=timeout)
            except NotEnoughValidWindowsError as e:
                # The endpoint answered; only the data plane is still
                # cold. Transport-level success: readmit below and let
                # the fetch rounds skip it (not-ready) until it warms.
                h.last_error = f"{type(e).__name__}: {e}"
            except Exception as e:   # noqa: BLE001 — probe failure
                h.last_error = f"{type(e).__name__}: {e}"
                if h.breaker is not None:
                    h.breaker.record_failure(now)
                continue
            if h.breaker is not None:
                h.breaker.record_success(now)
            h.health = MemberHealth.READMITTING
            h.health_since_ms = now
            h.health_seq = self._journal_health(
                h, "member-readmitting", "info", {})
            LOG.info("fleet[%s]: probe succeeded; READMITTING (rejoins "
                     "next tick)", h.cluster_id)

    def _allocate_budget(self, todo: list, now: int) -> None:
        """Draw this tick's move grants from the fleet-wide budget,
        urgency-weighted (hard-goal violations, then forecast
        time-to-breach). Grants land in each member's summary row."""
        requests = []
        for h, _r in todo:
            s = h.last_summary
            requests.append(BudgetRequest(
                cluster_id=h.cluster_id,
                requested=int(s.get("numMoves") or 0),
                hard_violations=len(s.get("violatedHardGoals") or ()),
                time_to_breach_ms=(h.last_forecast or {}).get(
                    "timeToBreachMs")))
        grants = self.budget.allocate(requests, now)
        for h, _r in todo:
            g = grants.get(h.cluster_id)
            if g is not None:
                h.last_summary["budget"] = g.to_json()

    def _tick_locked(self, now_ms: int | None, force: bool) -> dict:
        now = now_ms if now_ms is not None else self._now_ms()
        t0 = _time.monotonic()
        with self._lock:
            members = list(self._members.values())
        # Pin the engine's cluster-axis shape floor to the FULL fleet
        # size — quarantined members included — so a partial-readiness
        # or quarantine tick reuses the full fleet's compiled programs
        # (padding slots are skip-branch cheap; readmission is likewise
        # recompile-free).
        self.engine.cluster_bucket_floor = len(members)
        active = [h for h in members
                  if h.health != MemberHealth.QUARANTINED]
        quarantined = [h for h in members
                       if h.health == MemberHealth.QUARANTINED]
        ready: list[tuple[FleetClusterHandle, object]] = []
        with self.tracer.span("fleet.tick", clusters=len(members)), \
                self.collector.cycle("fleet-tick"):
            for h, result, err, fault in self._fetch_round(active, now):
                if err is not None:
                    if fault:
                        self._on_fetch_fail(h, now, err)
                    else:
                        self._on_fetch_not_ready(h, err)
                    continue
                self._on_fetch_ok(h, now, result)
                ready.append((h, result))
            summary = {"clusters": len(members), "ready": len(ready),
                       "proposed": 0, "errors": 0, "skipped": 0,
                       "quarantined": len(quarantined)}
            # Half-open probes for quarantined members start here and
            # resolve after the dispatch — overlapped, so a probe into a
            # still-dead endpoint never extends the healthy siblings'
            # tick.
            probes = self._submit_probes(quarantined, now)
            if not ready:
                self._collect_probes(probes, now)
                self.ticks += 1
                self.last_tick_ms = now
                self._tick_timer.update(_time.monotonic() - t0)
                return summary
            need = force or any(h.cache is None or not h.cache.valid()
                                for h, _ in ready)
            todo = ready if need else []
            summary["skipped"] = len(ready) - len(todo)
            sweep_due = bool(self.risk_sweep_every
                             and self.ticks % self.risk_sweep_every == 0)
            if not todo and not sweep_due:
                # Nothing to compute: don't pay the fleet stack (pad +
                # device upload of every member's model) for a tick that
                # would use none of it.
                self._collect_probes(probes, now)
                self.ticks += 1
                self.last_tick_ms = now
                self._tick_timer.update(_time.monotonic() - t0)
                return summary
            fleet = FleetModel.stack(
                [(h.cluster_id, r.model, r.metadata, r.generation,
                  r.stale) for h, r in ready],
                broker_pad_multiple=self.broker_pad_multiple,
                partition_pad_multiple=self.partition_pad_multiple)
            self.last_bucket = fleet.bucket
            if todo:
                results = self.engine.propose(fleet, self.options)
                for (h, r), res in zip(todo, results):
                    if isinstance(res, OptimizationFailureError):
                        h.last_error = str(res)
                        summary["errors"] += 1
                        res = res.result
                    h.last_summary = self._cluster_summary(h, res)
                    if h.cache is not None:
                        stored = h.cache.store(res,
                                               generation=r.generation,
                                               cache_id=h.cluster_id)
                        if not stored:
                            LOG.info(
                                "fleet[%s]: generation moved mid-"
                                "dispatch (%s -> %s); result dropped",
                                h.cluster_id, r.generation,
                                h.monitor.generation)
                    summary["proposed"] += 1
                if self.budget is not None:
                    self._allocate_budget(todo, now)
            self._collect_probes(probes, now)
            if sweep_due:
                try:
                    risks = self.engine.sweep_n1(fleet)
                except Exception:
                    LOG.warning("fleet N-1 sweep failed", exc_info=True)
                    self._tick_errors.mark()
                else:
                    by_id = {r["clusterId"]: r for r in risks}
                    for h, _ in ready:
                        if h.cluster_id in by_id:
                            h.last_risk = by_id[h.cluster_id]
            # Anomaly fan-out: each member's detector sweep runs on the
            # shared tick (AnomalyDetectorManager.run_once semantics) —
            # one scheduler, per-cluster detection and self-healing.
            for h, _ in ready:
                if h.detector is None:
                    continue
                try:
                    h.detector.run_once(now)
                except Exception:
                    LOG.warning("fleet[%s]: anomaly fan-out failed",
                                h.cluster_id, exc_info=True)
                    self._tick_errors.mark()
        self.ticks += 1
        self.last_tick_ms = now
        self._tick_timer.update(_time.monotonic() - t0)
        return summary

    def forecast_sweep(self, trajectories, now_ms: int | None = None
                       ) -> list[dict]:
        """Sweep projected load trajectories across EVERY ready member
        in one batched ``[C, S]`` dispatch (``FleetOptimizer.
        sweep_trajectories`` — the scenario axis composed with the
        cluster axis). ``trajectories`` is one
        :class:`~..whatif.TrajectoryScale` grid (each member's factors
        resolve against its own topics) or ``{cluster_id: grid}``.
        Per-member summaries land on the handles for ``/fleet``.
        Serialized with the background tick on the tick mutex — both
        paths dispatch on the shared engine and pin its cluster-axis
        shape floor."""
        now = now_ms if now_ms is not None else self._now_ms()
        with self._tick_lock:
            return self._forecast_sweep_locked(trajectories, now)

    def _forecast_sweep_locked(self, trajectories, now: int) -> list[dict]:
        with self._lock:
            members = list(self._members.values())
        self.engine.cluster_bucket_floor = len(members)
        ready = []
        for h in members:
            try:
                result = h.monitor.cluster_model(now)
            except Exception as e:
                h.ready = False
                h.last_error = f"{type(e).__name__}: {e}"
                continue
            h.ready = True
            h.last_error = None
            ready.append((h, result))
        if not ready:
            return []
        fleet = FleetModel.stack(
            [(h.cluster_id, r.model, r.metadata, r.generation, r.stale)
             for h, r in ready],
            broker_pad_multiple=self.broker_pad_multiple,
            partition_pad_multiple=self.partition_pad_multiple)
        self.last_bucket = fleet.bucket
        summaries = self.engine.sweep_trajectories(fleet, trajectories)
        by_id = {s["clusterId"]: s for s in summaries}
        for h, _ in ready:
            if h.cluster_id in by_id:
                h.last_forecast = by_id[h.cluster_id]
        return summaries

    @staticmethod
    def _cluster_summary(h: FleetClusterHandle, res) -> dict:
        total = max(len(res.goal_results), 1)
        violated = [g.name for g in res.goal_results if not g.satisfied]
        return {
            # Documented in docs/fleet.md: the fraction of the chain's
            # goals currently satisfied — 1.0 is a fully balanced member.
            "balanceScore": round(1.0 - len(violated) / total, 4),
            "violatedGoals": violated,
            "violatedHardGoals": res.violated_hard_goals,
            "numProposals": len(res.proposals),
            "numMoves": res.num_moves,
            "staleModel": res.stale_model,
        }

    # -------------------------------------------------- background loop
    def start(self, tick_interval_s: float) -> None:
        """Background shared tick (fleet.tick.ms); idempotent."""
        if self._ticker is not None and self._ticker.is_alive():
            return
        stop = threading.Event()
        self._stop = stop

        def loop():
            while not stop.wait(tick_interval_s):
                try:
                    self.tick()
                except Exception:
                    LOG.warning("fleet tick failed", exc_info=True)
                    self._tick_errors.mark()

        self._ticker = threading.Thread(target=loop, daemon=True,
                                        name="fleet-tick")
        self._ticker.start()

    def stop(self) -> None:
        self._stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=5)
            self._ticker = None
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)

    # ----------------------------------------------------------- surface
    def summary_json(self, now_ms: int | None = None) -> dict:
        """The ``GET /fleet`` payload: per-cluster balance score,
        freshness and risk, plus the shared bucket/dispatch readout."""
        now = now_ms if now_ms is not None else self._now_ms()
        with self._lock:
            members = list(self._members.values())
        clusters = []
        for h in members:
            row = {"clusterId": h.cluster_id,
                   "ready": h.ready,
                   "health": h.health,
                   "degradedTicks": h.degraded_ticks,
                   "healthSinceMs": h.health_since_ms,
                   "generation": h.generation,
                   "lastError": h.last_error,
                   **h.last_summary}
            if h.endpoint:
                row["endpoint"] = h.endpoint
            if h.breaker is not None:
                row["breaker"] = h.breaker.to_json()
            if h.backend is not None and hasattr(h.backend, "to_json"):
                row["backend"] = h.backend.to_json()
            if h.cache is not None:
                row["freshness"] = h.cache.freshness_json(now)
            if h.last_risk is not None:
                row["risk"] = h.last_risk
            if h.last_forecast is not None:
                row["forecast"] = {
                    "maxRisk": h.last_forecast.get("maxRisk"),
                    "riskiest": h.last_forecast.get("riskiest")}
            clusters.append(row)
        out = {"enabled": True,
               "numClusters": len(members),
               "quarantined": sum(
                   1 for h in members
                   if h.health == MemberHealth.QUARANTINED),
               "ticks": self.ticks,
               "lastTickMs": self.last_tick_ms,
               "bucket": self.last_bucket,
               "lastDispatchMs": (
                   None if self.engine.last_dispatch_s is None
                   else round(self.engine.last_dispatch_s * 1e3, 3)),
               "clusters": clusters}
        if self.budget is not None:
            out["budget"] = self.budget.to_json()
        return out

    def stats_json(self) -> dict:
        """The ``fleet`` section of ``/devicestats``: cluster count,
        current shape bucket, last dispatch wall clock, plus a
        per-member health/breaker map for fleet dashboards."""
        with self._lock:
            members = list(self._members.values())
        member_map = {}
        for h in members:
            m = {"health": h.health,
                 "degradedTicks": h.degraded_ticks,
                 "ready": h.ready}
            if h.endpoint:
                m["endpoint"] = h.endpoint
            if h.breaker is not None:
                m["breaker"] = h.breaker.state
            if h.backend is not None and hasattr(h.backend, "to_json"):
                m["backend"] = h.backend.to_json()
            member_map[h.cluster_id] = m
        out = {"clusterCount": len(members),
               "ticks": self.ticks,
               "bucket": self.last_bucket,
               "lastDispatchMs": (
                   None if self.engine.last_dispatch_s is None
                   else round(self.engine.last_dispatch_s * 1e3, 3)),
               "lastTickMs": self.last_tick_ms,
               "members": member_map}
        if self.budget is not None:
            out["budget"] = self.budget.to_json()
        return out

    def rebalance(self, now_ms: int | None = None) -> dict:
        """``POST /fleet/rebalance``: force one tick now (every member
        recomputes regardless of cache validity) and return the summary.
        Proposals land in the members' caches; EXECUTION stays a
        per-cluster decision through each cluster's own endpoints — a
        fleet-wide execute-everything switch is exactly the blast radius
        this layer exists to avoid."""
        tick = self.tick(now_ms, force=True)
        return {"tick": tick, **self.summary_json(now_ms)}
