"""Intra-broker (disk) optimization: the JBOD dimension.

Rebuild of the reference's disk-level machinery — ``model/Disk.java``,
``IntraBrokerDiskCapacityGoal.java`` (hard: per-disk utilization under the
capacity threshold) and ``IntraBrokerDiskUsageDistributionGoal.java``
(balance utilization across the disks of each broker) — as a TPU-first
batched kernel.

The structure is friendlier than inter-broker search: logdir moves never
leave their broker, so every broker's rebalance is independent and the
whole cluster optimizes as one vectorized loop — per iteration, every
broker moves its best replica from its most- to least-loaded disk
(segment-argmax over the flattened replica axis), all brokers at once.
``REMOVE_DISKS`` is the same kernel with the doomed disks' capacity zeroed
so everything on them drains to the surviving disks.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..core.resources import Resource
from ..executor.tasks import IntraBrokerReplicaMove


@struct.dataclass
class DiskState:
    """Disk-level arrays paired with a FlatClusterModel (same P/R/B padding;
    D = padded max logdirs per broker)."""

    replica_disk: jax.Array    # i32[P, R] — disk slot on the hosting broker (-1 none)
    replica_size: jax.Array    # f32[P, R] — DISK load of the replica
    replica_broker: jax.Array  # i32[P, R]
    disk_capacity: jax.Array   # f32[B, D] (0 = absent or draining)
    disk_valid: jax.Array      # bool[B, D]

    @property
    def disk_util(self) -> jax.Array:
        """f32[B, D] — one scatter-add over all replicas."""
        B, D = self.disk_capacity.shape
        idx = self.replica_broker * D + self.replica_disk
        ok = (self.replica_disk >= 0)
        idx = jnp.where(ok, idx, B * D)
        util = jnp.zeros((B * D + 1,), jnp.float32).at[idx.reshape(-1)].add(
            jnp.where(ok, self.replica_size, 0.0).reshape(-1))
        return util[:B * D].reshape(B, D)


@dataclass
class IntraBrokerResult:
    moves: list[IntraBrokerReplicaMove]
    capacity_violation_before: float
    capacity_violation_after: float
    balance_violation_before: float
    balance_violation_after: float
    iterations: int

    def goal_summary(self) -> list[dict]:
        """Per-goal entries in the same shape as the inter-broker
        ``goalSummary`` (ref OptimizerResult), naming the two goal facets
        of the fused kernel (single source: the facet classes below)."""
        rows = [(IntraBrokerDiskCapacityGoal,
                 self.capacity_violation_before,
                 self.capacity_violation_after),
                (IntraBrokerDiskUsageDistributionGoal,
                 self.balance_violation_before,
                 self.balance_violation_after)]
        return [
            {"goal": goal.name, "hard": goal.hard,
             "violationBefore": before, "violationAfter": after,
             "status": "NO-ACTION" if before <= 1e-6
             else ("FIXED" if after <= 1e-6 else "VIOLATED")}
            for goal, before, after in rows]


def build_disk_state(model, metadata, admin, capacity_resolver
                     ) -> tuple[DiskState, list[list[str]]]:
    """Assemble disk arrays from live logdir metadata + per-logdir capacity
    (ref LoadMonitor populating Disk objects from describeLogDirs +
    BrokerCapacityInfo.diskCapacityByLogDir)."""
    logdirs_by_broker: list[list[str]] = []
    caps: list[dict[str, float]] = []
    placement = admin.describe_replica_log_dirs()   # one full-cluster scan
    dirs_by_broker: dict[int, set[str]] = {}
    for (t, p, b), d in placement.items():
        dirs_by_broker.setdefault(b, set()).add(d)
    # Configured-but-empty logdirs are valid drain destinations the
    # placement scan can't reveal (ref AdminClient.describeLogDirs).
    conf_fn = getattr(admin, "describe_logdirs", None)
    if conf_fn is not None:
        for b, dirs in conf_fn().items():
            dirs_by_broker.setdefault(b, set()).update(dirs)
    for broker_id in metadata.broker_ids:
        info = capacity_resolver.capacity_for_broker("", "", broker_id)
        by_dir = info.disk_capacity_by_logdir
        if by_dir is None:
            # Single logical disk unless the admin reports real logdirs.
            names = sorted(dirs_by_broker.get(broker_id, set())) or ["logdir0"]
            total = info.capacity[Resource.DISK]
            by_dir = {d: total / len(names) for d in names}
        logdirs_by_broker.append(sorted(by_dir))
        caps.append(by_dir)
    D = max((len(d) for d in logdirs_by_broker), default=1)
    B = model.num_brokers_padded
    P, R = model.replica_broker.shape
    disk_capacity = np.zeros((B, D), np.float32)
    disk_valid = np.zeros((B, D), bool)
    dir_index: list[dict[str, int]] = []
    for i, dirs in enumerate(logdirs_by_broker):
        dir_index.append({d: j for j, d in enumerate(dirs)})
        for j, d in enumerate(dirs):
            disk_capacity[i, j] = caps[i][d]
            disk_valid[i, j] = True

    replica_disk = np.full((P, R), -1, np.int32)
    rb = np.asarray(model.replica_broker)
    for p, key in enumerate(metadata.partition_keys):
        for r in range(R):
            b = rb[p, r]
            if b >= len(metadata.broker_ids):
                continue
            broker_id = metadata.broker_ids[b]
            d = placement.get((key[0], key[1], broker_id))
            if d is not None and d in dir_index[b]:
                replica_disk[p, r] = dir_index[b][d]
            elif dir_index[b]:
                replica_disk[p, r] = 0
    from ..model.flat import replica_loads
    sizes = np.asarray(replica_loads(model))[..., Resource.DISK]
    state = DiskState(replica_disk=jnp.asarray(replica_disk),
                      replica_size=jnp.asarray(sizes),
                      replica_broker=jnp.asarray(rb),
                      disk_capacity=jnp.asarray(disk_capacity),
                      disk_valid=jnp.asarray(disk_valid))
    return state, logdirs_by_broker


def _violations(state: DiskState, cap_threshold: float,
                balance_threshold: float):
    """(capacity_violation, balance_violation) — both scalars."""
    util = state.disk_util
    cap = state.disk_capacity * cap_threshold
    # Draining disks (capacity 0) count everything on them as over-capacity.
    over_cap = jnp.where(state.disk_valid, jnp.maximum(util - cap, 0.0), 0.0)
    # Balance: per broker, disks within avg*threshold band (ref
    # IntraBrokerDiskUsageDistributionGoal's balance percentage).
    live = state.disk_valid & (state.disk_capacity > 0)
    n_live = jnp.maximum(live.sum(axis=1), 1)
    avg = jnp.where(live, util, 0.0).sum(axis=1) / n_live            # [B]
    upper = avg[:, None] * balance_threshold
    lower = avg[:, None] * (2.0 - balance_threshold)
    bal = jnp.where(live, jnp.maximum(util - upper, 0.0)
                    + jnp.maximum(lower - util, 0.0), 0.0)
    return over_cap.sum(), bal.sum()


class IntraBrokerDiskCapacityGoal:
    """Named facet of the fused intra-broker kernel (ref
    ``IntraBrokerDiskCapacityGoal.java``): no disk above
    ``capacity * cap_threshold``; draining disks (capacity 0) must empty
    completely. Hard goal — its residual gates rebalance_disks results."""

    name = "IntraBrokerDiskCapacityGoal"
    hard = True


class IntraBrokerDiskUsageDistributionGoal:
    """Named facet of the fused intra-broker kernel (ref
    ``IntraBrokerDiskUsageDistributionGoal.java``): each broker's disks
    within ``avg * balance_threshold`` of the broker's mean disk
    utilization. Soft goal."""

    name = "IntraBrokerDiskUsageDistributionGoal"
    hard = False


def optimize_intra_broker(state: DiskState, *, cap_threshold: float = 0.8,
                          balance_threshold: float = 1.10,
                          max_iters: int = 512) -> tuple[DiskState, jax.Array]:
    """One jitted pass: every broker simultaneously moves its heaviest
    movable replica from its most-pressured disk to its best destination
    disk, until no broker can improve. Returns (final state, iters)."""

    B, D = state.disk_capacity.shape
    P, R = state.replica_disk.shape

    def pressure(util, capacity, valid):
        # Draining (capacity 0) disks are infinitely pressured; otherwise
        # pressure = utilization above the per-disk balance midpoint.
        live = valid & (capacity > 0)
        n_live = jnp.maximum(live.sum(axis=1, keepdims=True), 1)
        avg = jnp.where(live, util, 0.0).sum(axis=1, keepdims=True) / n_live
        pres = jnp.where(valid & (capacity <= 0) & (util > 0), jnp.inf,
                         jnp.where(live, util - avg, -jnp.inf))
        return pres, avg

    def body(carry):
        rd, it, _ = carry
        st = state.replace(replica_disk=rd)
        util = st.disk_util
        pres, avg = pressure(util, state.disk_capacity, state.disk_valid)
        src = jnp.argmax(pres, axis=1)                               # [B]
        live = state.disk_valid & (state.disk_capacity > 0)
        dst_score = jnp.where(live, util, jnp.inf)
        dst = jnp.argmin(dst_score, axis=1)                          # [B]
        gap = (util[jnp.arange(B), src] - util[jnp.arange(B), dst])
        drain = state.disk_capacity[jnp.arange(B), src] <= 0

        # Per-broker best replica on the source disk: heaviest that still
        # fits in half the gap (so the move improves), any size when
        # draining. Segment-argmax via scatter-max of (size, index) pairs.
        on_src = (rd == src[st.replica_broker]) & (rd >= 0)
        fits = (st.replica_size <= gap[st.replica_broker] * 0.5) | \
            drain[st.replica_broker]
        # Zero-size replicas still occupy a logdir: they matter (only) when
        # the disk is draining — the operator is about to remove it.
        movable = on_src & fits & ((st.replica_size > 0)
                                   | drain[st.replica_broker])
        score = jnp.where(movable, st.replica_size, -jnp.inf)
        flat = score.reshape(-1)
        seg_best = jnp.full((B + 1,), -jnp.inf).at[
            st.replica_broker.reshape(-1)].max(flat)
        # winner: the first flat index achieving its broker's best score
        is_best = (flat == seg_best[st.replica_broker.reshape(-1)]) \
            & jnp.isfinite(flat)
        order = jnp.where(is_best, jnp.arange(P * R), P * R)
        first = jnp.full((B + 1,), P * R).at[
            st.replica_broker.reshape(-1)].min(order)
        winners = jnp.clip(first[:B], 0, P * R - 1)
        valid_move = (first[:B] < P * R) & (dst != src)
        new_rd = rd.reshape(-1).at[
            jnp.where(valid_move, winners, P * R)].set(
            dst, mode="drop").reshape(P, R)
        moved = (new_rd != rd).any()
        return new_rd, it + 1, moved

    def cond(carry):
        _, it, moved = carry
        return moved & (it < max_iters)

    rd, iters, _ = jax.lax.while_loop(
        cond, body, (state.replica_disk, jnp.zeros((), jnp.int32),
                     jnp.ones((), bool)))
    return state.replace(replica_disk=rd), iters


def diff_intra_moves(before: DiskState, after: DiskState, metadata,
                     logdirs_by_broker: list[list[str]]
                     ) -> list[IntraBrokerReplicaMove]:
    """Materialize logdir moves from the disk-slot diff (the intra-broker
    AnalyzerUtils.getDiff)."""
    b0 = np.asarray(before.replica_disk)
    b1 = np.asarray(after.replica_disk)
    rb = np.asarray(before.replica_broker)
    sizes = np.asarray(before.replica_size)
    moves: list[IntraBrokerReplicaMove] = []
    for p, r in zip(*np.nonzero(b0 != b1)):
        if p >= len(metadata.partition_keys) or rb[p, r] >= len(
                metadata.broker_ids):
            continue
        topic, partition = metadata.partition_keys[p]
        broker = int(rb[p, r])
        dirs = logdirs_by_broker[broker]
        moves.append(IntraBrokerReplicaMove(
            topic=topic, partition=partition,
            broker_id=metadata.broker_ids[broker],
            source_logdir=dirs[int(b0[p, r])],
            dest_logdir=dirs[int(b1[p, r])],
            size_mb=float(sizes[p, r])))
    return moves


def intra_broker_rebalance(model, metadata, admin, capacity_resolver, *,
                           cap_threshold: float = 0.8,
                           balance_threshold: float = 1.10,
                           drained_disks: dict[int, list[str]] | None = None
                           ) -> IntraBrokerResult:
    """End-to-end: build disk state -> (optionally zero the capacity of
    disks being removed) -> run the kernel -> emit logdir moves (the
    REMOVE_DISKS / intra-broker rebalance entry, ref RemoveDisksRunnable +
    the intra-broker goals)."""
    state, logdirs_by_broker = build_disk_state(model, metadata, admin,
                                                capacity_resolver)
    if drained_disks:
        cap = np.asarray(state.disk_capacity).copy()
        util = np.asarray(state.disk_util)
        bindex = {bid: i for i, bid in enumerate(metadata.broker_ids)}
        for broker_id, dirs in drained_disks.items():
            i = bindex.get(broker_id)
            if i is None:
                raise ValueError(f"unknown broker id {broker_id}")
            for d in dirs:
                if d not in logdirs_by_broker[i]:
                    # A typo'd logdir must fail the request, not silently
                    # leave the disk it named untouched while unrelated
                    # balance moves execute and report success.
                    raise ValueError(
                        f"broker {broker_id} has no logdir {d!r} "
                        f"(knows {sorted(logdirs_by_broker[i])})")
                cap[i, logdirs_by_broker[i].index(d)] = 0.0
            if not (cap[i] > 0).any():
                raise ValueError(
                    f"broker {broker_id}: cannot remove every logdir "
                    f"({sorted(dirs)}) — no surviving disk to drain to")
            # ref RemoveDisksRunnable.java:156-158: the broker's FULL disk
            # usage must fit under the surviving disks' capacity x
            # threshold, or the drain is refused up front (half-moving
            # replicas off a disk being removed is worse than failing).
            future_usage = float(util[i].sum())
            remaining = float(cap[i].sum())
            if future_usage > remaining * cap_threshold:
                raise ValueError(
                    f"Not enough remaining capacity to move replicas to "
                    f"for broker {broker_id}: {future_usage:.1f} MB used "
                    f"vs {remaining:.1f} MB x {cap_threshold} surviving")
        state = state.replace(disk_capacity=jnp.asarray(cap))
    cv0, bv0 = _violations(state, cap_threshold, balance_threshold)
    final, iters = optimize_intra_broker(
        state, cap_threshold=cap_threshold,
        balance_threshold=balance_threshold)
    cv1, bv1 = _violations(final, cap_threshold, balance_threshold)
    return IntraBrokerResult(
        moves=diff_intra_moves(state, final, metadata, logdirs_by_broker),
        capacity_violation_before=float(cv0),
        capacity_violation_after=float(cv1),
        balance_violation_before=float(bv0),
        balance_violation_after=float(bv1),
        iterations=int(jax.device_get(iters)))
