"""The batched greedy search engine.

Replaces ``AbstractGoal.optimize``'s triple-nested scalar loop
(``AbstractGoal.java:82-135`` / ``maybeApplyBalancingAction`` ``:230-272``)
with, per goal, a ``lax.while_loop`` whose body:

1. asks the goal for a batch of candidate actions (flow-matched source
   replica -> destination pairs, or top-K x top-D grids — all device-side
   ``top_k``/``argsort``/``cumsum``, no host round trips);
2. scores every candidate at once: base legality, acceptance by all
   previously-optimized goals (the lexicographic chain, ref
   ``AnalyzerUtils.isProposalAcceptableForOptimizedGoals``), and the goal's
   own residual delta;
3. partitions the best M candidates into *conflict-free groups* — within a
   group no two candidates share a source broker, destination broker, or
   partition row — via prefix-rank grouping (a candidate's group index is
   the max count of earlier same-key candidates; same-key candidates form
   cliques, so ranks are distinct within a key), then applies each group as
   one vectorized scatter after re-validating against the updated state.

The loop exits when an iteration applies nothing (no improving legal action
— same fixed point as the reference's ``_finished`` flag). Mandatory moves
(offline replicas, self-healing) are applied even when they don't improve
the current goal, provided they are legal and accepted by earlier goals.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from .constraint import SearchConfig
from .goals import GoalKernel
from .state import (SearchContext, SearchState, apply_group, base_legality)

# Ordering sentinel only (never added to a metric value): mandatory moves
# sort ahead of every improving move.
_MUST_FIRST = -1e30


def violation_stack(goals: Sequence[GoalKernel], state, ctx) -> jax.Array:
    """f32[num_goals] residual per goal — the single definition shared by
    the fused per-pass readings and ``CompiledGoalChain.violations``."""
    return jnp.stack([g.violation(state, ctx) for g in goals])


# ---------------------------------------------------------------------------
# Joint multi-objective scoring over violation stacks (the population
# search's selection math — parallel/population.py evaluates these inside
# the jitted program, the optimizer's final winner pick re-runs them on the
# fetched host copies; jnp works on both).
# ---------------------------------------------------------------------------

def normalized_stacks(stacks, scales):
    """Scale-normalize violation stacks for cross-goal comparison:
    ``stacks[..., g] / max(scale_g, 1)`` with satisfied goals clamped to
    exactly 0 (the ulp-aware ``GoalResult.satisfied`` cutoff,
    ``1e-6 + 1e-6 * scale``) so converged goals tie bit-exactly instead
    of ranking on float dust. Goals measure violations in wildly
    different units (load units vs replica counts); per-goal
    ``violation_scale`` is the magnitude the float32 reductions run
    over, making the normalized residuals dimensionless and summable."""
    stacks = jnp.asarray(stacks, jnp.float32)
    scales = jnp.asarray(scales, jnp.float32)
    tol = 1e-6 + 1e-6 * scales
    norm = stacks / jnp.maximum(scales, 1.0)
    return jnp.where(stacks <= tol, 0.0, norm)


def weighted_objective(stacks, scales, hard_mask, *, hard_weight: float,
                       move_weight: float = 0.0, moves=None):
    """f32[K] scalarized joint objective per plan: scale-normalized
    violations summed with hard goals up-weighted by ``hard_weight``
    (large enough that any hard residual dominates every soft trade-off),
    plus an optional per-move penalty. Lower is better."""
    norm = normalized_stacks(stacks, scales)
    w = jnp.where(jnp.asarray(hard_mask, bool), hard_weight, 1.0)
    obj = (norm * w).sum(axis=-1)
    if move_weight and moves is not None:
        obj = obj + move_weight * jnp.asarray(moves, jnp.float32)
    return obj


def pareto_ranks(stacks, scales):
    """i32[K] dominance-count Pareto rank per plan over the normalized
    violation stacks: ``rank[j]`` = number of plans that dominate plan j
    (all goals <=, at least one strictly <). Rank 0 is the Pareto front;
    its size is the population-diversity telemetry the optimizer
    surfaces."""
    n = normalized_stacks(stacks, scales)
    le = (n[:, None, :] <= n[None, :, :]).all(axis=-1)
    lt = (n[:, None, :] < n[None, :, :]).any(axis=-1)
    dominates = le & lt                     # [K, K]: i dominates j
    return dominates.sum(axis=0, dtype=jnp.int32)


def _chain_accepts(prev_goals: Sequence[GoalKernel], state, ctx, cands):
    ok = jnp.ones(cands.p.shape, bool)
    for g in prev_goals:
        ok = ok & g.accepts(state, ctx, cands)
    return ok


def make_goal_pass(goal: GoalKernel, prev_goals: Sequence[GoalKernel],
                   cfg: SearchConfig,
                   all_goals: Sequence[GoalKernel] | None = None):
    """Build the jittable single-goal optimization pass.

    Returns ``run(state, ctx, key) -> (state, iters, violations, moves)``
    where ``violations`` is the post-pass residual stack over
    ``all_goals`` and ``moves`` the cumulative ``state.moves_applied``
    boundary — both computed inside the same jit so the host never pays a
    separate dispatch for the goal-boundary readings the reference
    records at ``GoalOptimizer.java:458-497`` (the moves boundary is what
    lets per-goal candidate-acceptance telemetry ride the existing
    end-of-chain fetch with zero extra syncs). ``prev_goals`` are baked
    in at trace time (the goal chain is static configuration)."""

    eps = cfg.epsilon
    G = cfg.apply_groups

    def eligibility(state, ctx, cands):
        ok = base_legality(state, ctx, cands)
        ok = ok & _chain_accepts(prev_goals, state, ctx, cands)
        delta = goal.delta(state, ctx, cands)
        return ok & ((delta < -eps) | cands.must)

    def apply_batch(state: SearchState, ctx: SearchContext, cands, score):
        M = min(cfg.apply_per_iter, score.shape[0])
        _, order = jax.lax.top_k(-score, M)
        c = jax.tree.map(lambda x: x[order], cands)
        sel = jnp.isfinite(score[order])

        def same(a, b):
            return a[:, None] == b[None, :]

        # Structural conflicts: shared *partition rows* only (primary or swap
        # counterpart — non-swaps carry p2 == p, so those terms degenerate).
        # ``apply_group``'s slot writes are per-partition-row; its broker
        # aggregates are scatter-adds, which stay exact under any amount of
        # source/destination sharing. Collective bound overshoot from broker
        # sharing is handled exactly by the goals' prefix-sum guards below —
        # this is what lets hundreds of moves into/out of the same hot broker
        # apply in one round instead of one per round.
        conflict = (same(c.p, c.p) | same(c.p, c.p2)
                    | same(c.p2, c.p) | same(c.p2, c.p2))
        earlier = jnp.tril(jnp.ones((M, M), bool), k=-1)
        conflict_earlier = conflict & earlier

        guard_goals = [goal, *prev_goals]

        def rbody(carry):
            state, n, pending, rounds, _ = carry
            elig = pending & eligibility(state, ctx, c)
            emask = conflict_earlier & elig[None, :]
            blocked = emask.any(axis=1)
            # Prefix mask for guards: earlier, eligible, not partition-blocked
            # candidates are the ones that will actually co-apply; guards are
            # evaluated against exactly that set. (A guarded-out earlier
            # candidate still counts as pending next round — conservative.)
            ok = jnp.ones((M,), bool)
            gmask = earlier & elig[None, :]
            for g in guard_goals:
                gok = g.collective_guard(state, ctx, c, gmask)
                if gok is None:
                    gok = ~((same(c.src, c.src) | same(c.dst, c.dst))
                            & gmask).any(axis=1)
                if g is goal:
                    # The goal may not veto its own mandatory moves:
                    # draining a dead broker leaves its source below any
                    # lower bound by construction (matches eligibility's
                    # must-bypass of the improvement test). Earlier goals'
                    # guards still bind, like actionAcceptance does.
                    gok = gok | c.must
                ok = ok & gok
            do = elig & ~blocked & ok
            state = apply_group(state, ctx, c, do)
            return (state, n + do.sum(dtype=jnp.int32), pending & ~do,
                    rounds + 1, do.any())

        def rcond(carry):
            _, _, pending, rounds, progressed = carry
            return pending.any() & (rounds < G) & progressed

        state, n, _, _, _ = jax.lax.while_loop(
            rcond, rbody, (state, jnp.zeros((), jnp.int32), sel,
                           jnp.zeros((), jnp.int32), jnp.ones((), bool)))
        return state, n

    def steer_ctx(state: SearchState, ctx: SearchContext) -> SearchContext:
        """Steer candidate generation toward destinations the earlier goals
        in the chain can accept (e.g. don't flow disk moves onto a broker
        whose replica count already sits at its balance ceiling). Pure
        heuristic: acceptance is still enforced per candidate, and if the
        intersection is empty the original destination set is kept so
        mandatory moves stay routable."""
        if not prev_goals:
            return ctx
        recv = jnp.ones(ctx.broker_alive.shape, bool)
        for g in prev_goals:
            recv = recv & g.receptive_dest(state, ctx)
        dest = recv & ctx.dest_allowed
        # Only replica-move destinations are steered: leadership candidates'
        # destinations are pinned to wherever replicas already sit, and
        # legality/acceptance are enforced per candidate against the raw ctx.
        return ctx.replace(
            dest_allowed=jnp.where(dest.any(), dest, ctx.dest_allowed))

    def run(state: SearchState, ctx: SearchContext, key: jax.Array):
        # Converged-goal early exit: a goal whose violation is already ~0
        # with no offline replicas pending has no eligible action — the
        # loop below would only burn stall_patience zero-apply iterations
        # proving it (eligibility requires delta < -eps OR a must-move).
        # lax.cond executes one branch, so a satisfied goal costs one
        # violation read instead of ~5 candidate iterations; in a 15-goal
        # chain most passes are satisfied most of the time.
        active = ((goal.violation(state, ctx) > eps)
                  | state.offline.any())

        def _skip(st):
            return st, jnp.zeros((), jnp.int32)

        def _optimize(state):
            return _run_active(state, ctx, key)

        state, iters = jax.lax.cond(active, _optimize, _skip, state)
        stack = violation_stack(all_goals or [goal], state, ctx)
        return state, iters, stack, state.moves_applied

    def _run_active(state: SearchState, ctx: SearchContext, key: jax.Array):
        patience = cfg.stall_patience

        if goal.supports_bulk_drain and cfg.drain_rounds > 0:
            # Vectorized shedding prologue: each round applies up to
            # drain_batch conflict-free moves in one scatter (sources are
            # partition-disjoint, receiver intake bounded analytically by
            # the budgets), so a 500K-move skew drains in a handful of
            # rounds instead of max_iters_per_goal candidate iterations.
            # Per-candidate legality + earlier-goal acceptance still gate
            # each move; the fine loop below finishes the tail.
            min_applied = max(cfg.drain_batch // 64, 8)

            def dcond(carry):
                _, r, applied = carry
                return (r < cfg.drain_rounds) & (applied >= min_applied)

            def dbody(carry):
                state, r, _ = carry
                # Steered context: receiver budgets only on brokers every
                # earlier goal is willing to see gain a replica — otherwise
                # the fill routes moves straight into acceptance vetoes
                # (e.g. count-full brokers once ReplicaDistribution ran).
                c = goal.bulk_drain(state, steer_ctx(state, ctx),
                                    jax.random.fold_in(key, 70_000 + r),
                                    cfg)
                elig = eligibility(state, ctx, c)
                state = apply_group(state, ctx, c, elig)
                return state, r + 1, elig.sum(dtype=jnp.int32)

            state, _, _ = jax.lax.while_loop(
                dcond, dbody,
                (state, jnp.zeros((), jnp.int32),
                 jnp.full((), jnp.iinfo(jnp.int32).max, jnp.int32)))

        def cond(carry):
            _, it, stalls = carry
            return (stalls < patience) & (it < cfg.max_iters_per_goal)

        def body(carry):
            state, it, stalls = carry
            k = jax.random.fold_in(key, it)
            cands = goal.propose(state, steer_ctx(state, ctx), k, cfg)
            elig = eligibility(state, ctx, cands)
            delta = goal.delta(state, ctx, cands)
            # Mandatory (offline) moves outrank everything; otherwise best
            # (most-negative) deltas apply first.
            score = jnp.where(
                elig,
                jnp.where(cands.must, _MUST_FIRST,
                          jnp.clip(delta, -1e29, 1e29)),
                jnp.inf)
            state, applied = apply_batch(state, ctx, cands, score)
            stalls = jnp.where(applied == 0, stalls + 1, 0)
            return (state, it + 1, stalls)

        state, iters, _ = jax.lax.while_loop(
            cond, body, (state, jnp.zeros((), jnp.int32),
                         jnp.zeros((), jnp.int32)))
        return state, iters

    return run


def make_chain_step(goals: Sequence[GoalKernel], cfg: SearchConfig):
    """Compose the per-goal passes into one jittable
    ``step(state, ctx, key) -> (state, violations)`` — the whole-chain
    building block shared by the multi-branch search, the multichip
    dryrun, and tests (each pass still enforces acceptance by all earlier
    goals, so the composition preserves the lexicographic chain)."""
    passes = [make_goal_pass(g, list(goals[:i]), cfg,
                             all_goals=list(goals))
              for i, g in enumerate(goals)]

    def step(state, ctx, key):
        stack = None
        for i, p in enumerate(passes):
            state, _, stack, _ = p(state, ctx, jax.random.fold_in(key, i))
        return state, stack

    return step


class CompiledGoalChain:
    """Per-goal jitted passes for one (goal chain, config) pair.

    Kept per-goal (not one fused jit) deliberately: it preserves the
    reference's *anytime* behavior — after every goal the host holds a valid,
    strictly-not-worse state (ref ``GoalOptimizer.java:458-497`` loop) — and
    gives per-goal wall-clock numbers for ``OptimizerResult``.
    """

    def __init__(self, goals: Sequence[GoalKernel], cfg: SearchConfig,
                 collector=None):
        import threading

        from ..core.runtime_obs import default_collector
        self.goals = list(goals)
        self.cfg = cfg
        #: device-runtime ledger (None = the process default): every
        #: program below is a TrackedProgram, so dispatches, compiles and
        #: AOT warmups land on /devicestats and as compile.<name> spans.
        self.collector = collector or default_collector()
        # Warmup bookkeeping: keyed by the (state, ctx) shape signature —
        # one chain serves models of different padded sizes, each needing
        # its own compile. Per-key events let distinct shape signatures
        # compile concurrently (their compiles are independent) while
        # duplicate keys coalesce onto one compilation instead of racing
        # into two full parallel compiles.
        self._warm_events: dict[tuple, threading.Event] = {}
        self._warm_lock = threading.Lock()
        self.passes = []
        self._pass_fns = []
        for i, g in enumerate(self.goals):
            run = make_goal_pass(g, self.goals[:i], cfg,
                                 all_goals=self.goals)
            self._pass_fns.append(run)
            self.passes.append(self.collector.track(
                f"pass.{g.name}", jax.jit(run, donate_argnums=(0,))))
        self._aux = self.collector.track("chain-aux",
                                         jax.jit(self._aux_impl))
        #: single-program whole-chain walk (cfg.fused_chain): one dispatch
        #: + one sync per optimize. Compiled lazily on first use so the
        #: default per-goal path never pays its (serial) XLA compile.
        self._fused = self.collector.track(
            "fused-chain", jax.jit(self._fused_impl, donate_argnums=(0,)))

    def _aux_impl(self, state, ctx):
        """Everything the host loop reads *before* the goal passes, fused
        into one dispatch: (offline.any() — the broken-broker self-check
        exemption, f32[G] per-goal rounding scales, f32[G] initial
        violation stack). One tunnel round trip instead of G + 2."""
        return (state.offline.any(),
                jnp.stack([g.violation_scale(state, ctx)
                           for g in self.goals]),
                violation_stack(self.goals, state, ctx))

    def _fused_impl(self, state, ctx, key):
        """The whole lexicographic chain in one traced program: every
        per-goal pass body inlined back-to-back, plus the aux readings —
        so one dispatch and one host fetch cover what the per-goal path
        spreads over G dispatches. Key folding matches the per-goal walk
        exactly (fold_in(key, i)), so both paths produce identical moves.
        Returns (state, aux, i32[G] per-goal iters, f32[G, G] boundary
        stacks — row i is the violation stack after goal i, i32[G]
        cumulative moves-applied boundaries)."""
        aux = self._aux_impl(state, ctx)
        iters, bounds, moves = [], [], []
        for i, run in enumerate(self._pass_fns):
            state, it, stack, m = run(state, ctx, jax.random.fold_in(key, i))
            iters.append(it)
            bounds.append(stack)
            moves.append(m)
        return state, aux, jnp.stack(iters), jnp.stack(bounds), \
            jnp.stack(moves)

    @staticmethod
    def _shape_key(*trees) -> tuple:
        # ONE bucket definition shared with the collector's recompile
        # classification — warmup keying and /devicestats shape buckets
        # must never drift apart.
        from ..core.runtime_obs import shape_key
        return shape_key(*trees)

    def warmup(self, state, ctx, key, max_workers: int | None = None) -> None:
        """AOT-compile every pass concurrently (XLA compilation releases the
        GIL, so a thread pool gets real parallelism). Ensures the persistent
        compilation cache is on so the compiled executables land in the
        file cache and the chain's first real run — this process or any
        later one — skips XLA entirely. Serial cold compile of a 15-goal
        chain costs tens of minutes on TPU; warmed-up it is the cost of
        the slowest single pass. No-op when these shapes were already
        warmed; concurrent callers serialize on one compilation."""
        import threading
        wkey = self._shape_key(state, ctx)
        while True:
            with self._warm_lock:
                event = self._warm_events.get(wkey)
                if event is None:
                    event = threading.Event()
                    self._warm_events[wkey] = event
                    owner = True
                else:
                    owner = False
            if not owner:
                # Another thread is (or finished) compiling this exact
                # shape — wait it out; a *different* shape key never
                # blocks here.
                event.wait()
                with self._warm_lock:
                    if self._warm_events.get(wkey) is event:
                        return          # owner succeeded
                continue   # owner failed and popped the key: retry as owner
            try:
                # AOT executables don't feed the jit dispatch cache
                # directly; the persistent cache is the bridge that makes
                # the follow-up jitted call cheap. Idempotent, and falls
                # back gracefully.
                from ..utils.platform import enable_compilation_cache
                enable_compilation_cache()
                from concurrent.futures import ThreadPoolExecutor
                if self.cfg.fused_chain:
                    # The fused program is the ONLY program this mode
                    # runs — its output carries the aux readings, and
                    # polish rounds are further fused dispatches (the
                    # optimizer's fused polish branch never touches the
                    # per-goal passes), so nothing else needs compiling.
                    jobs = [(self._fused, (state, ctx, key))]
                else:
                    jobs = [(p, (state, ctx, key)) for p in self.passes]
                    jobs.append((self._aux, (state, ctx)))
                # Pool workers have no active span (thread-local nesting),
                # so each AOT job records its compile.<program> span with
                # the warming thread's span as explicit parent — the
                # concurrent compiles render under optimizer.warmup in
                # /trace instead of vanishing.
                parent = self.collector.tracer.current_span_id()

                def _aot(job, _parent=parent):
                    program, args = job
                    program.aot_compile(args, parent_id=_parent)

                with ThreadPoolExecutor(max_workers
                                        or min(len(jobs), 16)) as ex:
                    list(ex.map(_aot, jobs))
            except BaseException:
                # Failed warmups must not poison the key: drop the event so
                # waiters and later calls retry the compile instead of
                # returning instantly as if warmed.
                with self._warm_lock:
                    self._warm_events.pop(wkey, None)
                event.set()
                raise
            event.set()
            return

    def violations(self, state, ctx) -> jax.Array:
        """f32[num_goals] residual per goal (aux's third element — one
        compiled program serves both readings)."""
        return self._aux(state, ctx)[2]

    def aux(self, state, ctx):
        """(offline.any(), f32[G] violation scales, f32[G] violations) in
        one dispatch — the host loop's pre-pass readings."""
        return self._aux(state, ctx)

    def fused(self, state, ctx, key):
        """One-dispatch whole-chain walk (see ``_fused_impl``)."""
        return self._fused(state, ctx, key)
