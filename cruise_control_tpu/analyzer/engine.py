"""The batched greedy search engine.

Replaces ``AbstractGoal.optimize``'s triple-nested scalar loop
(``AbstractGoal.java:82-135`` / ``maybeApplyBalancingAction`` ``:230-272``)
with, per goal, a ``lax.while_loop`` whose body:

1. asks the goal for a batch of candidate actions (top-K replicas x top-D
   destinations — all device-side ``top_k``/gathers, no host round trips);
2. scores every candidate at once: base legality, acceptance by all
   previously-optimized goals (the lexicographic chain, ref
   ``AnalyzerUtils.isProposalAcceptableForOptimizedGoals``), and the goal's
   own residual delta;
3. applies up to M best candidates through a sequential ``lax.scan`` that
   re-validates each against the already-updated state (two-row aggregate
   updates), so conflicting candidates in the same batch are skipped, not
   mis-applied.

The loop exits when an iteration applies nothing (no improving legal action
— same fixed point as the reference's ``_finished`` flag). Mandatory moves
(offline replicas, self-healing) are applied even when they don't improve
the current goal, provided they are legal and accepted by earlier goals.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from .constraint import SearchConfig
from .goals import GoalKernel
from .state import (SearchContext, SearchState, apply_candidate, base_legality,
                    candidate_at)


def _chain_accepts(prev_goals: Sequence[GoalKernel], state, ctx, cands):
    ok = jnp.ones(cands.p.shape, bool)
    for g in prev_goals:
        ok = ok & g.accepts(state, ctx, cands)
    return ok


def make_goal_pass(goal: GoalKernel, prev_goals: Sequence[GoalKernel],
                   cfg: SearchConfig):
    """Build the jittable single-goal optimization pass.

    Returns ``run(state, ctx, key) -> (state, iters)``. ``prev_goals`` are
    baked in at trace time (the goal chain is static configuration)."""

    eps = cfg.epsilon

    def apply_batch(state: SearchState, ctx: SearchContext, cands, score):
        M = min(cfg.apply_per_iter, score.shape[0])
        _, order = jax.lax.top_k(-score, M)

        def body(carry, i):
            state, n = carry
            c = candidate_at(cands, i)
            ok = base_legality(state, ctx, c)
            ok = ok & _chain_accepts(prev_goals, state, ctx, c)
            d = goal.delta(state, ctx, c)
            do = ok & ((d < -eps) | c.must)
            state = jax.lax.cond(do, lambda s: apply_candidate(s, ctx, c),
                                 lambda s: s, state)
            return (state, n + do.astype(jnp.int32)), None

        (state, n), _ = jax.lax.scan(body, (state, jnp.zeros((), jnp.int32)),
                                     order)
        return state, n

    def run(state: SearchState, ctx: SearchContext, key: jax.Array):
        def cond(carry):
            _, it, done = carry
            return (~done) & (it < cfg.max_iters_per_goal)

        def body(carry):
            state, it, _ = carry
            k = jax.random.fold_in(key, it)
            cands = goal.propose(state, ctx, k, cfg)
            ok = base_legality(state, ctx, cands)
            ok = ok & _chain_accepts(prev_goals, state, ctx, cands)
            delta = goal.delta(state, ctx, cands)
            # Mandatory (offline) moves outrank everything; otherwise only
            # improving actions are eligible.
            eligible = ok & ((delta < -eps) | cands.must)
            score = jnp.where(eligible,
                              jnp.where(cands.must, delta - 1e12, delta),
                              jnp.inf)
            state, applied = apply_batch(state, ctx, cands, score)
            return (state, it + 1, applied == 0)

        state, iters, _ = jax.lax.while_loop(
            cond, body, (state, jnp.zeros((), jnp.int32),
                         jnp.zeros((), bool)))
        return state, iters

    return run


class CompiledGoalChain:
    """Per-goal jitted passes for one (goal chain, config) pair.

    Kept per-goal (not one fused jit) deliberately: it preserves the
    reference's *anytime* behavior — after every goal the host holds a valid,
    strictly-not-worse state (ref ``GoalOptimizer.java:458-497`` loop) — and
    gives per-goal wall-clock numbers for ``OptimizerResult``.
    """

    def __init__(self, goals: Sequence[GoalKernel], cfg: SearchConfig):
        self.goals = list(goals)
        self.cfg = cfg
        self.passes = []
        for i, g in enumerate(self.goals):
            run = make_goal_pass(g, self.goals[:i], cfg)
            self.passes.append(jax.jit(run, donate_argnums=(0,)))
        self._violations = jax.jit(self._violations_impl)

    def _violations_impl(self, state, ctx):
        return jnp.stack([g.violation(state, ctx) for g in self.goals])

    def violations(self, state, ctx) -> jax.Array:
        """f32[num_goals] residual per goal."""
        return self._violations(state, ctx)
