"""Host-side optimizer orchestration (ref ``analyzer/GoalOptimizer.java``).

``TpuGoalOptimizer.optimize`` is the rebuild of
``GoalOptimizer.optimizations`` (``GoalOptimizer.java:435-524``): run the
goal chain in priority order (each pass a compiled batched search, see
:mod:`engine`), then diff initial vs final placement into execution
proposals (``AnalyzerUtils.getDiff``, ``:508-513``).

Everything per-goal stays on device; the host only sequences goals, stamps
wall-clock durations, and materializes the proposal diff at the end.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field, replace

import jax
import numpy as np

from ..model.flat import FlatClusterModel
from ..model.proposals import ExecutionProposal, diff_proposals, proposal_summary
from ..model.spec import ClusterMetadata
from .constraint import BalancingConstraint, PopulationConfig, SearchConfig
from .engine import CompiledGoalChain
from .goals import GoalKernel, default_goals
from .options import OptimizationOptions
from .state import build_context, init_state, to_model


@dataclass
class GoalResult:
    name: str
    hard: bool
    violation_before: float
    violation_after: float
    duration_s: float
    iterations: int
    #: magnitude the goal's float32 penalty sums reduce over (0 for
    #: integer-count goals, whose arithmetic is exact) — see
    #: GoalKernel.violation_scale
    scale: float = 0.0
    #: candidate actions this goal's pass actually applied (the
    #: moves_applied delta at the goal boundary, riding the end-of-chain
    #: fetch; 0 on the branched path where boundaries are unobservable)
    accepted: int = 0

    @property
    def satisfied(self) -> bool:
        # Ulp-aware cutoff: a float32 reduction over ``scale`` units of
        # load carries ~1e-7 relative rounding error, so a broker landing
        # exactly on its capacity limit can read as over by ~scale ulps.
        # 1e-6 * scale allows a handful of ulps; the absolute 1e-6 floor
        # covers scale == 0 (integer goals, exact arithmetic).
        return self.violation_after <= 1e-6 + 1e-6 * self.scale

    def to_json(self) -> dict:
        return {"goal": self.name, "hard": self.hard,
                "violationBefore": self.violation_before,
                "violationAfter": self.violation_after,
                "optimizationDurationMs": round(self.duration_s * 1e3, 3),
                "iterations": self.iterations,
                "acceptedMoves": self.accepted,
                "status": "NO-ACTION" if self.violation_before <= 1e-6
                else ("FIXED" if self.satisfied else "VIOLATED")}


@dataclass
class OptimizerResult:
    """Rebuild of ``analyzer/OptimizerResult.java``: proposals + per-goal
    stats + violated-goal sets before/after + provision verdict."""

    proposals: list[ExecutionProposal]
    goal_results: list[GoalResult]
    num_moves: int
    duration_s: float
    final_model: FlatClusterModel
    provision_response: object | None = None   # detector.ProvisionResponse
    #: Post-optimization audit of registered hard goals NOT in the chain
    #: (ref GoalOptimizer.java:458-497 — the reference runs its configured
    #: hard goals on every proposal computation, so a chain can never
    #: silently omit them; GoalViolationDetector.java:56 audits the same
    #: set continuously). Empty when the chain already contains every
    #: registered hard goal, when the audit is skipped
    #: (skip_hard_goal_check) or per-goal waived (waived_hard_goals).
    hard_goal_audit: list[GoalResult] = field(default_factory=list)
    #: device-side search telemetry collected from the SearchState
    #: boundaries riding the end-of-chain host fetch (no extra syncs):
    #: per-goal iteration/acceptance counts and the whole-chain violation
    #: trajectory. None on paths that cannot observe boundaries (branched).
    telemetry: dict | None = None
    #: True when the cluster model these proposals were computed from was
    #: stale-served (monitor degradation under sample dropouts) — the
    #: facade's execution gate refuses to act on such results unless the
    #: operator opted in (see monitor.StaleClusterModelError).
    stale_model: bool = False

    @property
    def violated_goals_before(self) -> list[str]:
        return [g.name for g in self.goal_results if g.violation_before > 1e-6]

    @property
    def violated_goals_after(self) -> list[str]:
        return [g.name for g in self.goal_results if not g.satisfied]

    @property
    def violated_hard_goals(self) -> list[str]:
        """Hard goals left violated — chain members AND audited off-chain
        hard goals, so a soft-goal-only chain cannot make the gate
        vacuous."""
        return ([g.name for g in self.goal_results
                 if g.hard and not g.satisfied]
                + [g.name for g in self.hard_goal_audit
                   if not g.satisfied])

    def to_json(self) -> dict:
        summary = proposal_summary(self.proposals)
        summary["numActions"] = self.num_moves
        return {"summary": summary,
                "goalSummary": [g.to_json() for g in self.goal_results],
                "hardGoalAudit": [g.to_json()
                                  for g in self.hard_goal_audit],
                "violatedGoalsBefore": self.violated_goals_before,
                "violatedGoalsAfter": self.violated_goals_after,
                "proposals": [p.to_json() for p in self.proposals],
                "optimizationDurationMs": round(self.duration_s * 1e3, 3),
                "searchTelemetry": self.telemetry,
                "provisionResponse": (None if self.provision_response is None
                                      else self.provision_response.to_json())}


class OptimizationFailureError(RuntimeError):
    """A hard goal remains violated (ref OptimizationFailureException).
    Carries the result so callers can still read the provision verdict and
    per-goal diagnostics."""

    def __init__(self, message: str, result: OptimizerResult):
        super().__init__(message)
        self.result = result


def _walk_passes(chain, idxs, state, ctx, keys, on_start=None,
                 collector=None):
    """Run ``chain.passes[i] for i in idxs`` back-to-back with NO host
    read in between: every pass is dispatched before any result is
    fetched, so the device (and, under axon, the tunnel) pipelines the
    walk with one sync at the end instead of two per pass — per-pass host
    reads dominate wall-clock for small models behind a high-latency
    transport.

    Passes execute in dispatch order (each consumes its predecessor's
    donated state), so blocking on each stack in turn yields completion
    timestamps and hence per-pass durations; the first pass's reading
    absorbs the dispatch loop itself. ``on_start(j)`` fires at execution
    (not dispatch) order so OperationProgress tracks the pass actually
    running. Returns ``(state, [(iters, stack, moves), ...] fetched to
    host, [duration_s, ...])`` — ``moves`` is the cumulative
    moves_applied boundary feeding per-goal acceptance telemetry."""
    dispatched = []
    for i, k in zip(idxs, keys):
        state, iters, stack, moves = chain.passes[i](state, ctx, k)
        dispatched.append((iters, stack, moves))
    t0 = time.monotonic()
    times = []
    for j, (_, stack, _) in enumerate(dispatched):
        if on_start is not None:
            on_start(j)
        jax.block_until_ready(stack)
        times.append(time.monotonic())
    durations = [times[j] - (times[j - 1] if j else t0)
                 for j in range(len(times))]
    fetched = jax.device_get(dispatched)
    if collector is not None:
        # Transfer accounting rides the fetch that already happened: byte
        # counts come off the host-side result (metadata only, no extra
        # syncs — the zero-syncs tracing gate covers this path too).
        collector.record_d2h(collector.tree_bytes(fetched))
    return state, fetched, durations


#: Process-wide compiled-chain registry. Chains were cached per
#: TpuGoalOptimizer instance, so every fresh optimizer built for the same
#: goal chain — facade memoization misses, goal-scoped healing optimizers,
#: detector optimizers, per-stack test fixtures — re-traced and re-compiled
#: identical XLA programs (the persistent cache softens the XLA half but
#: not tracing, and the in-process jit dispatch caches never shared).
#: A chain's compiled identity is exactly (search config, per-goal
#: (class, hard, constraint, bind signature), mesh): goal kernels are
#: stateless beyond their constraint (frozen dataclass of trace-time
#: constants) and bind-time masks (hashed by ``bind_signature``) — a goal
#: subclass carrying any OTHER config must fold it into its
#: ``bind_signature`` (the same contract the per-instance cache already
#: relied on for rebinding). FIFO-bounded: an evicted chain still in use
#: keeps working through its holder's reference; it just recompiles for
#: the next requester.
_SHARED_CHAINS: dict = {}
_SHARED_CHAINS_MAX = 64
_SHARED_CHAINS_LOCK = threading.Lock()

#: Process-wide compiled population-search programs, for the same reason
#: as ``_SHARED_CHAINS``: the facade's memoized goal-scoped optimizers
#: and per-stack test fixtures build fresh TpuGoalOptimizer instances for
#: identical (config, goal binding, K-bucket) tuples, and the population
#: program (the full chain x (1 + polish rounds), traced once) is the
#: most expensive single program in the repo. Bounded via the shared
#: ProgramCache machinery (lock-across-build get-or-create, FIFO).
def _population_programs():
    global _POPULATION_PROGRAMS
    with _SHARED_CHAINS_LOCK:
        if _POPULATION_PROGRAMS is None:
            from ..parallel.batching import ProgramCache
            _POPULATION_PROGRAMS = ProgramCache(16)
        return _POPULATION_PROGRAMS


_POPULATION_PROGRAMS = None


def _shared_chain_key(cfg: SearchConfig, goals, mesh_key):
    # name AND class: one class serves several catalog entries (the four
    # resource variants of CapacityGoal/UsageDistributionGoal differ only
    # in name + resource), and a subclass may reuse its parent's name.
    return (cfg,
            tuple((type(g), g.name, g.hard, getattr(g, "constraint", None),
                   g.bind_signature()) for g in goals),
            mesh_key)


class TpuGoalOptimizer:
    """Owns compiled goal chains; reusable across models with the same padded
    shapes (recompiles transparently otherwise — XLA cache keyed on shapes).
    Compiled chains are shared PROCESS-WIDE across optimizer instances (see
    ``_SHARED_CHAINS``): two optimizers configured for the same chain reuse
    one set of compiled passes and one warmup."""

    def __init__(self, goals: list[GoalKernel] | None = None,
                 constraint: BalancingConstraint | None = None,
                 config: SearchConfig | None = None,
                 options_generator=None,
                 registry=None,
                 mesh=None,
                 branches: int = 0,
                 population: "PopulationConfig | int | None" = None,
                 tuned_store=None,
                 hard_goal_names: list[str] | None = None,
                 tracer=None, collector=None):
        from ..core.runtime_obs import default_collector
        from ..core.sensors import (GOAL_OPTIMIZER_SENSOR, MetricRegistry)
        from ..core.tracing import default_tracer
        self.constraint = constraint or BalancingConstraint()
        self.goals = goals if goals is not None else default_goals(self.constraint)
        self.config = config or SearchConfig()
        #: per-shape-bucket tuned SearchConfig overrides
        #: (analyzer/tuning.py TunedConfigStore, ``search.tuning.*``
        #: server config): applied in _prepare BEFORE scaled_for, so a
        #: warm process serves tuned schedules with zero recompiles
        #: within a bucket (one tuned config per bucket = one chain key).
        self.tuned_store = tuned_store
        #: the active traffic regime (workload/regime.py vocabulary),
        #: flipped by the continuous tuning loop on regime shifts. A
        #: regime qualifies the tuned-store lookup — ``(bucket, regime)``
        #: entries win over plain buckets — and therefore the chain /
        #: dispatch-group key, so a shift between already-warm regimes
        #: swaps WHICH cached chain runs without compiling a new one.
        self.active_regime: str | None = None
        #: multi-objective population search over K candidate plans
        #: (``search.population`` server config; parallel/population.py):
        #: every member runs the full chain under its own PRNG stream in
        #: ONE jitted program, generations are joint weighted/Pareto
        #: scoring + truncation selection, and member 0 anchors the
        #: sequential schedule (K=1 is bit-identical to the sequential
        #: walk). size 0 = off. Mutually exclusive with branches/mesh —
        #: both own the device axis.
        if population is None:
            population = PopulationConfig()
        elif isinstance(population, int):
            population = PopulationConfig(size=population)
        self.population = population
        if self.population.enabled:
            if self.population.objective not in ("weighted", "pareto"):
                raise ValueError(
                    f"unknown population objective "
                    f"{self.population.objective!r}: expected 'weighted' "
                    "or 'pareto'")
            if branches and int(branches) > 1:
                raise ValueError(
                    "search.population and search.branches are mutually "
                    "exclusive: both replicate the model per device "
                    "(the population IS the generalized branch pool)")
            if mesh is not None:
                raise ValueError(
                    "search.population and search.mesh.devices are "
                    "mutually exclusive: the population replicates the "
                    "model per member, the mesh shards it")
            if self.config.fused_chain:
                raise ValueError(
                    "search.population and search.fused.chain are "
                    "mutually exclusive: the population program IS one "
                    "fused dispatch already, and its polish rounds use "
                    "the per-goal key schedule — running it against the "
                    "fused sequential path would break the K=1 "
                    "bit-parity anchor guarantee (docs/search.md)")
        #: /devicestats `population` section — last run's joint-scoring
        #: snapshot (None until a population optimize ran).
        self.last_population_stats: dict | None = None
        #: the REGISTERED hard-goal set for the post-optimization audit
        #: (ref the ``hard.goals`` server config consumed by
        #: sanityCheckHardGoalPresence and GoalViolationDetector): None =
        #: the default catalog's hard members. Chain membership still
        #: exempts a goal from re-audit.
        self.hard_goal_names = hard_goal_names
        #: best-of-N independent search branches (``search.branches``
        #: server config; parallel/branches.py): each device runs the
        #: full chain under its own PRNG stream via shard_map, the
        #: lexicographically best final state wins — the device-resident
        #: replacement for the reference's proposal-precompute thread
        #: pool (GoalOptimizer.java:112-119, N chain runs on cloned
        #: models, best cached). 0/1 = single-branch (this machinery
        #: entirely bypassed). Mutually exclusive with ``mesh``.
        self.branches = int(branches or 0)
        self._branched_runs: dict = {}
        if self.branches > 1 and mesh is not None:
            raise ValueError("search.branches and search.mesh.devices are "
                             "mutually exclusive: branches replicate the "
                             "model per device, the mesh shards it")
        #: optional jax.sharding.Mesh: when set, every optimize()/warmup()
        #: places the model on the mesh (partition axis sharded, broker
        #: axis replicated — parallel/sharding.py layout) and the jitted
        #: goal passes partition via GSPMD, with the per-iteration broker
        #: aggregate riding an ICI all-reduce. Single-device meshes are a
        #: no-op, so the served path can always be constructed with one.
        self.mesh = mesh
        #: OptimizationOptionsGenerator plugin applied to every run's
        #: options inside _prepare — the single choke point, so the
        #: proposal cache and the goal-violation detector (which call
        #: optimize() directly, not through the facade) can't bypass it.
        self.options_generator = options_generator
        self._audit_fns: dict[tuple, object] = {}
        self.registry = registry or MetricRegistry()
        #: span tracer threading the whole pipeline (None = the shared
        #: process-wide default, like the reference's single registry)
        self.tracer = tracer or default_tracer()
        #: device-runtime ledger (None = process default): compiled
        #: chains, audit fns and the branched shard_map program all
        #: register as TrackedPrograms; optimize() brackets itself in a
        #: collector cycle so /devicestats reports per-cycle compile and
        #: transfer deltas.
        self.collector = collector or default_collector()
        # ref GoalOptimizer.java:128 proposal-computation-timer.
        self._proposal_timer = self.registry.timer(MetricRegistry.name(
            GOAL_OPTIMIZER_SENSOR, "proposal-computation-timer"))
        if self.population.enabled:
            # Population-search telemetry families (all fed from the
            # end-of-chain fetch — no extra device reads): last Pareto-
            # front size and winner slot, plans-evaluated meter. Gauges
            # register ONCE per registry: goal-scoped optimizers (the
            # facade's memoized builders) share the server optimizer's
            # registry, and re-registering would rebind the lambdas to
            # the newest instance — /metrics would then report a
            # goal-scoped optimizer's stale snapshot instead of the
            # serving loop's. First constructed (the server optimizer)
            # wins; meters accumulate across instances by design.
            name = MetricRegistry.name
            for metric, key in (("population-pareto-front-size",
                                 "paretoFrontSize"),
                                ("population-winner-index", "winner")):
                full = name(GOAL_OPTIMIZER_SENSOR, metric)
                if self.registry.get(full) is None:
                    self.registry.gauge(
                        full, lambda _k=key: (
                            self.last_population_stats or {}).get(_k, 0))
            self._population_meter = self.registry.meter(
                name(GOAL_OPTIMIZER_SENSOR, "population-plans-evaluated"))

    def _chain_for(self, cfg: SearchConfig, goals: list[GoalKernel]
                   ) -> CompiledGoalChain:
        # Mesh identity in the key: the same chain object jit-caches per
        # input sharding, but warmup events are keyed by *shape* signature
        # — a chain warmed unsharded must not satisfy a sharded warmup.
        from ..parallel.sharding import mesh_fingerprint
        mesh_key = mesh_fingerprint(self.mesh)
        key = _shared_chain_key(cfg, goals, mesh_key)
        # Locked get-or-create against the PROCESS-WIDE registry:
        # optimizers are shared across request threads (facade
        # memoization) and chains across optimizer instances, so every
        # racing first request must converge on ONE chain object —
        # CompiledGoalChain.warmup coalesces compiles per instance, and
        # distinct instances would each pay the full parallel XLA
        # compile. The chain's TrackedPrograms land on the FIRST
        # requester's collector (in practice everyone shares the process
        # default).
        with _SHARED_CHAINS_LOCK:
            chain = _SHARED_CHAINS.pop(key, None)
            if chain is None:
                chain = CompiledGoalChain(goals, cfg,
                                          collector=self.collector)
            _SHARED_CHAINS[key] = chain       # re-insert = most recent
            while len(_SHARED_CHAINS) > _SHARED_CHAINS_MAX:
                _SHARED_CHAINS.pop(next(iter(_SHARED_CHAINS)))
            return chain

    def _prepare(self, model: FlatClusterModel, metadata: ClusterMetadata,
                 options: OptimizationOptions):
        """Shared optimize()/warmup() prep: scaled config, bound goals,
        compiled-chain lookup, search context (with the request's exclusion
        masks) and initial state — one definition so a warmed chain is
        exactly the chain a matching optimize() will run."""
        if self.options_generator is not None:
            options = self.options_generator.generate(options, metadata)
        if self.mesh is not None:
            # Compute follows data: sharding the model here is all GSPMD
            # needs — ctx/state derive from model arrays and inherit the
            # layout; the jitted passes partition automatically.
            from ..parallel.sharding import shard_model
            model = shard_model(model, self.mesh)
        P = model.num_partitions_padded
        B = model.num_brokers_padded
        # Tuned schedule lookup BEFORE the tiny-model clamp: one tuned
        # config per shape bucket means one scaled cfg — hence one chain
        # key and ZERO recompiles — for every model in the bucket.
        base_cfg = self.config
        if self.tuned_store is not None:
            base_cfg = self.tuned_store.apply(
                base_cfg, metadata.num_partitions, metadata.num_brokers,
                regime=self.active_regime)
        cfg = base_cfg.scaled_for(metadata.num_partitions,
                                  metadata.num_brokers)
        if options.fast_mode:
            cfg = replace(
                cfg,
                max_iters_per_goal=max(cfg.max_iters_per_goal // 4, 16)
            ).scaled_for(max(metadata.num_partitions // 4, 8),
                         metadata.num_brokers)
        # Resolve pattern-configured goals against this model's metadata
        # (topic masks, broker sets); the chain cache key carries the
        # binding so unchanged topology reuses compiled passes.
        goals = [g.bind(metadata) for g in self.goals]
        chain = self._chain_for(cfg, goals)
        audit = self._audit_goals_for(goals, metadata, options)

        excluded_parts = options.excluded_partition_mask(metadata, P)
        ctx = build_context(
            model,
            excluded_partitions=None if excluded_parts is None
            else jax.numpy.asarray(excluded_parts),
            excluded_brokers_for_replica_move=_as_jnp(
                options.replica_move_exclusion_mask(metadata, B)),
            excluded_brokers_for_leadership=_as_jnp(
                options.broker_mask(metadata, B,
                                    options.excluded_brokers_for_leadership)))

        needs_tlc = any(g.uses_topic_leader_counts for g in goals + audit)
        needs_topics = needs_tlc or any(g.uses_topic_counts
                                        for g in goals + audit)
        state = init_state(
            model,
            with_topic_counts=metadata.num_topics if needs_topics else None,
            with_topic_leader_counts=needs_tlc)
        return cfg, goals, chain, ctx, state, audit

    def _audit_goals_for(self, chain_goals, metadata,
                         options: OptimizationOptions):
        """Registered hard goals NOT in the chain, bound to this model —
        the post-optimization audit set (ref GoalOptimizer.java:458-497:
        the reference's proposal computation always runs its configured
        hard goals; GoalViolationDetector.java:56 audits the same set).
        Without this, a request naming only soft goals would make the
        hard-goal gate vacuous. Empty when skipped or fully waived."""
        if options.skip_hard_goal_check:
            return []
        in_chain = {g.name for g in chain_goals}
        # A chain carrying a documented relaxation of a registered hard
        # goal signals the operator chose the alternative: auditing the
        # strict form would fail every RF > num_racks cluster the
        # relaxation exists for.
        from .goals import HARD_GOAL_ALTERNATIVES as alternatives
        if self.hard_goal_names is not None:
            from .goals import goals_by_name
            registered = goals_by_name(self.hard_goal_names,
                                       self.constraint)
        else:
            registered = [g for g in default_goals(self.constraint)
                          if g.hard]
        return [g.bind(metadata) for g in registered
                if g.name not in in_chain
                and g.name not in options.waived_hard_goals
                and not any(a in in_chain
                            for a in alternatives.get(g.name, ()))]

    def _audit_fn_for(self, audit):
        """Jitted ``(state, ctx) -> (f32[A] violations, f32[A] scales)``
        over the audit goals — one dispatch each on the initial and final
        states; cached per goal binding (jit itself re-specializes per
        input shapes/shardings)."""
        key = tuple((g.name, g.bind_signature()) for g in audit)
        fn = self._audit_fns.get(key)
        if fn is None:
            from .engine import violation_stack

            def _audit(state, ctx, _goals=tuple(audit)):
                import jax.numpy as jnp
                return (violation_stack(_goals, state, ctx),
                        jnp.stack([g.violation_scale(state, ctx)
                                   for g in _goals]))
            fn = self._audit_fns.setdefault(
                key, self.collector.track("hard-goal-audit",
                                          jax.jit(_audit)))
            # Bounded like the facade's goal-optimizer LRU: bind
            # signatures carry per-topic masks, so an evolving topic set
            # would otherwise accumulate compiled audit programs forever.
            while len(self._audit_fns) > 16:
                self._audit_fns.pop(next(iter(self._audit_fns)))
        return fn

    def warmup(self, model: FlatClusterModel, metadata: ClusterMetadata,
               options: OptimizationOptions | None = None) -> None:
        """Compile the goal chain for this model's shapes (and these
        options — fast_mode compiles a different chain) ahead of time, all
        passes in parallel (see ``CompiledGoalChain.warmup``). Safe to call
        from a background thread at server startup; a subsequent
        ``optimize`` with the same shapes pays no XLA compile."""
        options = options or OptimizationOptions()
        with self.tracer.span("optimizer.warmup"):
            cfg, goals, chain, ctx, state, audit = self._prepare(
                model, metadata, options)
            key = jax.random.PRNGKey(options.seed)
            if audit:
                # The off-chain hard-goal audit runs on the request path
                # too — pre-compile its (tiny) violation-stack program
                # alongside the chain so the first optimize pays no XLA
                # at all. (aot_compile: the compile lands on /devicestats
                # and as a compile.hard-goal-audit span.)
                self._audit_fn_for(audit).aot_compile((state, ctx))
            if self.population.enabled:
                # The population path serves its one fused program (the
                # per-goal passes never dispatch standalone) — warm that,
                # through the persistent cache like the branched path.
                from ..utils.platform import enable_compilation_cache
                enable_compilation_cache()
                run, _, _, _ = self._population_run_for(cfg, goals, chain)
                run.aot_compile((state, ctx, key))
                return
            if self.branches > 1:
                # The branched path never runs the per-goal passes — warm
                # the shard_map program it actually serves instead. AOT
                # compiles don't seed the jit dispatch cache; the
                # persistent file cache is the bridge that makes the
                # first real optimize skip XLA (mirrors
                # CompiledGoalChain.warmup).
                from ..utils.platform import enable_compilation_cache
                enable_compilation_cache()
                self._branched_run_for(cfg, goals).aot_compile(
                    (state, ctx, key))
                return
            chain.warmup(state, ctx, key)

    def _branched_run_for(self, cfg: SearchConfig, goals):
        """Get-or-build the jitted shard_map program for this (cfg, goal
        binding, branch count) — ONE definition so warmup pre-compiles
        exactly the program optimize serves (the warm/serve-mismatch
        hazard _chain_for's mesh key guards against)."""
        from ..parallel.branches import make_branch_mesh, make_branched_search
        bkey = (cfg, tuple(g.bind_signature() for g in goals), self.branches)
        run = self._branched_runs.get(bkey)
        if run is None:
            run = self._branched_runs.setdefault(
                bkey, make_branched_search(
                    goals, cfg, make_branch_mesh(self.branches),
                    collector=self.collector))
            # FIFO-bounded like _SHARED_CHAINS: bind signatures carry
            # per-topic masks, so a long-lived fleet process with
            # churning shape buckets / topic sets would otherwise
            # accumulate compiled shard_map programs forever. An evicted
            # program still in flight keeps working through its holder's
            # reference; the next requester just rebuilds it.
            while len(self._branched_runs) > _SHARED_CHAINS_MAX:
                self._branched_runs.pop(next(iter(self._branched_runs)))
        return run

    def _population_run_for(self, cfg: SearchConfig, goals, chain):
        """Get-or-build the population-search program for this (cfg, goal
        binding, K-bucket) — keyed like the shared-chain registry plus
        the population config, cached PROCESS-WIDE so fresh optimizer
        instances for the same chain reuse one compiled program. Returns
        ``(run, D devices, members per device, K bucket)``."""
        from ..parallel.population import (make_population_mesh,
                                           make_population_search,
                                           population_layout)
        D, k, K = population_layout(self.population.size)
        key = ("population",
               _shared_chain_key(cfg, goals, None),
               self.population, D, k)
        run = _population_programs().get_or_build(
            key, lambda: make_population_search(
                chain._pass_fns, goals, cfg, self.population,
                make_population_mesh(D), k, collector=self.collector))
        return run, D, k, K

    def optimize(self, model: FlatClusterModel, metadata: ClusterMetadata,
                 options: OptimizationOptions | None = None,
                 on_goal_start=None) -> OptimizerResult:
        """``on_goal_start(goal_name)``: optional progress hook invoked as
        each goal pass begins (the facade feeds OperationProgress with it —
        ref the ``OptimizationForGoal`` steps in /user_tasks)."""
        options = options or OptimizationOptions()
        # The collector cycle brackets the whole computation: on exit the
        # h2d/d2h/compile deltas become /devicestats' lastCycle (outermost
        # wins, so a facade-level cycle spanning monitor+optimize absorbs
        # this one).
        with self.collector.cycle("propose"), \
                self.tracer.span("optimizer.optimize",
                                 brokers=metadata.num_brokers,
                                 partitions=metadata.num_partitions) as root:
            result = self._optimize_impl(model, metadata, options,
                                         on_goal_start)
            root.set(moves=result.num_moves, proposals=len(result.proposals))
            return result

    def _optimize_impl(self, model: FlatClusterModel,
                       metadata: ClusterMetadata,
                       options: OptimizationOptions,
                       on_goal_start) -> OptimizerResult:
        t0 = time.monotonic()
        with self.tracer.span("optimizer.prepare"):
            cfg, goals, chain, ctx, state, audit = self._prepare(
                model, metadata, options)
        key = jax.random.PRNGKey(options.seed)
        # Off-chain hard-goal audit, initial reading: dispatched before any
        # donating pass touches the state buffer (same ordering argument as
        # chain.aux below — device execution follows dispatch order).
        audit_fn = self._audit_fn_for(audit) if audit else None
        audit_before = (audit_fn(state, ctx) if audit_fn is not None
                        else None)

        # First use of this (shapes, goal-chain) pairing: compile all
        # passes in parallel instead of paying serial XLA compiles one
        # goal at a time as the chain walks (tens of minutes for a full
        # default chain on TPU; the persistent compilation cache then
        # makes later processes skip XLA entirely). No-op once warmed.
        # (The branched path compiles its own shard_map program instead —
        # it never runs the per-goal passes.)
        if self.population.enabled:
            return self._optimize_population(model, metadata, options,
                                             cfg, goals, chain, ctx,
                                             state, key, t0, on_goal_start,
                                             audit, audit_fn, audit_before)
        if self.branches > 1:
            return self._optimize_branched(model, metadata, options, cfg,
                                           goals, chain, ctx, state, key,
                                           t0, on_goal_start,
                                           audit, audit_fn, audit_before)
        with self.tracer.span("optimizer.warmup"):
            chain.warmup(state, ctx, key)

        # One violation stack per goal boundary: stack[i] before goal i runs
        # doubles as stack[j<i] "after" readings (matches the per-goal stats
        # the reference records at GoalOptimizer.java:458-497).
        #
        # The chain walk is fully async: every goal pass is dispatched
        # before any result is read, so the device (and, under axon, the
        # tunnel) pipelines the whole chain with ONE host sync at the end
        # instead of two per goal — per-goal host reads dominate wall-clock
        # for small models behind a high-latency transport. Pre-pass
        # readings (broken-broker flag, per-goal rounding scales, initial
        # violation stack) ride one fused aux dispatch for the same reason.
        walk_span = self.tracer.span(
            "optimizer.walk", mode="fused" if cfg.fused_chain else "per-goal",
            goals=len(goals))
        with walk_span:
            if cfg.fused_chain:
                # One device dispatch + one host fetch for the entire chain
                # (latency-bound serving: demo clusters, self-healing
                # replans over a tunneled device). Key folding inside the
                # fused program matches the per-goal walk, so the MAIN
                # walk's moves are identical across modes; if residuals
                # survive into polish, the modes diverge there (fused
                # polish re-runs the whole chain under a distinct PRNG
                # stream, per-goal polish re-runs only the unconverged
                # subset) — both land on valid converged plans, just not
                # bit-identical ones.
                if on_goal_start is not None:
                    # One program = no observable per-goal boundaries:
                    # report ONE truthful step for the whole fused walk
                    # instead of pretending every goal started at t=0 (the
                    # per-goal path reports steps at real execution
                    # boundaries).
                    on_goal_start(f"FusedChain[{len(goals)}]")
                t_walk = time.monotonic()
                state, aux, iters_arr, bounds, moves_arr = chain.fused(
                    state, ctx, key)
                fetched_host = jax.device_get((aux, iters_arr, bounds,
                                               moves_arr))
                self.collector.record_d2h(
                    self.collector.tree_bytes(fetched_host))
                (has_broken_raw, scales_arr, v0), iters_np, bounds_np, \
                    moves_np = fetched_host
                walk_s = time.monotonic() - t_walk
                # Per-goal wall-clock is unobservable inside one program;
                # attribute the fused walk proportionally to iteration
                # counts.
                total_iters = max(int(iters_np.sum()), 1)
                durations = [walk_s * int(it) / total_iters
                             for it in iters_np]
                fetched = list(zip(iters_np, bounds_np, moves_np))
            else:
                aux = chain.aux(state, ctx)
                state, fetched, durations = _walk_passes(
                    chain, range(len(goals)), state, ctx,
                    [jax.random.fold_in(key, i) for i in range(len(goals))],
                    on_start=(None if on_goal_start is None
                              else lambda j: on_goal_start(goals[j].name)),
                    collector=self.collector)
                has_broken_raw, scales_arr, v0 = jax.device_get(aux)
                self.collector.record_d2h(self.collector.tree_bytes(
                    (has_broken_raw, scales_arr, v0)))
        # ref AbstractGoal.java:110-119: the "never worsen" assertion only
        # runs when brokenBrokers.isEmpty() — a dead-broker drain's
        # must-moves (remove_brokers, fix_offline_replicas, self-healing)
        # bypass the per-candidate improvement test and may legitimately
        # worsen a goal's own residual while healing the cluster.
        has_broken = bool(has_broken_raw)
        scales = [float(s) for s in scales_arr]
        goal_results: list[GoalResult] = []
        boundary = np.asarray(v0)
        #: whole-chain violation trajectory — row 0 is the initial stack,
        #: row i+1 the stack after goal i's pass (all fetched with the
        #: walk; polish rounds append further rows below).
        trajectory: list[list[float]] = [[float(x) for x in boundary]]
        prev_moves = 0
        for i, (goal, (iters, stack, moves)) in enumerate(zip(goals,
                                                              fetched)):
            before_i = float(boundary[i])
            boundary = np.asarray(stack)
            trajectory.append([float(x) for x in boundary])
            accepted_i = int(moves) - prev_moves
            prev_moves = int(moves)
            after_i = float(boundary[i])
            # Self-check (ref AbstractGoal.java:110-119: the optimization
            # "stats should not be worse" assertion): a goal pass may never
            # worsen its OWN violation — lexicographic acceptance makes
            # that structurally impossible, so a breach means a broken
            # goal kernel, and silently serving its plan would hand the
            # executor a regression.
            if after_i > before_i * (1 + 1e-6) + 1e-6:
                if has_broken:
                    logging.getLogger(__name__).warning(
                        "goal %s worsened its own violation %.6g -> %.6g "
                        "while draining broken brokers (self-check exempt, "
                        "ref AbstractGoal brokenBrokers guard)",
                        goal.name, before_i, after_i)
                else:
                    raise RuntimeError(
                        f"optimization self-check failed: goal {goal.name} "
                        f"worsened its own violation {before_i:.6g} -> "
                        f"{after_i:.6g}")
            goal_results.append(GoalResult(
                name=goal.name, hard=goal.hard,
                violation_before=before_i,
                violation_after=after_i,
                duration_s=durations[i],
                iterations=int(iters),
                scale=scales[i],
                accepted=accepted_i))

        # Per-goal child spans of the walk, reconstructed from the
        # single-sync duration list (fused mode: proportional attribution
        # by iteration count) — no extra device reads, just bookkeeping.
        off = walk_span.start_s
        for gr in goal_results:
            self.tracer.record(
                f"goal.{gr.name}", gr.duration_s, start_s=off,
                parent_id=walk_span.span_id,
                attrs={"iterations": gr.iterations,
                       "accepted": gr.accepted,
                       "violationBefore": round(gr.violation_before, 6),
                       "violationAfter": round(gr.violation_after, 6)})
            off += gr.duration_s

        # Polish passes: later goals' accepted actions may have drifted
        # earlier goals within the acceptance tolerances; re-running the
        # drifted goals re-zeros them (converged goals are skipped — their
        # residual is already ≤ ε on the fused post-pass stack). No
        # reference equivalent — the reference's single sequential walk
        # simply tolerates the drift.
        # Per-goal convergence threshold: stricter than (or equal to) the
        # satisfied/hard-goal cutoff — GoalResult.satisfied tolerates
        # 1e-6 + 1e-6*scale, polish skips only below min(epsilon, 1e-6) —
        # so a goal can never be skipped as converged yet reported
        # VIOLATED.
        polish_eps = min(cfg.epsilon, 1e-6)
        moves_total = prev_moves
        # +1: skip decisions use each round's *starting* boundary (so the
        # whole round dispatches async with one fetch — a per-goal host
        # sync is what the async walk exists to avoid), which means drift
        # created by a pass onto an already-converged goal inside the LAST
        # budgeted round would go unseen; the extra round is the catch-up
        # sweep for exactly that case and is skipped whenever the previous
        # round ended clean. ``not (<=)`` keeps NaN residuals (broken goal
        # kernel) in the todo set rather than silently converged.
        for rnd in range(cfg.polish_passes + 1 if cfg.polish_passes else 0):
            if (boundary <= polish_eps).all():
                break
            with self.tracer.span("optimizer.polish", round=rnd):
                if cfg.fused_chain:
                    # Fused mode never touches the per-goal programs (they
                    # would each pay an XLA compile on first use — a
                    # latency spike on exactly the latency-bound path
                    # fused serves): a polish round is one more fused
                    # whole-chain dispatch; converged goals cost one
                    # violation read each (the engine's lax.cond early
                    # exit).
                    tp0 = time.monotonic()
                    state, _aux2, it2, b2, m2 = chain.fused(
                        state, ctx, jax.random.fold_in(key, 50_000 + rnd))
                    it2, b2, m2 = jax.device_get((it2, b2, m2))
                    self.collector.record_d2h(
                        self.collector.tree_bytes((it2, b2, m2)))
                    w = time.monotonic() - tp0
                    tot = max(int(it2.sum()), 1)
                    boundary = np.asarray(b2[-1])
                    trajectory.append([float(x) for x in boundary])
                    prev = moves_total
                    for i, gr in enumerate(goal_results):
                        acc = int(m2[i]) - prev
                        prev = int(m2[i])
                        goal_results[i] = replace(
                            gr,
                            duration_s=gr.duration_s + w * int(it2[i]) / tot,
                            iterations=gr.iterations + int(it2[i]),
                            accepted=gr.accepted + acc)
                    moves_total = prev
                    continue
                todo = [i for i in range(len(goals))
                        if not (boundary[i] <= polish_eps)]
                state, fetched, durations = _walk_passes(
                    chain, todo, state, ctx,
                    [jax.random.fold_in(key, 1000 * (rnd + 1) + i)
                     for i in todo], collector=self.collector)
                for j, (i, (iters, stack, moves)) in enumerate(zip(todo,
                                                                   fetched)):
                    boundary = np.asarray(stack)
                    gr = goal_results[i]
                    acc = int(moves) - moves_total
                    moves_total = int(moves)
                    goal_results[i] = replace(
                        gr, violation_after=float(boundary[i]),
                        duration_s=gr.duration_s + durations[j],
                        iterations=gr.iterations + int(iters),
                        accepted=gr.accepted + acc)
                trajectory.append([float(x) for x in boundary])

        # The boundary stack is the ground truth for final residuals; a
        # goal's stored reading can be stale if a later pass moved it.
        goal_results = [replace(gr, violation_after=float(boundary[i]))
                        for i, gr in enumerate(goal_results)]
        return self._finish(model, metadata, options, state, goal_results,
                            t0, ctx, audit, audit_fn, audit_before,
                            trajectory=trajectory)

    def _optimize_population(self, model, metadata, options, cfg, goals,
                             chain, ctx, state, key, t0, on_goal_start,
                             audit=(), audit_fn=None, audit_before=None):
        """Multi-objective population search (parallel/population.py): K
        candidate plans evolve in ONE jitted program — every member runs
        the chain walk under its own PRNG stream, polish generations are
        joint weighted/Pareto scoring + truncation selection, and the
        served plan is the multi-objective winner with hard-goal audit
        verdicts dominating. Member 0 anchors the exact sequential
        schedule, so K=1 is bit-identical to the sequential walk and the
        winner never scores worse than the sequential plan under the
        configured objective. ALL telemetry (per-member per-goal
        acceptance, Pareto front size, survivor history) rides the one
        end-of-chain fetch — zero extra device syncs (tier-1 gated)."""
        from ..parallel.population import select_plan
        run, D, k, K = self._population_run_for(cfg, goals, chain)
        if on_goal_start is not None:
            # One program = one truthful progress step (fused convention).
            on_goal_start(f"PopulationSearch[{len(goals)}x{K}]")
        with self.tracer.span("optimizer.walk", mode="population",
                              population=K, devices=D,
                              goals=len(goals)) as walk_span:
            t_walk = time.monotonic()
            (states, aux, iters, walk_bounds, polish_rows, moves,
             accepted, perms, ranks, weighted) = run(state, ctx, key)
            fetched = jax.device_get((aux, iters, walk_bounds,
                                      polish_rows, moves, accepted,
                                      perms, ranks, weighted))
            self.collector.record_d2h(self.collector.tree_bytes(fetched))
            ((has_broken_raw, scales_arr, v0), iters_np, wb_np, pr_np,
             mv_np, acc_np, perm_np, rank_np, w_np) = fetched
            v0 = np.asarray(v0)
            wb_np = np.asarray(wb_np)
            pr_np = np.asarray(pr_np)
            boundary_np = pr_np[-1] if len(pr_np) else wb_np[:, -1, :]
            state, best, _vbest = select_plan(
                states, boundary_np, mv_np, rank_np, w_np,
                self.population,
                audit_eval=(None if audit_fn is None
                            else lambda s: audit_fn(s, ctx)))
            walk_span.set(winner=int(best))
        walk_s = time.monotonic() - t_walk

        has_broken = bool(has_broken_raw)
        logger = logging.getLogger(__name__)
        # Per-lineage self-check over the walk boundaries (the sequential
        # "never worsen your own violation" assertion, ref
        # AbstractGoal.java:110-119) — every surviving lineage is
        # checked, with the broken-broker drain exemption.
        for m in range(K):
            boundary = v0
            for i, g in enumerate(goals):
                before_i = float(boundary[i])
                boundary = wb_np[m, i]
                after_i = float(boundary[i])
                if after_i > before_i * (1 + 1e-6) + 1e-6:
                    if has_broken:
                        logger.warning(
                            "population[%d]: goal %s worsened its own "
                            "violation %.6g -> %.6g while draining broken "
                            "brokers (self-check exempt)", m, g.name,
                            before_i, after_i)
                    else:
                        raise RuntimeError(
                            f"optimization self-check failed: population "
                            f"member {m}, goal {g.name} worsened its own "
                            f"violation {before_i:.6g} -> {after_i:.6g}")

        # Winner bookkeeping — identical structure to the sequential
        # loop's, read off the winner slot's lineage rows.
        scales = [float(s) for s in np.asarray(scales_arr)]
        total_iters = max(int(iters_np[best].sum()), 1)
        goal_results: list[GoalResult] = []
        for i, goal in enumerate(goals):
            before_i = float((v0 if i == 0 else wb_np[best, i - 1])[i])
            goal_results.append(GoalResult(
                name=goal.name, hard=goal.hard,
                violation_before=before_i,
                violation_after=float(boundary_np[best][i]),
                # One program: per-goal wall-clock is unobservable —
                # attribute proportionally to iteration counts (fused
                # convention).
                duration_s=walk_s * int(iters_np[best, i]) / total_iters,
                iterations=int(iters_np[best, i]),
                scale=scales[i],
                accepted=int(acc_np[best, i])))

        # Winner trajectory, sequential convention: row 0 = initial
        # stack, rows 1..G = walk boundaries, one row per polish round
        # that actually ran (a round starting fully converged is the
        # host loop's `break` — its unchanged row is dropped).
        polish_eps = min(cfg.epsilon, 1e-6)
        trajectory = [[float(x) for x in v0]]
        trajectory += [[float(x) for x in wb_np[best, i]]
                       for i in range(len(goals))]
        prev_row = wb_np[best, -1]
        for r in range(len(pr_np)):
            if (prev_row <= polish_eps).all():
                break
            prev_row = pr_np[r, best]
            trajectory.append([float(x) for x in prev_row])

        # Front size straight off the program's fetched ranks — NO
        # recomputation (an eager pareto_ranks here would be a fresh
        # device dispatch on the serving path, invisible to the
        # zero-syncs gate's device_get patching).
        front = int((np.asarray(rank_np) == 0).sum())
        pop_stats = {
            "size": K,
            "requested": self.population.size,
            "devices": D,
            "objective": self.population.objective,
            "winner": int(best),
            "winnerIsAnchor": bool(best == 0),
            "paretoFrontSize": front,
            "paretoRanks": [int(x) for x in np.asarray(rank_np)],
            "weightedScores": [round(float(x), 6)
                               for x in np.asarray(w_np)],
            "movesPerMember": [int(x) for x in np.asarray(mv_np)],
            # i32[K][G]: candidate acceptance per member per goal — the
            # population-wide acceptance telemetry.
            "perGoalAcceptance": np.asarray(acc_np).tolist(),
            "survivorPerms": np.asarray(perm_np).tolist(),
        }
        self.last_population_stats = pop_stats
        self._population_meter.mark(K)
        return self._finish(model, metadata, options, state, goal_results,
                            t0, ctx, audit, audit_fn, audit_before,
                            trajectory=trajectory,
                            extra_telemetry={"population": pop_stats})

    def _optimize_branched(self, model, metadata, options, cfg, goals,
                           chain, ctx, state, key, t0, on_goal_start,
                           audit=(), audit_fn=None, audit_before=None):
        """Best-of-N independent search branches (parallel/branches.py):
        every device runs the FULL goal chain on a replicated model under
        its own PRNG stream via shard_map, and the lexicographically best
        final state is served — the device-resident replacement for the
        reference's proposal-precompute thread pool
        (GoalOptimizer.java:112-119: N chain runs on cloned models, best
        result cached). Per-goal iteration counts are not observable
        inside the shard_map program (reported as 0) and polish is
        skipped — branch diversity plays its role; the winning boundary
        still feeds the same hard-goal gate, and select_best fails loudly
        on NaN residuals (the broken-kernel case the sequential
        self-check catches)."""
        from ..parallel.branches import select_best, select_best_audited
        if on_goal_start is not None:
            on_goal_start(f"BranchedChain[{len(goals)}x{self.branches}]")
        aux = chain.aux(state, ctx)
        run = self._branched_run_for(cfg, goals)
        with self.tracer.span("optimizer.walk", mode="branched",
                              branches=self.branches,
                              goals=len(goals)) as walk_span:
            t_walk = time.monotonic()
            states, viols = run(state, ctx, key)
            if audit_fn is not None:
                # The off-chain hard-goal audit dominates branch selection:
                # without this, the chain-lexicographic winner could fail
                # the gate while an audit-passing plan existed in the same
                # run.
                state, best_idx, vbest = select_best_audited(
                    states, viols, lambda s: audit_fn(s, ctx))
            else:
                state, best_idx, vbest = select_best(states, viols)
            walk_span.set(winner=int(best_idx))
        walk_s = time.monotonic() - t_walk
        _has_broken, scales_arr, v0 = jax.device_get(aux)
        self.collector.record_d2h(self.collector.tree_bytes(
            (_has_broken, scales_arr, v0)))
        v0 = np.asarray(v0)
        logger = logging.getLogger(__name__)
        logger.info("branched search: %d branches, winner %d, %.2fs",
                    self.branches, best_idx, walk_s)
        goal_results: list[GoalResult] = []
        per = walk_s / max(len(goals), 1)
        # No per-goal self-check here: the sequential walk's "never worsen
        # your own violation" assertion reads the stack at each goal's OWN
        # pass boundary, which a single shard_map program cannot expose —
        # comparing the initial stack against the post-CHAIN stack would
        # false-positive on legal later-goal drift (the <= epsilon
        # regressions acceptance tolerates, the very drift polish exists
        # for). Each branch still enforces per-pass non-worsening
        # internally through lexicographic acceptance, and the winning
        # boundary feeds the same hard-goal gate below.
        for i, goal in enumerate(goals):
            goal_results.append(GoalResult(
                name=goal.name, hard=goal.hard,
                violation_before=float(v0[i]),
                violation_after=float(vbest[i]), duration_s=per,
                iterations=0, scale=float(scales_arr[i])))
        return self._finish(model, metadata, options, state, goal_results,
                            t0, ctx, audit, audit_fn, audit_before)

    def _finish(self, model, metadata, options, state, goal_results, t0,
                ctx=None, audit=(), audit_fn=None, audit_before=None,
                trajectory=None, extra_telemetry=None):
        with self.tracer.span("optimizer.finish") as fin:
            audit_results: list[GoalResult] = []
            if audit_fn is not None:
                t_a = time.monotonic()
                (v_after, scales), (v_before, _) = jax.device_get(
                    (audit_fn(state, ctx), audit_before))
                self.collector.record_d2h(self.collector.tree_bytes(
                    ((v_after, scales), (v_before, None))))
                audit_s = (time.monotonic() - t_a) / max(len(audit), 1)
                audit_results = [
                    GoalResult(name=g.name, hard=True,
                               violation_before=float(v_before[i]),
                               violation_after=float(v_after[i]),
                               duration_s=audit_s, iterations=0,
                               scale=float(scales[i]))
                    for i, g in enumerate(audit)]
            final = to_model(state, model)
            proposals = diff_proposals(model, final, metadata)
            num_moves = int(jax.device_get(state.moves_applied))
            fin.set(proposals=len(proposals), moves=num_moves)
        duration_s = time.monotonic() - t0
        # ref GoalOptimizer.java:183 _proposalComputationTimer.update.
        self._proposal_timer.update(duration_s)
        telemetry = self._record_goal_telemetry(goal_results, trajectory,
                                                num_moves)
        if extra_telemetry and telemetry is not None:
            # Path-specific sections (the population search's joint-
            # scoring snapshot) merge into the observable payload — all
            # values came off the device with the same end-of-chain
            # fetch.
            telemetry.update(extra_telemetry)
        result = OptimizerResult(
            proposals=proposals, goal_results=goal_results,
            num_moves=num_moves,
            duration_s=duration_s, final_model=final,
            provision_response=self._provision_verdict(final, goal_results),
            hard_goal_audit=audit_results,
            telemetry=telemetry)
        if result.violated_hard_goals and not options.skip_hard_goal_check:
            in_chain = {g.name for g in goal_results
                        if g.hard and not g.satisfied}
            audited = [n for n in result.violated_hard_goals
                       if n not in in_chain]
            detail = (f" (off-chain, caught by the registered-hard-goal "
                      f"audit: {audited})" if audited else "")
            raise OptimizationFailureError(
                f"hard goals still violated after optimization: "
                f"{result.violated_hard_goals}{detail}", result)
        return result

    def _record_goal_telemetry(self, goal_results, trajectory,
                               num_moves) -> dict | None:
        """Surface the device-side search telemetry: per-goal Prometheus
        series on the optimizer registry (a summary for durations, plain
        counters for iteration/acceptance totals) and the structured
        ``OptimizerResult.telemetry`` payload. Every number here came off
        the device with the chain walk's existing end-of-chain fetch —
        this method touches no device arrays.

        ``trajectory is None`` marks a path whose goal boundaries are
        structurally unobservable (the branched shard_map walk): the
        duration summaries still update (wall-clock attribution is real),
        but no telemetry payload is returned and the zero-valued
        iteration/acceptance counters are left untouched — a dict full of
        zeros would silently break the ``sum(accepted) == totalMoves``
        invariant consumers rely on."""
        from ..core.sensors import GOAL_OPTIMIZER_SENSOR, MetricRegistry
        observable = trajectory is not None
        for g in goal_results:
            base = MetricRegistry.name(GOAL_OPTIMIZER_SENSOR,
                                       f"goal-{g.name}")
            self.registry.timer(
                f"{base}-optimization-timer").update(g.duration_s)
            if observable:
                self.registry.counter(
                    f"{base}-iterations").inc(g.iterations)
                self.registry.counter(
                    f"{base}-accepted-moves").inc(g.accepted)
        if not observable:
            return None
        return {
            "perGoal": [{"goal": g.name,
                         "iterations": g.iterations,
                         "accepted": g.accepted,
                         "violationBefore": g.violation_before,
                         "violationAfter": g.violation_after,
                         "durationMs": round(g.duration_s * 1e3, 3)}
                        for g in goal_results],
            # Row 0 = initial stack, row i+1 = stack after pass i (polish
            # rounds append further rows); column g tracks goal g's score
            # across the whole walk.
            "violationTrajectory": [[round(x, 6) for x in row]
                                    for row in trajectory],
            "totalMoves": num_moves,
        }

    def _provision_verdict(self, final: FlatClusterModel,
                           goal_results: list[GoalResult]):
        """Under/over-provisioning verdict (ref CapacityGoal /
        ResourceDistributionGoal attaching ProvisionRecommendation to the
        result; BasicProvisioner acts on it).

        UNDER: a hard capacity goal is still violated — no placement fits
        the load; recommend the broker count that would. OVER: every
        resource's cluster-wide utilization sits below its (opt-in)
        low-utilization threshold; recommend shrinking to the smallest
        broker count that keeps utilization under the usable ceiling.
        """
        from ..model.flat import broker_utilization
        util = np.asarray(jax.device_get(broker_utilization(final)))
        alive = np.asarray(jax.device_get(final.broker_alive
                                          & final.broker_valid))
        caps = np.asarray(jax.device_get(final.broker_capacity))

        def placement():
            # Lazy: only the shrink branch reads the placement, and the
            # [P, R] fetch is real money at the 10Kx1M tier.
            return (np.asarray(jax.device_get(final.replica_broker)),
                    np.asarray(jax.device_get(final.broker_rack)))
        return self._provision_verdict_from_host(
            util, alive, caps, final.num_brokers_padded, goal_results,
            placement=placement)

    def _provision_verdict_from_host(self, util, alive, caps, B,
                                     goal_results, *, placement):
        """Host half of :meth:`_provision_verdict`, on already-fetched
        arrays — the fleet layer computes every member's utilization in
        one batched program and one stacked fetch, then runs this per
        member with zero further device reads. ``placement`` is a lazy
        ``() -> (replica_broker, broker_rack)`` (only the shrink branch
        needs it)."""
        from ..detector.provisioner import (ProvisionRecommendation,
                                            ProvisionResponse,
                                            ProvisionStatus)
        from ..core.resources import RESOURCE_NAMES, Resource
        cst = self.constraint
        response = ProvisionResponse()

        def _headroom(total: float, usable_total: float) -> dict:
            """The numbers that motivated a verdict, attached to its
            recommendation (ProvisionRecommendation.headroom)."""
            return {"demand": round(total, 3),
                    "usableCapacity": round(usable_total, 3),
                    "headroomPct": round(
                        100.0 * (1.0 - total / max(usable_total, 1e-9)),
                        2)}
        n_alive = max(int(alive.sum()), 1)
        violated_capacity = {g.name for g in goal_results
                             if g.hard and not g.satisfied
                             and "CapacityGoal" in g.name}
        # Broker count needed per resource; shrink verdicts must respect the
        # max over ALL resources (removing brokers a low-CPU cluster doesn't
        # need could overload its disks).
        needed_by_resource: dict[Resource, int] = {}
        for r in Resource:
            name = RESOURCE_NAMES[int(r)]
            total = float(util[:, int(r)].sum())
            usable_per_broker = float(
                np.where(alive, caps[:, int(r)], 0.0).sum()
            ) / n_alive * cst.cap_threshold(r)
            if usable_per_broker <= 0:
                continue
            needed_by_resource[r] = int(np.ceil(total / usable_per_broker))
            goal_name = {Resource.CPU: "CpuCapacityGoal",
                         Resource.NW_IN: "NetworkInboundCapacityGoal",
                         Resource.NW_OUT: "NetworkOutboundCapacityGoal",
                         Resource.DISK: "DiskCapacityGoal"}[r]
            if goal_name in violated_capacity:
                response.aggregate(ProvisionRecommendation(
                    ProvisionStatus.UNDER_PROVISIONED,
                    num_brokers=max(needed_by_resource[r] - n_alive, 1),
                    resource=name,
                    reason=f"{name} demand {total:.0f} exceeds usable "
                           f"capacity of {n_alive} brokers",
                    headroom=_headroom(total, usable_per_broker * n_alive)))
        if response.status is not ProvisionStatus.UNDER_PROVISIONED:
            # Shrink floors beyond resource demand (ref ProvisionerUtils):
            # replica density must stay under
            # overprovisioned.max.replicas.per.broker, and the cluster
            # must SPAN at least max-RF + overprovisioned.min.extra.racks
            # racks (rack-aware placement headroom) — a rack count, not a
            # broker count: when the alive brokers don't cover that many
            # racks, no shrink is recommended at all.
            rb, racks = placement()
            valid_rb = rb < B
            total_replicas = int(valid_rb.sum())
            max_rf = int(valid_rb.sum(axis=1).max()) if rb.size else 0
            num_alive_racks = len(set(racks[alive].tolist()))
            if num_alive_racks < max_rf + cst.overprovisioned_min_extra_racks:
                if not response.recommendations:
                    response.status = ProvisionStatus.RIGHT_SIZED
                return response
            min_needed = max(
                *needed_by_resource.values(),
                cst.overprovisioned_min_brokers,
                int(np.ceil(total_replicas
                            / cst.overprovisioned_max_replicas_per_broker)))
            for r, low in zip(Resource, cst.low_utilization_threshold):
                if low <= 0 or r not in needed_by_resource:
                    continue
                total = float(util[:, int(r)].sum())
                usable_per_broker = float(
                    np.where(alive, caps[:, int(r)], 0.0).sum()
                ) / n_alive * cst.cap_threshold(r)
                if (total < low * usable_per_broker * n_alive
                        and min_needed < n_alive):
                    response.aggregate(ProvisionRecommendation(
                        ProvisionStatus.OVER_PROVISIONED,
                        num_brokers=n_alive - min_needed,
                        resource=RESOURCE_NAMES[int(r)],
                        reason=f"{RESOURCE_NAMES[int(r)]} utilization below "
                               f"{low:.0%} of usable capacity (cluster still "
                               f"needs {min_needed} brokers for its most "
                               "demanding resource)",
                        headroom=_headroom(total,
                                           usable_per_broker * n_alive)))
        if not response.recommendations:
            response.status = ProvisionStatus.RIGHT_SIZED
        return response


def _as_jnp(mask):
    if mask is None:
        return None
    import jax.numpy as jnp
    return jnp.asarray(mask)
